"""Attention building blocks: rotary embeddings and multi-head attention.

Rotary utilities mirror the reference's ``modules/attention/utils.py``
(``precompute_freqs_cis:42``, llama3 frequency scaling ``apply_scaling:20``).
The attention core defaults to a pure-XLA softmax attention (which XLA fuses
well on TPU); the Pallas flash-attention kernel in :mod:`..ops.flash_attention`
is used automatically for longer sequences (reference:
``kernels/flash_attn.py:162``).
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel import layers as pl
from ..parallel import mesh as ps


def attention_dropout_seed(module: nn.Module, rate: float):
    """``(dropout_p, dropout_seed)`` gate shared by every model family:
    dropout is active iff ``rate > 0`` AND the module was given a
    ``"dropout"`` rng (no deterministic-flag threading). The uint32 seed
    feeds the counter-based mask hash
    (:func:`..ops.flash_attention.dropout_keep_mask`) — one draw per
    attention module, folded per layer by the scan rng split."""
    if rate > 0.0 and module.has_rng("dropout"):
        return rate, jax.random.bits(module.make_rng("dropout"), (),
                                     jnp.uint32)
    return 0.0, None


def apply_rope_scaling(freqs: jax.Array,
                       scale_factor: float = 8.0,
                       low_freq_factor: float = 1.0,
                       high_freq_factor: float = 4.0,
                       original_max_position: int = 8192) -> jax.Array:
    """Llama-3 style rope frequency scaling (reference
    ``modules/attention/utils.py:20``)."""
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    wavelen = 2 * math.pi / freqs
    scaled = jnp.where(wavelen > low_freq_wavelen, freqs / scale_factor, freqs)
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    mid = (1 - smooth) * freqs / scale_factor + smooth * freqs
    is_mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(is_mid, mid, scaled)


def precompute_rope(head_dim: int, max_len: int, theta: float = 10000.0,
                    use_scaled: bool = False,
                    dtype: Any = jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables ``[max_len, head_dim//2]`` (reference
    ``precompute_freqs_cis:42``)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    if use_scaled:
        inv_freq = apply_rope_scaling(inv_freq)
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
    """Apply rotary embedding. ``x: [B, S, N, D]``; cos/sin ``[L, D/2]``;
    ``positions: [B, S]`` (defaults to arange)."""
    b, s, n, d = x.shape
    if positions is None:
        cos_p = cos[:s][None, :, None, :]
        sin_p = sin[:s][None, :, None, :]
    else:
        cos_p = cos[positions][:, :, None, :]
        sin_p = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p,
                           x2 * cos_p + x1 * sin_p], axis=-1)
    return out.astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, K, D] -> [B, S, K*n_rep, D] (GQA head expansion)."""
    if n_rep == 1:
        return x
    b, s, k, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :],
                            (b, s, k, n_rep, d)).reshape(b, s, k * n_rep, d)


def sdpa_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True,
                   segment_positions: Optional[jax.Array] = None,
                   scale: Optional[float] = None,
                   dropout_p: float = 0.0,
                   dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """Plain softmax attention, fp32 accumulation. ``q: [B, S, N, D]``,
    ``k/v: [B, S, N, D]`` (already GQA-expanded). Attention dropout uses the
    same counter-based (seed, head, q, k) hash as the flash kernels
    (``ops.flash_attention.dropout_keep_mask``), so sdpa and flash produce
    bit-identical masks for the same seed."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        kpos = jnp.arange(sk)
        if segment_positions is None:
            mask = (jnp.arange(sq)[:, None] >= kpos[None, :])[None, None]
        else:
            # [B, S] query positions -> [B, 1, Q, K]
            mask = (segment_positions[:, :, None] >= kpos[None, None, :]
                    )[:, None]
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0:
        from ..ops.flash_attention import dropout_keep_mask, flat_bh

        bh = flat_bh(b, n)
        keep = dropout_keep_mask(
            jnp.asarray(dropout_seed, jnp.uint32), bh,
            jnp.arange(sq)[None, None, :, None],
            jnp.arange(sk)[None, None, None, :], sk, dropout_p)
        probs = jnp.where(keep, probs * (1.0 / (1.0 - dropout_p)), 0.0)
    out = jnp.einsum("bnqk,bknd->bqnd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
