"""Sequence-parallel-aware normalisation layers.

Analogue of the reference's ``parallel_layers/layer_norm.py:17`` and
``modules/rms_norm.py:36``. In the explicit shard_map path, when activations
are sequence-sharded across tp, the (replicated) norm weights receive a
different gradient on each tp shard; the reference marks such weights
``sequence_parallel_enabled`` and all-reduces their grads later
(``grads.py:330``). Here the same effect is local and composable: the weight
passes through ``copy_to_tensor_parallel_region`` (identity fwd, psum bwd),
so the summed gradient appears directly in autodiff — no deferred pass.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel import mappings
from ..parallel import mesh as ps


class RMSNorm(nn.Module):
    """RMSNorm in fp32 accumulation (llama-style)."""

    eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    axis: str = ps.TP_AXIS

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.with_partitioning(
            nn.initializers.ones_init(), (None,)), (x.shape[-1],),
            self.param_dtype)
        if self.sequence_parallel:
            scale = mappings.copy_to_tensor_parallel_region(scale, self.axis)
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + self.eps)
        return (y * scale.astype(jnp.float32)).astype(self.dtype)


class LayerNorm(nn.Module):
    """LayerNorm with optional SP-aware weight grads (reference
    ``layer_norm.py:17``)."""

    eps: float = 1e-5
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    axis: str = ps.TP_AXIS

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = x.shape[-1]
        scale = self.param("scale", nn.with_partitioning(
            nn.initializers.ones_init(), (None,)), (h,), self.param_dtype)
        bias = None
        if self.use_bias:
            bias = self.param("bias", nn.with_partitioning(
                nn.initializers.zeros_init(), (None,)), (h,), self.param_dtype)
        if self.sequence_parallel:
            scale = mappings.copy_to_tensor_parallel_region(scale, self.axis)
            if bias is not None:
                bias = mappings.copy_to_tensor_parallel_region(bias, self.axis)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * scale.astype(jnp.float32)
        if bias is not None:
            y = y + bias.astype(jnp.float32)
        return y.astype(self.dtype)
