"""ctypes bindings for the native C++ token-batch loader (csrc/data_loader.cpp).

The loader mmaps a binary token stream and prefetches shuffled
``[batch, seq+1]`` int32 batches on background C++ threads (bounded ring
buffer) — the training loop's IO runs off the Python GIL entirely. The
shared object is built with g++ on first use and cached next to the source;
environments without a toolchain fall back to a numpy implementation with
identical semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Iterator, Optional

import numpy as np

_BUILD_LOCK = threading.Lock()
_LIB = None
_LIB_FAILED = False


class DataLoaderStallError(RuntimeError):
    """``next_batch`` produced nothing within ``stall_timeout_s`` — the
    producer threads are wedged (dead filesystem, mmap fault) rather than
    slow. The resilience watchdog treats this as a stall, not a crash."""


def _csrc_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "csrc",
        "data_loader.cpp")


def _load_native():
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        src = _csrc_path()
        so = os.path.join(os.path.dirname(src), "libnxd_data_loader.so")
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", src, "-o", so],
                    check=True, capture_output=True)
            lib = ctypes.CDLL(so)
            lib.nxd_loader_create.restype = ctypes.c_void_p
            lib.nxd_loader_create.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_long, ctypes.c_long,
                ctypes.c_long, ctypes.c_int, ctypes.c_int]
            lib.nxd_loader_num_sequences.restype = ctypes.c_long
            lib.nxd_loader_num_sequences.argtypes = [ctypes.c_void_p]
            lib.nxd_loader_next.restype = ctypes.c_int
            lib.nxd_loader_next.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(ctypes.c_int32)]
            lib.nxd_loader_destroy.argtypes = [ctypes.c_void_p]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
            _LIB = None
    return _LIB


class TokenBatchLoader:
    """Iterator of ``{"input_ids": [B,S], "labels": [B,S]}`` int32 batches
    from a flat binary token file (uint16 or uint32)."""

    def __init__(self, path: str, batch: int, seqlen: int, seed: int = 0,
                 dtype: str = "uint16", nthreads: int = 2,
                 capacity: int = 8, force_python: bool = False,
                 stall_timeout_s: Optional[float] = None):
        self.path = path
        self.batch = batch
        self.seqlen = seqlen
        self.seed = seed
        # wall-clock budget per next_batch (None = block forever); the
        # blocking produce runs on a helper thread so a wedged native ring
        # buffer surfaces as DataLoaderStallError instead of a silent hang
        self.stall_timeout_s = stall_timeout_s
        # heartbeat for external stall detection (resilience.Watchdog)
        self.last_batch_at = time.monotonic()
        self.dtype = np.dtype(dtype)
        if self.dtype.itemsize not in (2, 4):
            raise ValueError("token dtype must be uint16 or uint32")
        if nthreads < 1:
            raise ValueError("nthreads must be >= 1 (zero producer threads "
                             "would deadlock next_batch)")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._handle = None
        self._lib = None if force_python else _load_native()
        if self._lib is not None:
            self._handle = self._lib.nxd_loader_create(
                path.encode(), self.dtype.itemsize, batch, seqlen, seed,
                nthreads, capacity)
            if not self._handle:
                raise ValueError(
                    f"native loader rejected {path!r} (missing, or fewer "
                    f"than {batch} sequences of length {seqlen + 1})")
            self.num_sequences = int(
                self._lib.nxd_loader_num_sequences(self._handle))
            self.native = True
        else:
            self._tokens = np.memmap(path, dtype=self.dtype, mode="r")
            self.num_sequences = len(self._tokens) // (seqlen + 1)
            if self.num_sequences < batch:
                raise ValueError(
                    f"{path!r} has fewer than {batch} sequences of length "
                    f"{seqlen + 1}")
            self._rng = np.random.RandomState(seed)
            self.native = False

    def _produce(self) -> np.ndarray:
        n = self.batch * (self.seqlen + 1)
        if self._handle is not None:
            out = np.empty((n,), np.int32)
            rc = self._lib.nxd_loader_next(
                self._handle, out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int32)))
            if rc != 0:
                raise RuntimeError("native loader stopped")
            return out.reshape(self.batch, self.seqlen + 1)
        idx = self._rng.randint(0, self.num_sequences, self.batch)
        per = self.seqlen + 1
        return np.stack([
            np.asarray(self._tokens[i * per:(i + 1) * per],
                       dtype=np.int32) for i in idx])

    def next_batch(self) -> dict:
        if self.stall_timeout_s is None:
            ids = self._produce()
        else:
            box = {}

            def run():
                try:
                    box["ids"] = self._produce()
                except BaseException as e:  # re-raised on the caller
                    box["err"] = e

            # daemon: a wedged producer blocked in C must not pin the
            # interpreter open after the caller gave up on it
            t = threading.Thread(target=run, daemon=True,
                                 name="nxd-loader-next")
            t.start()
            t.join(timeout=self.stall_timeout_s)
            if t.is_alive():
                raise DataLoaderStallError(
                    f"data loader produced no batch within "
                    f"{self.stall_timeout_s:.1f}s (native={self.native})")
            if "err" in box:
                raise box["err"]
            ids = box["ids"]
        self.last_batch_at = time.monotonic()
        return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.next_batch()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.nxd_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
