"""Data loading (native C++ prefetch loader + pure-python fallback)."""

from .native_loader import TokenBatchLoader

__all__ = ["TokenBatchLoader"]
