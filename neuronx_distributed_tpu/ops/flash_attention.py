"""Memory-efficient (flash) attention.

Analogue of the reference's NKI flash attention wrapper
(``kernels/flash_attn.py:162`` → ``nki.kernels.attention.flash_fwd/bwd``).

Current implementation: blockwise online-softmax attention expressed with
``lax.scan`` over KV blocks — O(S) memory instead of O(S²), fp32 accumulation,
differentiable through JAX autodiff (the scan's VJP recomputes per-block,
which is exactly the flash-backward memory profile). XLA fuses each block's
QK^T → rescale → PV chain onto the MXU.

A hand-tiled Pallas (Mosaic) kernel can be slotted in behind the same
signature; this scan formulation is the golden reference for it (the
reference keeps torch fallbacks for its NKI kernels the same way).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attention(q, k_blk, v_blk, q_pos, k_pos_start, block_k, causal,
                     scale):
    """Scores and partial PV for one KV block. q: [B,N,Sq,D],
    k_blk/v_blk: [B,N,Bk,D]."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        k_pos = k_pos_start + jnp.arange(block_k)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return s


@functools.partial(jax.jit, static_argnames=("causal", "block_k"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_k: int = 512,
                    scale: Optional[float] = None) -> jax.Array:
    """Blockwise attention. ``q/k/v: [B, S, N, D]`` (kv already GQA-expanded);
    returns ``[B, S, N, D]``."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    if sk % block_k != 0:
        # fall back to one block covering everything (static shapes only)
        block_k = sk
    nblocks = sk // block_k
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,N,Sq,D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    q_pos = jnp.arange(sq)

    kb = kt.reshape(b, n, nblocks, block_k, d)
    vb = vt.reshape(b, n, nblocks, block_k, d)

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        k_blk, v_blk, idx = blk
        s = _block_attention(qt, k_blk, v_blk, q_pos, idx * block_k, block_k,
                             causal, scale)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (m_new = -inf) against NaNs
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.where(jnp.isfinite(m_prev),
                               jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bnqk,bnkd->bnqd", p, v_blk, preferred_element_type=jnp.float32)
        return (m_new, l_new, acc), None

    m0 = jnp.full((b, n, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n, sq), jnp.float32)
    acc0 = jnp.zeros((b, n, sq, d), jnp.float32)
    blks = (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0),
            jnp.arange(nblocks))
    (m, l, acc), _ = lax.scan(body, (m0, l0, acc0), blks)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
