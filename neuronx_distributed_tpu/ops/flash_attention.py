"""Memory-efficient (flash) attention.

Analogue of the reference's NKI flash attention wrapper
(``kernels/flash_attn.py:162`` → ``nki.kernels.attention.flash_fwd/bwd``).

Current implementation: blockwise online-softmax attention expressed with
``lax.scan`` over KV blocks — O(S) memory instead of O(S²), fp32 accumulation,
differentiable through JAX autodiff (the scan's VJP recomputes per-block,
which is exactly the flash-backward memory profile). XLA fuses each block's
QK^T → rescale → PV chain onto the MXU.

A hand-tiled Pallas (Mosaic) kernel can be slotted in behind the same
signature; this scan formulation is the golden reference for it (the
reference keeps torch fallbacks for its NKI kernels the same way).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from .pallas_utils import compiler_params as _compiler_params


# ---------------------------------------------------------------------------
# Attention dropout: counter-based keep masks. The reference threads a seed
# into its NKI kernels the same way (``kernels/flash_attn.py:30,54`` passes
# seed + dropout_p into flash_fwd/flash_attn_bwd). Here the mask for element
# (head, q, k) is a pure integer hash of (seed, head, q, k) — a murmur3-style
# finalizer in plain uint32 ops — so the SAME mask regenerates anywhere it is
# needed: the Pallas forward kernel, both Pallas backward kernels, the XLA
# fallback scan, and ``sdpa_reference``. No PRNG state to carry, no [S, S]
# mask to materialise, and (unlike ``pltpu.prng_random_bits``) it works in
# interpret mode on CPU, so CI exercises the exact TPU mask path.
# ---------------------------------------------------------------------------

def dropout_keep_mask(seed, head_idx, q_pos, k_pos, sk: int, p: float):
    """Boolean keep-mask from integer coordinate arrays (broadcastable).

    ``seed``: uint32 scalar. ``head_idx``: flat batch*head index. The
    per-element counter is ``q*sk + k`` (unique while sq*sk < 2**32, i.e.
    sequences to 64K) xored with a per-(seed, head) hash, then mixed with
    the murmur3 finalizer. Keep probability is ``1 - p``.
    """
    hseed = (seed.astype(jnp.uint32)
             + head_idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    hseed = (hseed ^ (hseed >> 16)) * jnp.uint32(0x21F0AAAD)
    x = (q_pos.astype(jnp.uint32) * jnp.uint32(sk)
         + k_pos.astype(jnp.uint32)) ^ hseed
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x >= jnp.uint32(round(p * 0xFFFFFFFF))


def flat_bh(b: int, n: int) -> jax.Array:
    """``[B, N, 1, 1]`` flat batch*head coordinate for dropout masks.

    Every mask site (sdpa_reference, the XLA flash scan, ring attention)
    must use this exact batch-major layout — cross-implementation mask
    parity (and ring's bit-consistency with the dense model) depends on
    all of them agreeing.
    """
    return (jnp.arange(b)[:, None] * n
            + jnp.arange(n)[None, :])[..., None, None]


def _block_attention(q, k_blk, v_blk, q_pos, k_pos_start, block_k, causal,
                     scale):
    """Scores and partial PV for one KV block. q: [B,N,Sq,D],
    k_blk/v_blk: [B,N,Bk,D]."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        k_pos = k_pos_start + jnp.arange(block_k)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return s


def _flash_xla_impl(q, k, v, causal, block_k, scale, dropout_p,
                    dropout_seed):
    """Blockwise-scan forward; returns ``(out [B,S,N,D], lse [B,N,S])``."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    if sk % block_k != 0:
        # fall back to one block covering everything (static shapes only)
        block_k = sk
    nblocks = sk // block_k

    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,N,Sq,D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    q_pos = jnp.arange(sq)
    if dropout_p > 0.0:
        bh = flat_bh(b, n)
        seed = jnp.asarray(dropout_seed, jnp.uint32)

    kb = kt.reshape(b, n, nblocks, block_k, d)
    vb = vt.reshape(b, n, nblocks, block_k, d)

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        k_blk, v_blk, idx = blk
        s = _block_attention(qt, k_blk, v_blk, q_pos, idx * block_k, block_k,
                             causal, scale)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        # guard fully-masked rows (m_new = -inf) against NaNs
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.where(jnp.isfinite(m_prev),
                               jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        if dropout_p > 0.0:
            keep = dropout_keep_mask(
                seed, bh, q_pos[None, None, :, None],
                (idx * block_k + jnp.arange(block_k))[None, None, None, :],
                sk, dropout_p)
            p_acc = jnp.where(keep, p, 0.0)
        else:
            p_acc = p
        acc = acc * correction[..., None] + jnp.einsum(
            "bnqk,bnkd->bnqd", p_acc, v_blk,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, acc), None

    m0 = jnp.full((b, n, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n, sq), jnp.float32)
    acc0 = jnp.zeros((b, n, sq, d), jnp.float32)
    blks = (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0),
            jnp.arange(nblocks))
    (m, l, acc), _ = lax.scan(body, (m0, l0, acc0), blks)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    if dropout_p > 0.0:
        out = out * (1.0 / (1.0 - dropout_p))
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), -jnp.inf)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_xla(q, k, v, seed, causal, block_k, scale, dropout_p):
    out, _ = _flash_xla_impl(q, k, v, causal, block_k, scale, dropout_p,
                             seed[0])
    return out


def _flash_xla_vjp_fwd(q, k, v, seed, causal, block_k, scale, dropout_p):
    out, lse = _flash_xla_impl(q, k, v, causal, block_k, scale, dropout_p,
                               seed[0])
    # same named residuals as the Pallas path, so remat_policy=
    # "save_attention" is NOT a silent no-op when shapes demote the dispatch
    # to the XLA fallback (review finding r5): the saved out+lse feed
    # _flash_bwd_from_lse directly.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, seed, out, lse)


def _flash_xla_vjp_bwd(causal, block_k, scale, dropout_p, res, g):
    import numpy as np

    q, k, v, seed, out, lse = res
    dq, dk, dv = _flash_bwd_from_lse(q, k, v, out, lse, g, causal, block_k,
                                     scale, dropout_p, seed[0])
    return dq, dk, dv, np.zeros(seed.shape, jax.dtypes.float0)


_flash_xla.defvjp(_flash_xla_vjp_fwd, _flash_xla_vjp_bwd)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_k", "scale",
                                    "dropout_p"))
def flash_attention_xla(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True, block_k: int = 512,
                        scale: Optional[float] = None,
                        dropout_p: float = 0.0,
                        dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """Blockwise attention. ``q/k/v: [B, S, N, D]`` (kv already GQA-expanded);
    returns ``[B, S, N, D]``. ``dropout_p``: attention-probability dropout
    (the softmax normaliser sums UNdropped probabilities; dropped entries are
    zeroed and survivors rescaled by 1/(1-p), standard semantics; the
    counter-based mask regenerates identically in the backward)."""
    d = q.shape[-1]
    scale_ = scale if scale is not None else 1.0 / math.sqrt(d)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    seed = (jnp.asarray(dropout_seed, jnp.uint32).reshape((1,))
            if dropout_p > 0.0 else jnp.zeros((1,), jnp.uint32))
    # clamp HERE (not just in the impl) so the custom_vjp backward sees the
    # same static block_k the forward actually used — _flash_bwd_from_lse
    # reshapes k/v by it (review finding r5: sk % block_k != 0 would crash
    # the backward with a size-mismatched reshape)
    sk = k.shape[1]
    block_k = min(block_k, sk)
    if sk % block_k != 0:
        block_k = sk
    return _flash_xla(q, k, v, seed, causal, block_k, scale_, dropout_p)


# ---------------------------------------------------------------------------
# Pallas (Mosaic) TPU kernel — the hand-tiled fast path. Grid is
# (batch*heads, q_blocks, k_blocks) with the KV dim innermost (sequential on
# TPU): K/V stream through VMEM one (block_k, d) tile at a time while
# m/l/acc accumulate in VMEM scratch — constant VMEM regardless of sequence
# length. Forward only; the backward is the VJP of the scan formulation
# above (same recompute profile as a flash backward, one golden
# implementation to maintain).
# ---------------------------------------------------------------------------

def _tile_keep_mask(seed_ref, head, qi, kb, block_q, block_k, sk, dropout_p):
    """Regenerate the (block_q, block_k) keep mask for one tile — identical
    in the forward and both backward kernels (coords are global)."""
    shape = (block_q, block_k)
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, shape, 1)
    return dropout_keep_mask(seed_ref[0], head, q_pos, k_pos, sk, dropout_p)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref, m_ref,
                      l_ref, acc_ref, *, block_q: int, block_k: int,
                      num_kb: int, causal: bool, scale: float,
                      dropout_p: float, sk: int):
    from jax.experimental import pallas as pl

    head = pl.program_id(0)  # hoisted: program_id has no lowering inside
    qi = pl.program_id(1)    # pl.when bodies in interpret mode
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip blocks strictly above the causal diagonal
    @pl.when((not causal) or (kb * block_k <= qi * block_q + block_q - 1))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)           # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1)
        if dropout_p > 0.0:
            # normaliser l accumulates UNdropped p; only the PV accumulation
            # sees the mask (survivor rescale happens once, in _finalize)
            keep = _tile_keep_mask(seed_ref, head, qi, kb,
                                   block_q, block_k, sk, dropout_p)
            p_acc = jnp.where(keep, p, 0.0)
        else:
            p_acc = p
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p_acc, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        inv_keep = 1.0 / (1.0 - dropout_p) if dropout_p > 0.0 else 1.0
        o_ref[0] = (acc_ref[:] * inv_keep
                    / jnp.maximum(l_ref[:], 1e-30)[:, None]
                    ).astype(o_ref.dtype)
        # log-sum-exp per query row (softmax stats for the flash backward).
        # lse block is (1, 1, block_q): 3D so the sublane dim (=1) equals the
        # array dim — Mosaic's (8, 128) tiling rule for 2D blocks would
        # reject a (1, block_q) block on a (b*n, sq) array.
        lse_ref[0, 0] = jnp.where(
            l_ref[:] > 0, m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30)),
            -jnp.inf)


def _causal_kv_index(causal, block_q, block_k):
    """BlockSpec index_map for KV tiles in a (i, q_block, k_block) grid.

    Causal truncation: skipped (above-diagonal) iterations clamp the KV
    block index to the q-block's diagonal — Mosaic elides the DMA when
    consecutive iterations map to the same block, so masked blocks cost
    neither compute (``pl.when``) nor HBM traffic."""
    if not causal:
        return lambda i, j, kb: (i, kb, 0)
    return lambda i, j, kb: (
        i, jnp.minimum(kb, (j * block_q + block_q - 1) // block_k), 0)


def _flash_pallas_fwd(q, k, v, seed, causal, block_q, block_k, scale,
                      interpret=False, dropout_p=0.0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, n, d = q.shape
    sk = k.shape[1]
    qt = jnp.swapaxes(q, 1, 2).reshape(b * n, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * n, sk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * n, sk, d)
    num_kb = sk // block_k
    grid = (b * n, sq // block_q, num_kb)
    kv_index = _causal_kv_index(causal, block_q, block_k)

    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_q=block_q,
                          block_k=block_k, num_kb=num_kb, causal=causal,
                          scale=scale, dropout_p=dropout_p, sk=sk),
        out_shape=[jax.ShapeDtypeStruct((b * n, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b * n, 1, sq), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
                   pl.BlockSpec((1, 1, block_q),
                                lambda i, j, kb: (i, 0, j))],
        scratch_shapes=[pltpu.VMEM((block_q,), jnp.float32),
                        pltpu.VMEM((block_q,), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(qt, kt, vt, seed)
    return (jnp.swapaxes(out.reshape(b, n, sq, d), 1, 2),
            lse.reshape(b, n, sq))


def _flash_bwd_from_lse(q, k, v, out, lse, g, causal, block_k, scale,
                        dropout_p=0.0, dropout_seed=None):
    """Standard flash backward from saved softmax stats: one blockwise pass
    recomputing p = exp(s - lse) per KV block (no second forward's
    max/sum accumulation). All in fp32; O(S) memory. With ``dropout_p`` the
    forward's counter-based keep mask regenerates per block (same math as
    ``_flash_bwd_dkv_kernel``)."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    nb = sk // block_k
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)      # [B,N,Sq,D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    ot = jnp.swapaxes(out, 1, 2).astype(jnp.float32)
    gt = jnp.swapaxes(g, 1, 2).astype(jnp.float32)
    delta = jnp.sum(gt * ot, axis=-1)                   # [B,N,Sq]
    q_pos = jnp.arange(sq)
    if dropout_p > 0.0:
        bh_idx = flat_bh(b, n)
        seed_u32 = jnp.asarray(dropout_seed, jnp.uint32)
        inv_keep = 1.0 / (1.0 - dropout_p)

    kb_ = kt.reshape(b, n, nb, block_k, d)
    vb_ = vt.reshape(b, n, nb, block_k, d)

    def body(dq, blk):
        k_blk, v_blk, idx = blk
        s = jnp.einsum("bnqd,bnkd->bnqk", qt, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = idx * block_k + jnp.arange(block_k)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            s = jnp.where(mask, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s),
                      jnp.exp(s - lse[..., None]), 0.0)  # [B,N,Sq,BK]
        if dropout_p > 0.0:
            keep = dropout_keep_mask(
                seed_u32, bh_idx, q_pos[None, None, :, None],
                (idx * block_k + jnp.arange(block_k))[None, None, None, :],
                sk, dropout_p)
            p_v = jnp.where(keep, p * inv_keep, 0.0)
        else:
            p_v = p
        dv = jnp.einsum("bnqk,bnqd->bnkd", p_v, gt,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum("bnqd,bnkd->bnqk", gt, v_blk,
                        preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bnqk,bnkd->bnqd", ds, k_blk,
                             preferred_element_type=jnp.float32)
        dk = jnp.einsum("bnqk,bnqd->bnkd", ds, qt,
                        preferred_element_type=jnp.float32)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qt)
    dq, (dks, dvs) = lax.scan(
        body, dq0, (jnp.moveaxis(kb_, 2, 0), jnp.moveaxis(vb_, 2, 0),
                    jnp.arange(nb)))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, n, sk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, n, sk, d)
    return (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


# ---------------------------------------------------------------------------
# Pallas flash backward (reference binds NKI flash_attn_bwd the same way,
# kernels/flash_attn.py:18). Two kernels, the standard split:
#   dq:    grid (b*n, q_blocks, k_blocks) — dq accumulates over KV blocks;
#   dk/dv: grid (b*n, k_blocks, q_blocks) — dk/dv accumulate over Q blocks.
# Both recompute p = exp(s - lse) from the saved log-sum-exp; delta =
# sum(g * out) per row is precomputed in XLA (cheap elementwise reduce).
# The XLA scan formulation above (_flash_bwd_from_lse) stays as the golden
# fallback.
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         seed_ref, dq_ref, dq_acc, *, block_q: int,
                         block_k: int, num_kb: int, causal: bool,
                         scale: float, dropout_p: float, sk: int):
    from jax.experimental import pallas as pl

    head = pl.program_id(0)
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when((not causal) or (kb * block_k <= qi * block_q + block_q - 1))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            # dP flows only through kept entries (the same regenerated mask
            # as the forward); the delta identity delta = rowsum(g*out) =
            # sum_j P_j dP_j still holds under dropout, so ds is unchanged
            keep = _tile_keep_mask(seed_ref, head, qi, kb,
                                   block_q, block_k, sk, dropout_p)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, g_ref, lse_ref, delta_ref,
                          seed_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                          block_q: int, block_k: int, num_qb: int,
                          causal: bool, scale: float, dropout_p: float,
                          sk: int):
    from jax.experimental import pallas as pl

    head = pl.program_id(0)
    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when((not causal) or (qi * block_q + block_q - 1 >= kb * block_k))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse[:, None]), 0.0)
        if dropout_p > 0.0:
            keep = _tile_keep_mask(seed_ref, head, qi, kb,
                                   block_q, block_k, sk, dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            # dV sees the dropped+rescaled probabilities (out = D(P) @ V)
            p_v = jnp.where(keep, p * inv, 0.0)
        else:
            p_v = p
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p_v, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_pallas_bwd(q, k, v, out, lse, g, seed, causal, block_q, block_k,
                      scale, interpret=False, dropout_p=0.0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, n, d = q.shape
    sk = k.shape[1]
    qt = jnp.swapaxes(q, 1, 2).reshape(b * n, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * n, sk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * n, sk, d)
    gt = jnp.swapaxes(g, 1, 2).reshape(b * n, sq, d)
    ot = jnp.swapaxes(out, 1, 2).reshape(b * n, sq, d)
    # stats carried 3D (b*n, 1, sq) so their (1, 1, block_q) blocks satisfy
    # Mosaic's sublane tiling rule (see _flash_pallas_fwd)
    lse_t = lse.reshape(b * n, 1, sq)
    delta = jnp.sum(gt.astype(jnp.float32) * ot.astype(jnp.float32), -1,
                    keepdims=True).reshape(b * n, 1, sq)
    num_qb, num_kb = sq // block_q, sk // block_k

    kv_index = _causal_kv_index(causal, block_q, block_k)
    if causal:
        # first q block at/below the diagonal for this KV block
        def q_index(i, kb, j):
            return (i, jnp.maximum(j, (kb * block_k) // block_q), 0)

        def qrow_index(i, kb, j):
            return (i, 0, jnp.maximum(j, (kb * block_k) // block_q))
    else:
        def q_index(i, kb, j):
            return (i, j, 0)

        def qrow_index(i, kb, j):
            return (i, 0, j)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, num_kb=num_kb, causal=causal,
                          scale=scale, dropout_p=dropout_p, sk=sk),
        out_shape=jax.ShapeDtypeStruct((b * n, sq, d), q.dtype),
        grid=(b * n, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(qt, kt, vt, gt, lse_t, delta, seed)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, num_qb=num_qb, causal=causal,
                          scale=scale, dropout_p=dropout_p, sk=sk),
        out_shape=[jax.ShapeDtypeStruct((b * n, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * n, sk, d), v.dtype)],
        grid=(b * n, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_q), qrow_index),
            pl.BlockSpec((1, 1, block_q), qrow_index),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(kt, vt, qt, gt, lse_t, delta, seed)

    return (jnp.swapaxes(dq.reshape(b, n, sq, d), 1, 2),
            jnp.swapaxes(dk.reshape(b, n, sk, d), 1, 2),
            jnp.swapaxes(dv.reshape(b, n, sk, d), 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_pallas(q, k, v, seed, causal, block_q, block_k, scale, interpret,
                  dropout_p):
    out, _ = _flash_pallas_fwd(q, k, v, seed, causal, block_q, block_k,
                               scale, interpret, dropout_p)
    return out


def _flash_pallas_vjp_fwd(q, k, v, seed, causal, block_q, block_k, scale,
                          interpret, dropout_p):
    out, lse = _flash_pallas_fwd(q, k, v, seed, causal, block_q, block_k,
                                 scale, interpret, dropout_p)
    # Residual names for rematerialisation policies: under
    # ``jax.checkpoint(policy=save_only_these_names('flash_out',
    # 'flash_lse'))`` (models expose this as ``remat_policy=
    # 'save_attention'``) the backward pass reuses the saved output +
    # softmax stats instead of re-running the forward kernel — the flash
    # backward only ever needed (q, k, v, out, lse), and q/k/v fall out of
    # the (cheap) projection recompute. This trades O(B·S·N·D) saved bytes
    # for skipping the full attention forward in the backward pass.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, seed, out, lse)


def _flash_pallas_vjp_bwd(causal, block_q, block_k, scale, interpret,
                          dropout_p, res, g):
    import numpy as np

    q, k, v, seed, out, lse = res
    dq, dk, dv = _flash_pallas_bwd(q, k, v, out, lse, g, seed, causal,
                                   block_q, block_k, scale, interpret,
                                   dropout_p)
    # seed is integer-typed: its cotangent is the unit float0 type
    dseed = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dseed


_flash_pallas.defvjp(_flash_pallas_vjp_fwd, _flash_pallas_vjp_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "scale", "force_pallas",
                                             "dropout_p"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 512,
                    block_k: int = 512,
                    scale: Optional[float] = None,
                    force_pallas: Optional[bool] = None,
                    dropout_p: float = 0.0,
                    dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """Flash attention entry point: Pallas kernel on TPU when the shapes
    tile cleanly, scan/XLA formulation otherwise (the reference dispatches
    NKI-vs-torch the same way, ``kernels/flash_attn.py``).

    ``dropout_p`` + ``dropout_seed`` (uint32 scalar, required when p > 0):
    in-kernel attention dropout via counter-based masks — the same
    (seed, head, q, k) hash regenerates the mask in the forward kernel,
    both backward kernels, and the XLA fallback, so the two dispatch paths
    are bit-identical per seed (reference seed plumbing:
    ``kernels/flash_attn.py:30,54``)."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    scale_ = scale if scale is not None else 1.0 / math.sqrt(d)
    # head_dim not 128-aligned (BERT/GPT-NeoX d=64): the Pallas path
    # zero-pads D up to the lane width — zero columns add nothing to QK^T
    # and the padded V columns only produce output columns we slice off,
    # so the kernel result is exact. Costs up to 2x kernel FLOPs/VMEM at
    # d=64, still ahead of demoting the whole model to the XLA scan
    # (VERDICT r4 missing #6; the reference's NKI flash serves its d=64
    # zoo with the same kernel, kernels/flash_attn.py:162). The tileable
    # decision below uses the PADDED width; the XLA fallback receives the
    # original arrays.
    d_kernel = -(-d // 128) * 128
    # clamp block sizes to the sequence before any divisibility decision,
    # then shrink (in 128-steps) to a size that divides the sequence — so a
    # seq divisible by 256 but not 512 still takes the Pallas path with
    # 256-blocks instead of silently demoting to the XLA fallback
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    while bq > 128 and bq % 128 == 0 and sq % bq != 0:
        bq -= 128
    while bk > 128 and bk % 128 == 0 and sk % bk != 0:
        bk -= 128
    # Mosaic tiling: d and (because the lse output's lane dim is block_q)
    # the block sizes must be 128-aligned for the compiled TPU path; the
    # force path accepts 8-aligned blocks (interpret mode / expert use)
    tileable_loose = (sq % bq == 0 and sk % bk == 0
                      and bq % 8 == 0 and bk % 8 == 0)
    tileable_strict = (tileable_loose and bq % 128 == 0 and bk % 128 == 0)
    if force_pallas:
        if not tileable_loose:
            raise ValueError(
                f"force_pallas: shapes (sq={sq}, sk={sk}) don't tile "
                f"with block_q={bq}, block_k={bk} (blocks must be "
                "8-aligned and divide the sequence)")
        use_pallas = True
    elif force_pallas is None:
        use_pallas = (jax.default_backend() in ("tpu", "axon")
                      and tileable_strict)
    else:
        use_pallas = False
    if dropout_p > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed (a uint32 "
                             "scalar; derive it from a PRNG key per step)")
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape((1,))
    else:
        seed = jnp.zeros((1,), jnp.uint32)
    if use_pallas:
        interpret = jax.default_backend() == "cpu"
        if not interpret and not tileable_strict:
            raise ValueError(
                f"force_pallas on TPU requires 128-aligned blocks "
                f"(got block_q={bq}, block_k={bk}); loose 8-aligned blocks "
                "are only valid in CPU interpret mode")
        if d != d_kernel:
            padw = ((0, 0), (0, 0), (0, 0), (0, d_kernel - d))
            out = _flash_pallas(jnp.pad(q, padw), jnp.pad(k, padw),
                                jnp.pad(v, padw), seed, causal, bq, bk,
                                scale_, interpret, dropout_p)
            return out[..., :d]
        return _flash_pallas(q, k, v, seed, causal, bq, bk, scale_,
                             interpret, dropout_p)
    return flash_attention_xla(q, k, v, causal=causal,
                               block_k=bk, scale=scale_,
                               dropout_p=dropout_p,
                               dropout_seed=seed[0] if dropout_p > 0.0
                               else None)
