"""Blockwise (dropless) MoE expert kernels: Pallas grouped GLU + reference.

The expert matmul of the dropless MoE path (reference NKI kernel family
``modules/moe/blockwise.py:856``): tokens arrive sorted by expert in
fixed-size blocks (``modules/moe/blockwise.py`` computes the metadata), and
each block runs ``silu(x@Wg_e)·(x@Wu_e) @ Wd_e`` with the weights of the
expert that owns it. TPU-native design, following the
:mod:`.paged_attention` pattern:

* the block→expert table is **scalar-prefetched** into SMEM
  (``pltpu.PrefetchScalarGridSpec``), so the weight BlockSpec index_maps
  read ``block_expert[b]`` and each block streams exactly its expert's
  weight tiles from HBM — consecutive blocks of one expert elide the
  re-fetch (one expert-weight DMA per block *run*, not per block);
* the intermediate dim is tiled (grid dim ``ib``) so weight tiles fit VMEM
  at 7B/70B sizes; the backward is the same pattern transposed — dx is a
  grouped matmul against the transposed weights, dW accumulates per expert
  by *output revisiting* (consecutive blocks of one expert map to the same
  output tile, which Mosaic keeps in VMEM and flushes once);
* a **pure-jnp reference** mirrors the kernel's arithmetic exactly — same
  per-``(b, ib)`` ``dot_general`` shapes, same fp32 accumulation order, same
  sentinel skips — so interpret-mode parity is *bitwise*, and the reference
  doubles as the silent CPU fallback (auto-dispatch below);
* **auto-dispatch**: ``force_pallas=None`` runs the Pallas kernel on
  TPU-like backends and the jnp reference elsewhere; ``True`` forces the
  kernel (interpret mode off-TPU — the parity-test hook); ``False`` forces
  the reference.

Weight layouts are the stacked expert banks of
:class:`...modules.moe.expert_mlps.ExpertMLPs`: ``gate_up [E, H, 2, I]``,
``down [E, I, H]``. Blocks whose ``block_expert[b] >= E`` are *sentinels*
(padding or non-local EP pairs): their compute is skipped and their output
rows are zero; their weight-tile index clamps to the last real expert so a
sentinel run costs no extra DMA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .pallas_utils import compiler_params as _compiler_params

__all__ = ["grouped_glu", "grouped_glu_decode", "grouped_glu_reference",
           "use_pallas"]


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _dsilu(x):
    s = jax.nn.sigmoid(x)
    return s * (1 + x * (1 - s))


# ---------------------------------------------------------------------------
# Pallas kernels (training fwd/bwd + decode fwd)
# ---------------------------------------------------------------------------

def _glu_fwd_kernel(be_ref, x_ref, gu_ref, dn_ref, y_ref, *, num_ib: int,
                    num_real: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ib = pl.program_id(1)

    @pl.when(ib == 0)
    def _init():
        # unconditional: sentinel blocks' outputs must be ZERO (their
        # combine gates are zero, but 0 * uninitialized-HBM could be NaN)
        y_ref[...] = jnp.zeros_like(y_ref)

    @pl.when(be_ref[b] < num_real)
    def _compute():
        x = x_ref[...].astype(jnp.float32)            # [B, H]
        gu = gu_ref[0].astype(jnp.float32)            # [H, 2, bI]
        g = jax.lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        a = _silu(g) * u                              # [B, bI]
        y_ref[...] = y_ref[...] + jax.lax.dot_general(
            a, dn_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(y_ref.dtype)


def _glu_dx_kernel(be_ref, x_ref, gu_ref, dn_ref, dy_ref, dx_ref, *,
                   num_ib: int, num_real: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ib = pl.program_id(1)

    @pl.when(ib == 0)
    def _init():
        dx_ref[...] = jnp.zeros_like(dx_ref)

    @pl.when(be_ref[b] < num_real)
    def _compute():
        x = x_ref[...].astype(jnp.float32)
        dy = dy_ref[...].astype(jnp.float32)
        gu = gu_ref[0].astype(jnp.float32)            # [H, 2, bI]
        dn = dn_ref[0].astype(jnp.float32)            # [bI, H]
        g = jax.lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        da = jax.lax.dot_general(dy, dn, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dg = da * u * _dsilu(g)
        du = da * _silu(g)
        dx = jax.lax.dot_general(dg, gu[:, 0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dx = dx + jax.lax.dot_general(du, gu[:, 1], (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dx_ref[...] = dx_ref[...] + dx.astype(dx_ref.dtype)


def _glu_dw_kernel(be_ref, x_ref, gu_ref, dn_ref, dy_ref, dgu_ref, ddn_ref,
                   *, num_ib: int, num_real: int):
    """Grid (ib, b): consecutive b of one expert revisit the same dW output
    block, accumulating in VMEM; zero it on the expert's first block."""
    from jax.experimental import pallas as pl

    b = pl.program_id(1)
    # boundaries on the CLAMPED expert id (what the out index_map uses):
    # sentinel blocks share the last real expert's tile, so the real->
    # sentinel transition must NOT re-zero that expert's accumulated dW
    cur = jnp.minimum(be_ref[b], num_real - 1)
    prev = jnp.minimum(be_ref[jnp.maximum(b, 1) - 1], num_real - 1)
    first_of_expert = jnp.logical_or(b == 0, prev != cur)

    @pl.when(first_of_expert)
    def _init():
        dgu_ref[...] = jnp.zeros_like(dgu_ref)
        ddn_ref[...] = jnp.zeros_like(ddn_ref)

    @pl.when(be_ref[b] < num_real)
    def _compute():
        x = x_ref[...].astype(jnp.float32)
        dy = dy_ref[...].astype(jnp.float32)
        gu = gu_ref[0].astype(jnp.float32)
        dn = dn_ref[0].astype(jnp.float32)
        g = jax.lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        a = _silu(g) * u
        da = jax.lax.dot_general(dy, dn, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dg = da * u * _dsilu(g)
        du = da * _silu(g)
        # ddown[e, ib] += a^T @ dy ; dgu[e, :, 0/1, ib] += x^T @ dg/du
        ddn_ref[0] = ddn_ref[0] + jax.lax.dot_general(
            a, dy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(ddn_ref.dtype)
        dgw = jax.lax.dot_general(x, dg, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        duw = jax.lax.dot_general(x, du, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dgu_ref[0] = dgu_ref[0] + jnp.stack([dgw, duw], axis=1).astype(
            dgu_ref.dtype)


def _grouped_glu_pallas(xs, gate_up, down, block_expert, block_size,
                        block_i, interpret, num_real):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p, h = xs.shape
    e, _, _, i = gate_up.shape
    nb = p // block_size
    num_ib = i // block_i
    # sentinel blocks (be >= num_real) borrow the LAST real expert's weight
    # tiles via this clamp — the DMA is elided across a run of sentinel
    # blocks and the kernels' pl.when guards skip their compute entirely.
    # Grid order (b, ib): the y block accumulates over consecutive ib steps
    # in VMEM (a non-consecutive revisit would not re-fetch); weight tiles
    # are refetched per block — the layout that favours training, where
    # nb ~ E. Decode uses the (ib, b) grid of :func:`grouped_glu_decode`.
    we = functools.partial(jnp.minimum, num_real - 1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, num_ib),
        in_specs=[
            pl.BlockSpec((block_size, h), lambda b, ib, be: (b, 0)),
            pl.BlockSpec((1, h, 2, block_i),
                         lambda b, ib, be: (we(be[b]), 0, 0, ib)),
            pl.BlockSpec((1, block_i, h),
                         lambda b, ib, be: (we(be[b]), ib, 0)),
        ],
        out_specs=pl.BlockSpec((block_size, h), lambda b, ib, be: (b, 0)),
    )
    return pl.pallas_call(
        functools.partial(_glu_fwd_kernel, num_ib=num_ib,
                          num_real=num_real),
        out_shape=jax.ShapeDtypeStruct((p, h), xs.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(block_expert, xs, gate_up, down)


def _glu_fwd_decode_kernel(be_ref, x_ref, gu_ref, dn_ref, y_ref, *,
                           num_real: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(1)

    # each (ib, b) output block is written exactly once — no revisits
    y_ref[...] = jnp.zeros_like(y_ref)

    @pl.when(be_ref[b] < num_real)
    def _compute():
        x = x_ref[...].astype(jnp.float32)            # [B, H]
        gu = gu_ref[0].astype(jnp.float32)            # [H, 2, bI]
        g = jax.lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        a = _silu(g) * u                              # [B, bI]
        y_ref[...] = jax.lax.dot_general(
            a, dn_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(y_ref.dtype)[None]


def _grouped_glu_decode_pallas(xs, gate_up, down, block_expert, block_size,
                               block_i, interpret):
    """Forward-only grouped GLU tuned for decode HBM traffic.

    Grid order (ib, b) — token blocks INNERMOST — so consecutive blocks of
    one (clamped) expert keep an identical weight-tile index and Pallas
    elides the refetch: total weight traffic is (#hit experts) x weights
    instead of (#blocks) x weights. With ``sentinel_empty`` metadata all
    empty experts clamp into one shared sentinel run, so a T-token decode
    step reads only the experts those tokens hit — the bandwidth property
    the reference's fused token-gen kernel exists for
    (``moe_fused_tkg.py:85``). Each (ib, b) output block is written exactly
    once into a partial layout [num_ib, P, H] summed by XLA (an in-kernel
    accumulation would need non-consecutive output revisits, which do not
    re-fetch). The extra partial-sum traffic is O(num_ib·P·H) — trivial at
    decode's tiny P, which is why training keeps :func:`grouped_glu`.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p, h = xs.shape
    e, _, _, i = gate_up.shape
    num_real = e
    nb = p // block_size
    num_ib = i // block_i
    we = functools.partial(jnp.minimum, num_real - 1)
    partial = pl.pallas_call(
        functools.partial(_glu_fwd_decode_kernel, num_real=num_real),
        # fp32 partials: the per-ib contributions are summed below, and a
        # bf16 round-trip through HBM before that sum loses mantissa bits
        # the kernel already paid fp32 accumulation for (advisor r3)
        out_shape=jax.ShapeDtypeStruct((num_ib, p, h), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_ib, nb),
            in_specs=[
                pl.BlockSpec((block_size, h), lambda ib, b, be: (b, 0)),
                pl.BlockSpec((1, h, 2, block_i),
                             lambda ib, b, be: (we(be[b]), 0, 0, ib)),
                pl.BlockSpec((1, block_i, h),
                             lambda ib, b, be: (we(be[b]), ib, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_size, h),
                                   lambda ib, b, be: (ib, b, 0)),
        ),
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(block_expert, xs, gate_up, down)
    return jnp.sum(partial, axis=0).astype(xs.dtype)


def _grouped_glu_pallas_bwd(xs, gate_up, down, block_expert, dy, block_size,
                            block_i, interpret, num_real):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p, h = xs.shape
    e, _, _, i = gate_up.shape
    nb = p // block_size
    num_ib = i // block_i
    we = functools.partial(jnp.minimum, num_real - 1)

    dx = pl.pallas_call(
        functools.partial(_glu_dx_kernel, num_ib=num_ib,
                          num_real=num_real),
        out_shape=jax.ShapeDtypeStruct((p, h), xs.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb, num_ib),
            in_specs=[
                pl.BlockSpec((block_size, h), lambda b, ib, be: (b, 0)),
                pl.BlockSpec((1, h, 2, block_i),
                             lambda b, ib, be: (we(be[b]), 0, 0, ib)),
                pl.BlockSpec((1, block_i, h),
                             lambda b, ib, be: (we(be[b]), ib, 0)),
                pl.BlockSpec((block_size, h), lambda b, ib, be: (b, 0)),
            ],
            out_specs=pl.BlockSpec((block_size, h),
                                   lambda b, ib, be: (b, 0)),
        ),
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(block_expert, xs, gate_up, down, dy)

    dgu, ddn = pl.pallas_call(
        functools.partial(_glu_dw_kernel, num_ib=num_ib,
                          num_real=num_real),
        out_shape=[jax.ShapeDtypeStruct(gate_up.shape, jnp.float32),
                   jax.ShapeDtypeStruct(down.shape, jnp.float32)],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(num_ib, nb),
            in_specs=[
                pl.BlockSpec((block_size, h), lambda ib, b, be: (b, 0)),
                pl.BlockSpec((1, h, 2, block_i),
                             lambda ib, b, be: (we(be[b]), 0, 0, ib)),
                pl.BlockSpec((1, block_i, h),
                             lambda ib, b, be: (we(be[b]), ib, 0)),
                pl.BlockSpec((block_size, h), lambda ib, b, be: (b, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, h, 2, block_i),
                             lambda ib, b, be: (we(be[b]), 0, 0, ib)),
                pl.BlockSpec((1, block_i, h),
                             lambda ib, b, be: (we(be[b]), ib, 0)),
            ],
        ),
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(block_expert, xs, gate_up, down, dy)
    return dx, dgu.astype(gate_up.dtype), ddn.astype(down.dtype)


# ---------------------------------------------------------------------------
# Pure-jnp reference (bit-exact vs the kernels in interpret mode)
#
# Every dot below uses the SAME lax.dot_general dimension numbers, operand
# shapes and fp32 accumulation order as the kernel body executes them per
# (b, ib) grid step, so CPU parity is bitwise, not approximate: a scan over
# blocks is the grid's b loop, the unrolled num_ib loop is the grid's ib
# loop, and sentinel blocks contribute exactly nothing (lax.cond / where,
# never a masked add that could flip a -0.0).
# ---------------------------------------------------------------------------

def _ref_block_fwd(x_blk, gu_e, dn_e, live, block_i, num_ib, out_dtype):
    """One token block through the GLU with its (clamped) expert weights:
    the per-``ib`` fp32 partials accumulate in ``out_dtype`` exactly like
    ``y_ref[...] = y_ref[...] + partial.astype(y_ref.dtype)``."""
    x = x_blk.astype(jnp.float32)
    y = jnp.zeros((x.shape[0], dn_e.shape[-1]), out_dtype)
    for ib in range(num_ib):
        gu = lax.dynamic_slice_in_dim(gu_e, ib * block_i, block_i, axis=2)
        dn = lax.dynamic_slice_in_dim(dn_e, ib * block_i, block_i, axis=0)
        gu = gu.astype(jnp.float32)
        dn = dn.astype(jnp.float32)
        g = lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        u = lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        a = _silu(g) * u
        y = y + lax.dot_general(
            a, dn, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(out_dtype)
    return jnp.where(live, y, jnp.zeros_like(y))


def _ref_fwd(xs, gate_up, down, block_expert, block_size, block_i,
             num_real):
    p, h = xs.shape
    i = gate_up.shape[-1]
    nb = p // block_size
    num_ib = i // block_i
    xb = xs.reshape(nb, block_size, h)

    def step(_, inp):
        x_blk, be_b = inp
        we = jnp.minimum(be_b, num_real - 1)
        gu_e = lax.dynamic_index_in_dim(gate_up, we, 0, keepdims=False)
        dn_e = lax.dynamic_index_in_dim(down, we, 0, keepdims=False)
        y = _ref_block_fwd(x_blk, gu_e, dn_e, be_b < num_real, block_i,
                           num_ib, xs.dtype)
        return None, y

    _, ys = lax.scan(step, None, (xb, block_expert))
    return ys.reshape(p, h)


def _ref_decode_fwd(xs, gate_up, down, block_expert, block_size, block_i):
    """Decode reference: per-(ib, b) partials land in a [num_ib, P, H]
    fp32 layout summed at the end — the same ``jnp.sum(partial, axis=0)``
    the Pallas decode path performs outside the kernel."""
    p, h = xs.shape
    i = gate_up.shape[-1]
    num_real = gate_up.shape[0]
    nb = p // block_size
    num_ib = i // block_i
    xb = xs.reshape(nb, block_size, h)

    def step(_, inp):
        x_blk, be_b = inp
        we = jnp.minimum(be_b, num_real - 1)
        gu_e = lax.dynamic_index_in_dim(gate_up, we, 0, keepdims=False)
        dn_e = lax.dynamic_index_in_dim(down, we, 0, keepdims=False)
        x = x_blk.astype(jnp.float32)
        parts = []
        for ib in range(num_ib):
            gu = lax.dynamic_slice_in_dim(
                gu_e, ib * block_i, block_i, axis=2).astype(jnp.float32)
            dn = lax.dynamic_slice_in_dim(
                dn_e, ib * block_i, block_i, axis=0).astype(jnp.float32)
            g = lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            u = lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            a = _silu(g) * u
            y = lax.dot_general(a, dn, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            parts.append(jnp.where(be_b < num_real, y, jnp.zeros_like(y)))
        return None, jnp.stack(parts)                 # [num_ib, B, H]

    _, parts = lax.scan(step, None, (xb, block_expert))
    partial = jnp.moveaxis(parts, 1, 0).reshape(num_ib, p, h)
    return jnp.sum(partial, axis=0).astype(xs.dtype)


def _ref_dx(xs, gate_up, down, block_expert, dy, block_size, block_i,
            num_real):
    p, h = xs.shape
    i = gate_up.shape[-1]
    nb = p // block_size
    num_ib = i // block_i
    xb = xs.reshape(nb, block_size, h)
    dyb = dy.reshape(nb, block_size, h)

    def step(_, inp):
        x_blk, dy_blk, be_b = inp
        we = jnp.minimum(be_b, num_real - 1)
        gu_e = lax.dynamic_index_in_dim(gate_up, we, 0, keepdims=False)
        dn_e = lax.dynamic_index_in_dim(down, we, 0, keepdims=False)
        x = x_blk.astype(jnp.float32)
        dyf = dy_blk.astype(jnp.float32)
        dx = jnp.zeros((block_size, h), xs.dtype)
        for ib in range(num_ib):
            gu = lax.dynamic_slice_in_dim(
                gu_e, ib * block_i, block_i, axis=2).astype(jnp.float32)
            dn = lax.dynamic_slice_in_dim(
                dn_e, ib * block_i, block_i, axis=0).astype(jnp.float32)
            g = lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            u = lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            da = lax.dot_general(dyf, dn, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            dg = da * u * _dsilu(g)
            du = da * _silu(g)
            d = lax.dot_general(dg, gu[:, 0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            d = d + lax.dot_general(du, gu[:, 1], (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            dx = dx + d.astype(xs.dtype)
        return None, jnp.where(be_b < num_real, dx, jnp.zeros_like(dx))

    _, dxs = lax.scan(step, None, (xb, dyb, block_expert))
    return dxs.reshape(p, h)


def _ref_dw(xs, gate_up, down, block_expert, dy, block_size, block_i,
            num_real):
    """dW reference: fp32 accumulators updated block-by-block in ascending
    ``b`` order (the kernel's grid (ib, b) VMEM accumulation per expert
    tile is exactly this sequence of fp32 adds); sentinel blocks are
    skipped via ``lax.cond`` so they contribute no add at all."""
    p, h = xs.shape
    i = gate_up.shape[-1]
    nb = p // block_size
    num_ib = i // block_i
    xb = xs.reshape(nb, block_size, h)
    dyb = dy.reshape(nb, block_size, h)

    def step(carry, inp):
        dgu, ddn = carry
        x_blk, dy_blk, be_b = inp
        we = jnp.minimum(be_b, num_real - 1)
        gu_e = lax.dynamic_index_in_dim(gate_up, we, 0, keepdims=False)
        dn_e = lax.dynamic_index_in_dim(down, we, 0, keepdims=False)
        x = x_blk.astype(jnp.float32)
        dyf = dy_blk.astype(jnp.float32)

        def upd(c):
            dgu, ddn = c
            for ib in range(num_ib):
                gu = lax.dynamic_slice_in_dim(
                    gu_e, ib * block_i, block_i, axis=2).astype(jnp.float32)
                dn = lax.dynamic_slice_in_dim(
                    dn_e, ib * block_i, block_i, axis=0).astype(jnp.float32)
                g = lax.dot_general(x, gu[:, 0], (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                u = lax.dot_general(x, gu[:, 1], (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                a = _silu(g) * u
                da = lax.dot_general(dyf, dn, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
                dg = da * u * _dsilu(g)
                du = da * _silu(g)
                ddn_c = lax.dot_general(a, dyf, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                dgw = lax.dot_general(x, dg, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
                duw = lax.dot_general(x, du, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
                dgu_c = jnp.stack([dgw, duw], axis=1)     # [H, 2, bI]
                tile = lax.dynamic_slice(
                    dgu, (we, 0, 0, ib * block_i), (1, h, 2, block_i))
                dgu = lax.dynamic_update_slice(
                    dgu, tile + dgu_c[None], (we, 0, 0, ib * block_i))
                tile = lax.dynamic_slice(
                    ddn, (we, ib * block_i, 0), (1, block_i, h))
                ddn = lax.dynamic_update_slice(
                    ddn, tile + ddn_c[None], (we, ib * block_i, 0))
            return dgu, ddn

        carry = lax.cond(be_b < num_real, upd, lambda c: c, (dgu, ddn))
        return carry, None

    init = (jnp.zeros(gate_up.shape, jnp.float32),
            jnp.zeros(down.shape, jnp.float32))
    (dgu, ddn), _ = lax.scan(step, init, (xb, dyb, block_expert))
    return dgu.astype(gate_up.dtype), ddn.astype(down.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrappers: one pallas-backed, one reference-backed, identical
# signatures, so autodiff works through whichever path auto-dispatch picks
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _grouped_glu_kernel(xs, gate_up, down, block_expert, block_size,
                        block_i, interpret):
    return _grouped_glu_pallas(xs, gate_up, down, block_expert, block_size,
                               block_i, interpret, gate_up.shape[0])


def _kernel_fwd(xs, gate_up, down, block_expert, block_size, block_i,
                interpret):
    ys = _grouped_glu_pallas(xs, gate_up, down, block_expert, block_size,
                             block_i, interpret, gate_up.shape[0])
    return ys, (xs, gate_up, down, block_expert)


def _kernel_bwd(block_size, block_i, interpret, res, dy):
    xs, gate_up, down, block_expert = res
    dx, dgu, ddn = _grouped_glu_pallas_bwd(
        xs, gate_up, down, block_expert, dy, block_size, block_i, interpret,
        gate_up.shape[0])
    dbe = jnp.zeros(block_expert.shape, jax.dtypes.float0)
    return dx, dgu, ddn, dbe


_grouped_glu_kernel.defvjp(_kernel_fwd, _kernel_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def grouped_glu_reference(xs, gate_up, down, block_expert, block_size,
                          block_i):
    """Pure-jnp grouped GLU, arithmetic-identical to the Pallas kernel
    (the golden reference of the interpret-mode parity gate, and the
    silent CPU fallback of :func:`grouped_glu`)."""
    return _ref_fwd(xs, gate_up, down, block_expert, block_size, block_i,
                    gate_up.shape[0])


def _ref_vjp_fwd(xs, gate_up, down, block_expert, block_size, block_i):
    ys = _ref_fwd(xs, gate_up, down, block_expert, block_size, block_i,
                  gate_up.shape[0])
    return ys, (xs, gate_up, down, block_expert)


def _ref_vjp_bwd(block_size, block_i, res, dy):
    xs, gate_up, down, block_expert = res
    num_real = gate_up.shape[0]
    dx = _ref_dx(xs, gate_up, down, block_expert, dy, block_size, block_i,
                 num_real)
    dgu, ddn = _ref_dw(xs, gate_up, down, block_expert, dy, block_size,
                       block_i, num_real)
    dbe = jnp.zeros(block_expert.shape, jax.dtypes.float0)
    return dx, dgu, ddn, dbe


grouped_glu_reference.defvjp(_ref_vjp_fwd, _ref_vjp_bwd)


# ---------------------------------------------------------------------------
# auto-dispatch (the ops/paged_attention.py idiom)
# ---------------------------------------------------------------------------

def use_pallas(force_pallas=None) -> bool:
    """Resolve the dispatch knob: ``None`` (auto) → Pallas only on
    TPU-like backends, silent jnp reference elsewhere; ``True`` → always
    the kernel (interpret mode off-TPU — the bit-exactness test hook);
    ``False`` → always the reference."""
    if force_pallas is None:
        return jax.default_backend() in ("tpu", "axon")
    return bool(force_pallas)


def grouped_glu(xs, gate_up, down, block_expert, block_size, block_i,
                force_pallas=None):
    """Block-sparse grouped GLU: ``ys[b] = silu(x_b@Wg_e)·(x_b@Wu_e) @ Wd_e``
    with ``e = block_expert[b]`` (the dropless expert matmul; training
    fwd+bwd).

    Blocks whose ``block_expert[b] >= E`` (the weight arrays' expert count)
    are *sentinels* (padding / bound-EP non-local pairs): their compute is
    skipped and their output rows are zero. Deriving the sentinel threshold
    from the array shape (rather than a parameter) guarantees every real
    expert owns >= 1 block, so no dW tile is left unwritten."""
    if use_pallas(force_pallas):
        interpret = jax.default_backend() not in ("tpu", "axon")
        return _grouped_glu_kernel(xs, gate_up, down, block_expert,
                                   block_size, block_i, interpret)
    return grouped_glu_reference(xs, gate_up, down, block_expert,
                                 block_size, block_i)


def grouped_glu_decode(xs, gate_up, down, block_expert, block_size,
                       block_i, force_pallas=None):
    """Forward-only grouped GLU tuned for decode HBM traffic (token blocks
    innermost so one expert's weight DMA serves its whole block run; pair
    with ``sentinel_empty`` metadata so only hit experts are read)."""
    if use_pallas(force_pallas):
        interpret = jax.default_backend() not in ("tpu", "axon")
        return _grouped_glu_decode_pallas(xs, gate_up, down, block_expert,
                                          block_size, block_i, interpret)
    return _ref_decode_fwd(xs, gate_up, down, block_expert, block_size,
                           block_i)
