"""Flash decoding: sequence-split KV attention for token generation.

Analogue of the reference's KV-shared decode groups
(``parallel_layers/parallel_state.py:1473-1531`` ``num_cores_per_group``;
on-device combine ``trace/spmd.py:74`` ``combine_kv_on_device``): during
decode the KV cache's *slot* dim is sharded over a core group, every core
computes partial attention over its slice, and the partials merge with the
numerically-stable log-sum-exp combine.

TPU-native: the group is a mesh axis (normally ``tp`` — queries are small
and replicated at decode); the merge is three collectives (pmax + 2 psum)
inside shard_map. Inference-only (no VJP needed).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import mesh as ps


def flash_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           slot_pos: jax.Array, q_pos: jax.Array,
                           axis: str = ps.TP_AXIS,
                           scale: Optional[float] = None) -> jax.Array:
    """Attention of a small query block against a slot-sharded KV cache.

    Args:
      q: ``[B, S, N, D]`` queries (replicated over ``axis``).
      k/v: ``[B, L_local, KV, D]`` this shard's cache slots (GQA: N % KV
        == 0).
      slot_pos: ``[B, L_local]`` stored token position per slot
        (``PAD_POSITION`` for empty slots — never attended).
      q_pos: ``[B, S]`` query token positions (causal: slot attended iff
        ``slot_pos <= q_pos``).

    Returns ``[B, S, N, D]``. When ``axis`` is unbound this is plain
    masked attention over the full cache.
    """
    b, s, n, d = q.shape
    kvh = k.shape[2]
    g = n // kvh
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    qf = (q.astype(jnp.float32) * scale).reshape(b, s, kvh, g, d)
    scores = jnp.einsum("bskgd,blkd->bskgl", qf, k.astype(jnp.float32))
    mask = (slot_pos[:, None, None, None, :]
            <= q_pos[:, :, None, None, None])
    scores = jnp.where(mask, scores, -jnp.inf)

    m_local = jnp.max(scores, axis=-1)                  # [B,S,KV,G]
    m_safe = jnp.where(jnp.isfinite(m_local), m_local, 0.0)
    p = jnp.where(jnp.isfinite(scores),
                  jnp.exp(scores - m_safe[..., None]), 0.0)
    l_local = jnp.sum(p, axis=-1)
    o_local = jnp.einsum("bskgl,blkd->bskgd", p, v.astype(jnp.float32))

    if comm._axis_size(axis) not in (None, 1):
        # log-sum-exp combine across the decode group (reference
        # combine_kv_on_device, trace/spmd.py:74)
        m = lax.pmax(m_local, axis)
        m_gsafe = jnp.where(jnp.isfinite(m), m, 0.0)
        corr = jnp.where(jnp.isfinite(m_local),
                         jnp.exp(m_safe - m_gsafe), 0.0)
        l = lax.psum(l_local * corr, axis)
        o = lax.psum(o_local * corr[..., None], axis)
    else:
        l, o = l_local, o_local

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, s, n, d).astype(q.dtype)


# -- nxdlint jaxpr-audit entry point ---------------------------------------

from ..analysis.audit_registry import BuiltEntry, register_entry_point


@register_entry_point(
    "flash-decoding",
    description="tp flash decoding: slot-sharded KV combine via pmax + "
                "two psums on the tp axis",
    tags=("serve",),
    in_shardings=((), (None, "tp"), (None, "tp"), (None, "tp"), ()),
)
def _audit_flash_decoding() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``/``--mesh-protocol``: decode
    combine on a 4-way tp mesh. The cache shards stay tp-sharded after
    propagation; the small query/output are replicated by design (the
    entry declares no ``max_replicated_bytes`` ceiling)."""
    from jax.sharding import PartitionSpec as P

    from ..inference.kv_cache import PAD_POSITION

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(tensor_model_parallel_size=4)
    fn = jax.jit(ps.shard_map(
        lambda q, k, v, sp, qp: flash_decode_attention(q, k, v, sp, qp),
        mesh,
        in_specs=(P(), P(None, "tp"), P(None, "tp"), P(None, "tp"), P()),
        out_specs=P()))
    b, s, n, kvh, d, slots = 2, 2, 8, 4, 16, 32
    q = jnp.zeros((b, s, n, d), jnp.float32)
    k = jnp.zeros((b, slots, kvh, d), jnp.float32)
    slot_pos = jnp.full((b, slots), PAD_POSITION, jnp.int32)
    q_pos = jnp.zeros((b, s), jnp.int32)
    return BuiltEntry(fn=fn, args=(q, k, k, slot_pos, q_pos), mesh=mesh)
