"""Distributed argmax / top-k over a tp-sharded dimension.

Analogue of the reference's ``operators/argmax.py:55`` and
``operators/topk.py:31``: each shard computes its local winners, indices are
corrected by the shard's global offset, and an all-gather + final reduction
picks the global result — the full (e.g. vocab) dim never materialises on one
device. Used by the serving path for greedy/top-k sampling over tp-sharded
lm-head logits.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import mesh as ps


def distributed_argmax(x: jax.Array, axis: str = ps.TP_AXIS,
                       dim: int = -1) -> jax.Array:
    """Global argmax indices over the sharded ``dim`` (reference
    ``argmax:55``)."""
    n = comm._axis_size(axis)
    if n is None or n == 1:
        return jnp.argmax(x, axis=dim)
    dim = dim % x.ndim
    local_size = x.shape[dim]
    local_idx = jnp.argmax(x, axis=dim)
    local_max = jnp.max(x, axis=dim)
    offset = lax.axis_index(axis) * local_size
    global_idx = local_idx + offset
    # gather each shard's (max, idx) pair and reduce on every shard
    maxes = lax.all_gather(local_max, axis)          # [n, ...]
    idxs = lax.all_gather(global_idx, axis)          # [n, ...]
    winner = jnp.argmax(maxes, axis=0)               # [...]
    return jnp.take_along_axis(idxs, winner[None], axis=0)[0]


def distributed_topk(x: jax.Array, k: int, axis: str = ps.TP_AXIS,
                     dim: int = -1) -> Tuple[jax.Array, jax.Array]:
    """Global top-k ``(values, indices)`` over the sharded ``dim``
    (reference ``topk:31``): local top-k per shard, gather the n*k
    candidates, re-top-k."""
    n = comm._axis_size(axis)
    if n is None or n == 1:
        return lax.top_k(jnp.moveaxis(x, dim, -1), k)
    dim = dim % x.ndim
    local_size = x.shape[dim]
    if k > local_size:
        raise ValueError(f"k={k} exceeds local shard size {local_size}")
    xm = jnp.moveaxis(x, dim, -1)
    lv, li = lax.top_k(xm, k)                        # [..., k]
    offset = lax.axis_index(axis) * local_size
    li = li + offset
    # gather candidates along the k dim -> [..., n*k]
    cv = comm.all_gather(lv, axis, dim=-1)
    ci = comm.all_gather(li, axis, dim=-1)
    gv, gpos = lax.top_k(cv, k)
    gi = jnp.take_along_axis(ci, gpos, axis=-1)
    return gv, gi
