"""Ring attention for context parallelism.

Analogue of the reference's NKI ring attention wrapper
(``kernels/ring_attention_kernel.py:118`` → ``nki_ring_attn_func``): each cp
rank holds one sequence slice of Q/K/V; KV blocks rotate around the cp ring
while each rank accumulates flash-style online-softmax partials for its local
queries. The reference drives the ring with precomputed device ``src_tgt_pairs``
(``parallel_state.py:737-742``); here the ring is ``lax.ppermute`` over the
``cp`` mesh axis — the ring edges ARE the mesh axis ordering, which
``initialize_model_parallel`` lays out along the ICI torus.

Causal masking across ring steps: the kv block currently held at step ``i``
originated at rank ``(r - i) mod cp``; queries attend with position masks
computed from the *global* positions of both blocks, so causality holds
exactly across the ring (SURVEY §7.3 flags this as the hard part the
reference hides inside its NKI kernel).

Differentiable through JAX autodiff (the scan+ppermute transpose is the
reverse ring — same structure the pipeline engine relies on).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import mesh as ps


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis: str = ps.CP_AXIS,
                   causal: bool = True,
                   scale: Optional[float] = None,
                   dropout_p: float = 0.0,
                   dropout_seed: Optional[jax.Array] = None,
                   wire=None,
                   wire_dtype: Optional[str] = None,
                   wire_block_size: int = 256) -> jax.Array:
    """Ring attention over the cp axis.

    ``q/k/v: [B, S_local, N, D]`` — this rank's sequence slice, kv already
    GQA-expanded. Must be called with ``axis`` bound (inside shard_map);
    falls back to plain attention when cp is absent/1.

    ``dropout_p``: attention dropout with the shared counter-based hash
    over GLOBAL (q, k) sequence coordinates — every cp rank regenerates
    exactly the mask the non-CP model draws for its slice, so adding cp
    sharding is bit-consistent with the same model at cp=1. (Head indices
    in the hash are tp-LOCAL, so the masks match at equal TP degree;
    changing tp changes the draw, as in the reference's per-rank seed
    plumbing, ``kernels/ring_attention_kernel.py``.)

    ``wire`` / ``wire_dtype``: quantize the KV ring hops through the
    shared wire codec (EQuARX-style blockwise int8/fp8,
    :mod:`..parallel.wire_codec`): each ppermute ships the quantized
    payload plus its fp32 block scales and the receiver dequantizes
    before accumulating. ``wire`` takes a :class:`CompressionConfig`
    directly; ``wire_dtype`` (``"int8"``/``"fp8"``) builds one with
    ``wire_block_size``-element blocks. ``None``/``"fp32"`` keeps the
    hops at full precision and is BITWISE identical to the pre-wire ring
    (the fallback knob serving exposes as ``cp_wire_dtype="fp32"``).
    Each hop requantizes the visiting chunk, so a chunk that travels
    ``j`` hops has been through ``j`` round-trips — inference-only
    (rounding has zero gradient; the training path never passes ``wire``).

    Returns ``[B, S_local, N, D]``.
    """
    from ..parallel.wire_codec import CompressionConfig

    if wire is None and wire_dtype is not None and wire_dtype != "fp32":
        wire = CompressionConfig(dtype=wire_dtype,
                                 block_size=wire_block_size)
    if wire is not None and not wire.quantized:
        wire = None
    cp = comm._axis_size(axis)
    if cp is None or cp == 1:
        from ..modules.attention import sdpa_reference

        return sdpa_reference(q, k, v, causal=causal, scale=scale,
                              dropout_p=dropout_p,
                              dropout_seed=dropout_seed)

    b, s_local, n, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    r = lax.axis_index(axis)
    qpos = r * s_local + jnp.arange(s_local)  # global query positions

    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,N,Sq,D]
    ring_perm = [(i, (i + 1) % cp) for i in range(cp)]
    if dropout_p > 0.0:
        from .flash_attention import dropout_keep_mask, flat_bh

        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed")
        seed_u32 = jnp.asarray(dropout_seed, jnp.uint32)
        s_global = cp * s_local
        bh = flat_bh(b, n)

    def accumulate(carry, k_cur, v_cur, i):
        m_prev, l_prev, acc = carry
        src = (r - i) % cp  # rank where this kv block originated
        kt = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        s = jnp.einsum("bnqd,bnkd->bnqk", qt, kt,
                       preferred_element_type=jnp.float32) * scale
        kpos = src * s_local + jnp.arange(s_local)
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[..., None]), 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        if dropout_p > 0.0:
            keep = dropout_keep_mask(
                seed_u32, bh, qpos[None, None, :, None],
                kpos[None, None, None, :], s_global, dropout_p)
            p_acc = jnp.where(keep, p, 0.0)
        else:
            p_acc = p
        acc = acc * corr[..., None] + jnp.einsum(
            "bnqk,bnkd->bnqd", p_acc, vt,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    def step(carry, i):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        m_new, l_new, acc = accumulate((m_prev, l_prev, acc), k_cur, v_cur, i)
        if wire is None:
            k_next = comm.ppermute(k_cur, axis, ring_perm)
            v_next = comm.ppermute(v_cur, axis, ring_perm)
        else:
            # quantized hop: the int8/fp8 payload and its fp32 block
            # scales ride the same ring permute; dequantize on arrival
            from ..parallel.wire_codec import decode_payload, encode_payload

            kq, ks = encode_payload(k_cur, wire)
            vq, vs = encode_payload(v_cur, wire)
            kq = comm.ppermute(kq, axis, ring_perm)
            ks = comm.ppermute(ks, axis, ring_perm)
            vq = comm.ppermute(vq, axis, ring_perm)
            vs = comm.ppermute(vs, axis, ring_perm)
            k_next = decode_payload(kq, ks, wire).astype(k_cur.dtype)
            v_next = decode_payload(vq, vs, wire).astype(v_cur.dtype)
        return (m_new, l_new, acc, k_next, v_next), None

    m0 = jnp.full((b, n, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n, s_local), jnp.float32)
    acc0 = jnp.zeros((b, n, s_local, d), jnp.float32)
    # cp-1 rotating steps, then a final permute-free accumulate (uniform
    # across ranks; saves two collectives per call)
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(cp - 1))
    m, l, acc = accumulate((m, l, acc), k_last, v_last, cp - 1)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    if dropout_p > 0.0:
        out = out * (1.0 / (1.0 - dropout_p))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas-fused ring attention: each ring step runs the hand-tiled flash
# kernel (ops/flash_attention) on the visiting KV chunk; chunk partials
# merge across steps with the stable log-sum-exp combine. The backward is a
# second ring pass reusing the Pallas flash-backward kernels, with the
# dk/dv accumulators travelling around the ring alongside their KV chunk
# (one full circle returns them home). Reference ships this fusion as one
# NKI kernel (kernels/ring_attention_kernel.py:118); the XLA formulation
# above stays as the golden reference.
#
# Cross-rank causality is all-or-nothing per chunk: the diagonal chunk
# (src == r) uses the causal kernel, chunks from earlier ranks the dense
# kernel, later ranks contribute nothing — selected with lax.cond on the
# rank-dependent predicate (no collectives inside, so divergence across cp
# ranks is safe), which skips the masked chunks' compute entirely.
# ---------------------------------------------------------------------------

def _chunk_fwd(q, k_c, v_c, rel, seed, block_q, block_k, scale, interpret,
               dropout_p):
    """(out, lse) of q against one visiting chunk. rel = sign of
    (r - src): 0 -> diagonal (causal), >0 -> fully attended, <0 -> skip.
    ``seed``: (1,) uint32, already folded per (rank, src) pair so every
    chunk draws an independent mask and the backward regenerates it."""
    from .flash_attention import _flash_pallas_fwd

    def diag(q, k_c, v_c):
        return _flash_pallas_fwd(q, k_c, v_c, seed, True, block_q, block_k,
                                 scale, interpret, dropout_p=dropout_p)

    def full(q, k_c, v_c):
        return _flash_pallas_fwd(q, k_c, v_c, seed, False, block_q, block_k,
                                 scale, interpret, dropout_p=dropout_p)

    def skip(q, k_c, v_c):
        b, s, n, d = q.shape
        return (jnp.zeros_like(q),
                jnp.full((b, n, s), -jnp.inf, jnp.float32))

    return lax.cond(rel == 0, diag,
                    lambda q, k_c, v_c: lax.cond(rel > 0, full, skip,
                                                 q, k_c, v_c),
                    q, k_c, v_c)


def _chunk_bwd(q, k_c, v_c, out, lse, g, rel, seed, block_q, block_k, scale,
               interpret, dropout_p):
    from .flash_attention import _flash_pallas_bwd

    def diag(args):
        return _flash_pallas_bwd(*args, seed, True, block_q, block_k, scale,
                                 interpret, dropout_p=dropout_p)

    def full(args):
        return _flash_pallas_bwd(*args, seed, False, block_q, block_k,
                                 scale, interpret, dropout_p=dropout_p)

    def skip(args):
        q, k_c, v_c, _, _, _ = args
        return jnp.zeros_like(q), jnp.zeros_like(k_c), jnp.zeros_like(v_c)

    args = (q, k_c, v_c, out, lse, g)
    return lax.cond(rel == 0, diag,
                    lambda a: lax.cond(rel > 0, full, skip, a), args)


def _pair_seed(seed, r, src, cp):
    """Fold the (query-rank, chunk-home-rank) pair into the base seed so
    each of the cp^2 chunk visits draws an independent mask; fwd and bwd
    recompute the identical fold from (r, src), so masks regenerate."""
    pair = (r.astype(jnp.uint32) * jnp.uint32(cp) + src.astype(jnp.uint32))
    return seed + pair * jnp.uint32(0x9E3779B1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_pallas(q, k, v, seed, axis, block_q, block_k, scale, interpret,
                 dropout_p):
    out, _ = _ring_pallas_fwd_pass(q, k, v, seed, axis, block_q, block_k,
                                   scale, interpret, dropout_p)
    return out


def _ring_pallas_fwd_pass(q, k, v, seed, axis, block_q, block_k, scale,
                          interpret, dropout_p):
    cp = comm._axis_size(axis)
    b, s_local, n, d = q.shape
    r = lax.axis_index(axis)
    ring_perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, i):
        o_run, lse_run, k_cur, v_cur = carry
        src = (r - i) % cp
        rel = r - src  # 0 diag; >0 earlier rank (attend); <0 later (skip)
        o_i, lse_i = _chunk_fwd(q, k_cur, v_cur, rel,
                                _pair_seed(seed, r, src, cp), block_q,
                                block_k, scale, interpret, dropout_p)
        o_i = jnp.swapaxes(o_i, 1, 2).astype(jnp.float32)  # [B,N,S,D]
        m = jnp.maximum(lse_run, lse_i)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        a = jnp.where(jnp.isfinite(lse_run), jnp.exp(lse_run - m_safe), 0.0)
        bb = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - m_safe), 0.0)
        denom = jnp.maximum(a + bb, 1e-30)
        o_run = (o_run * (a / denom)[..., None]
                 + o_i * (bb / denom)[..., None])
        lse_run = m_safe + jnp.log(denom)
        lse_run = jnp.where(a + bb > 0, lse_run, -jnp.inf)
        k_next = comm.ppermute(k_cur, axis, ring_perm)
        v_next = comm.ppermute(v_cur, axis, ring_perm)
        return (o_run, lse_run, k_next, v_next), None

    o0 = jnp.zeros((b, n, s_local, d), jnp.float32)
    lse0 = jnp.full((b, n, s_local), -jnp.inf, jnp.float32)
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(cp))
    return jnp.swapaxes(o, 1, 2).astype(q.dtype), lse


def _ring_pallas_vjp_fwd(q, k, v, seed, axis, block_q, block_k, scale,
                         interpret, dropout_p):
    out, lse = _ring_pallas_fwd_pass(q, k, v, seed, axis, block_q, block_k,
                                     scale, interpret, dropout_p)
    return out, (q, k, v, seed, out, lse)


def _ring_pallas_vjp_bwd(axis, block_q, block_k, scale, interpret, dropout_p,
                         res, g):
    import numpy as np

    q, k, v, seed, out, lse = res
    cp = comm._axis_size(axis)
    r = lax.axis_index(axis)
    ring_perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, i):
        dq_acc, k_cur, v_cur, dk_buf, dv_buf = carry
        src = (r - i) % cp
        rel = r - src
        dq_i, dk_i, dv_i = _chunk_bwd(q, k_cur, v_cur, out, lse, g, rel,
                                      _pair_seed(seed, r, src, cp),
                                      block_q, block_k, scale, interpret,
                                      dropout_p)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_buf = dk_buf + dk_i.astype(jnp.float32)
        dv_buf = dv_buf + dv_i.astype(jnp.float32)
        # the accumulators travel with their chunk; after the full circle
        # they are back at the chunk's home rank
        k_cur = comm.ppermute(k_cur, axis, ring_perm)
        v_cur = comm.ppermute(v_cur, axis, ring_perm)
        dk_buf = comm.ppermute(dk_buf, axis, ring_perm)
        dv_buf = comm.ppermute(dv_buf, axis, ring_perm)
        return (dq_acc, k_cur, v_cur, dk_buf, dv_buf), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dkv0, jnp.zeros(v.shape, jnp.float32)),
        jnp.arange(cp))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            np.zeros(seed.shape, jax.dtypes.float0))


_ring_pallas.defvjp(_ring_pallas_vjp_fwd, _ring_pallas_vjp_bwd)


def ring_attention_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                          axis: str = ps.CP_AXIS,
                          block_q: int = 128, block_k: int = 128,
                          scale: Optional[float] = None,
                          interpret: Optional[bool] = None,
                          dropout_p: float = 0.0,
                          dropout_seed: Optional[jax.Array] = None,
                          ) -> jax.Array:
    """Ring attention with the Pallas flash kernels fused into each ring
    step. Same contract as :func:`ring_attention` except: causal only (the
    cross-chunk skip logic assumes causal), and dropout masks are the
    in-kernel per-chunk draw — deterministic and fwd/bwd-consistent (the
    (rank, chunk-home) pair is folded into the seed) but a DIFFERENT draw
    from :func:`ring_attention`'s global-coordinate masks, which are the
    ones bit-consistent with the cp=1 model. Falls back to
    :func:`ring_attention` (forwarding the dropout arguments) when cp is
    absent or shapes don't tile."""
    cp = comm._axis_size(axis)
    b, s_local, n, d = q.shape
    bq, bk = min(block_q, s_local), min(block_k, s_local)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    # compiled TPU Mosaic requires 128-aligned blocks (flash_attention's
    # tileable_strict); interpret mode accepts 8-aligned for tests
    align = 8 if interpret else 128
    tiles = (s_local % bq == 0 and s_local % bk == 0 and d % 128 == 0
             and bq % align == 0 and bk % align == 0)
    if cp is None or cp == 1 or not tiles:
        return ring_attention(q, k, v, axis=axis, causal=True, scale=scale,
                              dropout_p=dropout_p,
                              dropout_seed=dropout_seed)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    seed = (jnp.asarray(dropout_seed, jnp.uint32).reshape((1,))
            if dropout_p > 0.0 else jnp.zeros((1,), jnp.uint32))
    scale_ = scale if scale is not None else 1.0 / math.sqrt(d)
    return _ring_pallas(q, k, v, seed, axis, bq, bk, scale_, interpret,
                        dropout_p)


# -- nxdlint jaxpr-audit entry point ---------------------------------------

from ..analysis.audit_registry import BuiltEntry, register_entry_point


@register_entry_point(
    "ring-attention",
    description="cp ring attention: cp-1 rotating ppermute hops under "
                "shard_map on the cp axis",
    tags=("train", "serve"),
    in_shardings=((None, "cp", None, None),) * 3,
    max_replicated_bytes=1 << 20,
)
def _audit_ring_attention() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``/``--mesh-protocol``: the XLA ring
    on a 4-way cp mesh. The verifier checks every rotation perm covers
    the axis exactly once and q/k/v stay cp-sharded after propagation."""
    from jax.sharding import PartitionSpec as P

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    fn = jax.jit(ps.shard_map(
        lambda q, k, v: ring_attention(q, k, v),
        mesh, in_specs=(P(None, "cp", None, None),) * 3,
        out_specs=P(None, "cp", None, None)))
    q = jnp.zeros((2, 32, 4, 8), jnp.float32)
    return BuiltEntry(fn=fn, args=(q, q, q), mesh=mesh)


@register_entry_point(
    "ring-attention-int8",
    description="cp ring attention with int8 quantized KV hops: each "
                "ppermute ships the wire-codec payload + fp32 block "
                "scales (CP prefill serving tier)",
    tags=("serve",),
    wire_dtype="int8",
    # the fp32 *scales* legitimately ride the ring beside the int8
    # payload: at the audit shapes they are 64 elements per hop, below
    # this floor; the KV payloads themselves (4096 elements) would trip
    # the wire-precision rule if they ever shipped unquantized
    wire_min_elems=128,
    in_shardings=((None, "cp", None, None),) * 3,
    max_replicated_bytes=1 << 20,
)
def _audit_ring_attention_int8() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``/``--mesh-protocol``: the serving
    ring with quantized hops on a 4-way cp mesh. The wire-precision rule
    verifies no wide-float KV payload rides a ring primitive — only the
    int8 values and their (small) scale tensors may appear."""
    from jax.sharding import PartitionSpec as P

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    fn = jax.jit(ps.shard_map(
        lambda q, k, v: ring_attention(q, k, v, wire_dtype="int8"),
        mesh, in_specs=(P(None, "cp", None, None),) * 3,
        out_specs=P(None, "cp", None, None)))
    q = jnp.zeros((2, 32, 4, 8), jnp.float32)
    return BuiltEntry(fn=fn, args=(q, q, q), mesh=mesh)
