"""Ring attention for context parallelism.

Analogue of the reference's NKI ring attention wrapper
(``kernels/ring_attention_kernel.py:118`` → ``nki_ring_attn_func``): each cp
rank holds one sequence slice of Q/K/V; KV blocks rotate around the cp ring
while each rank accumulates flash-style online-softmax partials for its local
queries. The reference drives the ring with precomputed device ``src_tgt_pairs``
(``parallel_state.py:737-742``); here the ring is ``lax.ppermute`` over the
``cp`` mesh axis — the ring edges ARE the mesh axis ordering, which
``initialize_model_parallel`` lays out along the ICI torus.

Causal masking across ring steps: the kv block currently held at step ``i``
originated at rank ``(r - i) mod cp``; queries attend with position masks
computed from the *global* positions of both blocks, so causality holds
exactly across the ring (SURVEY §7.3 flags this as the hard part the
reference hides inside its NKI kernel).

Differentiable through JAX autodiff (the scan+ppermute transpose is the
reverse ring — same structure the pipeline engine relies on).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import mesh as ps


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis: str = ps.CP_AXIS,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Ring attention over the cp axis.

    ``q/k/v: [B, S_local, N, D]`` — this rank's sequence slice, kv already
    GQA-expanded. Must be called with ``axis`` bound (inside shard_map);
    falls back to plain attention when cp is absent/1.

    Returns ``[B, S_local, N, D]``.
    """
    cp = comm._axis_size(axis)
    if cp is None or cp == 1:
        from ..modules.attention import sdpa_reference

        return sdpa_reference(q, k, v, causal=causal, scale=scale)

    b, s_local, n, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    r = lax.axis_index(axis)
    qpos = r * s_local + jnp.arange(s_local)  # global query positions

    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,N,Sq,D]
    ring_perm = [(i, (i + 1) % cp) for i in range(cp)]

    def accumulate(carry, k_cur, v_cur, i):
        m_prev, l_prev, acc = carry
        src = (r - i) % cp  # rank where this kv block originated
        kt = jnp.swapaxes(k_cur, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(v_cur, 1, 2).astype(jnp.float32)
        s = jnp.einsum("bnqd,bnkd->bnqk", qt, kt,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src * s_local + jnp.arange(s_local)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[..., None]), 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bnqk,bnkd->bnqd", p, vt, preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    def step(carry, i):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        m_new, l_new, acc = accumulate((m_prev, l_prev, acc), k_cur, v_cur, i)
        k_next = comm.ppermute(k_cur, axis, ring_perm)
        v_next = comm.ppermute(v_cur, axis, ring_perm)
        return (m_new, l_new, acc, k_next, v_next), None

    m0 = jnp.full((b, n, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n, s_local), jnp.float32)
    acc0 = jnp.zeros((b, n, s_local, d), jnp.float32)
    # cp-1 rotating steps, then a final permute-free accumulate (uniform
    # across ranks; saves two collectives per call)
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(cp - 1))
    m, l, acc = accumulate((m, l, acc), k_last, v_last, cp - 1)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
