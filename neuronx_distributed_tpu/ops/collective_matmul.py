"""Decomposed (latency-hiding) tensor-parallel collective-matmuls.

The TP layers' hot path interleaves matmuls with collectives: the
sequence-parallel entry all-gathers activations before the column matmul,
and the row matmul exits through a reduce-scatter (or, in plain TP, an
all-reduce). Issued monolithically those collectives serialize with the
compute they feed — the wire sits idle during the matmul and the MXU sits
idle during the collective. This module decomposes each pair into a
``ppermute`` ring that streams remote shards (or partial products) while
each step's partial matmul runs, so XLA can overlap the per-step transfer
with the independent per-step compute (the reference hides the same
latency with hand-scheduled async all-reduce in
``LinearWithAsyncCommunication``, ``parallel_layers/layers.py:434-504``;
see also PAPERS.md on multi-node comm/compute overlap).

Four primitives, each a ``custom_vjp`` whose backward uses the *dual*
decomposition (grad of an all-gather-matmul is a matmul-reduce-scatter and
vice versa):

======================  ===========================  =======================
op                      forward                      backward (dx)
======================  ===========================  =======================
all_gather_matmul       AG(x, dim) @ w  (ring)       matmul_reduce_scatter
matmul_reduce_scatter   RS(x @ w, dim)  (ring)       all_gather_matmul
matmul_all_reduce       AR(x @ w) = AG(RS(x @ w))    x-free: g @ w^T
copy_matmul             x @ w (x replicated)         AR(g @ w^T) = AG(RS(.))
======================  ===========================  =======================

Bit-exactness contract
----------------------
``impl="decomposed"`` and ``impl="monolithic"`` are bit-identical in fp32
(fwd AND grad), by construction rather than by tolerance:

* XLA accumulates ``psum`` / ``psum_scatter`` contributions left-to-right
  in ascending rank order, so the decomposed reduce-scatter delivers each
  partial block directly to its destination (per-step shifted ppermutes),
  buffers them by *source rank*, and performs one ordered left-to-right
  summation — the same additions in the same order as the monolithic
  collective.
* matmuls are row-block stable: block ``j`` of ``concat(shards) @ w``
  equals ``shard_j @ w`` bit-for-bit, so the ring's per-step partial
  matmuls reproduce the monolithic product exactly.
* gathers are pure data movement and cannot perturb bits.

Bidirectional (two-stream) variants split the ring into clockwise and
counter-clockwise halves for even axis sizes — each shard travels at most
``n/2`` hops instead of ``n-1``, halving ring latency on bidirectional ICI
links. The buffered ordered summation makes the result independent of the
streaming direction, so uni/bidi are bit-identical too.

Quantized wire format (activation-collective compression)
----------------------------------------------------------
Every primitive takes an optional ``wire`` :class:`CompressionConfig`
(frozen/hashable → a static ``custom_vjp`` nondiff arg, never a
recompile). When quantized, the ring payloads — gathered shards in the AG
ring, per-destination partial blocks in the RS ring, and the cotangent
rings of every backward dual — ship as blockwise int8/fp8 values plus
per-block fp32 scales (the shared :mod:`..parallel.wire_codec`, the same
quantizer the gradient collectives use). Payloads keep their original
tensor layout (``encode_payload``: trailing-dim blocks, no flattening), so
block boundaries land at identical trailing-dim offsets in the decomposed
ring and the quantized monolithic fallback, making the two *bitwise*
equal: each source's contribution is ``DQ(Q(p))`` either way, and the
reduce-scatter's ascending-rank accumulation happens in the dequantized
domain in both. ``wire=None`` (or an fp32 config) leaves every code path
byte-identical to the uncompressed module. Cross-step error-feedback
residue for the gathered activation payload threads through
``all_gather_matmul(..., error=)`` exactly like the gradient collectives'
``comm_error`` (see docs/comm_compression.md).

Fallback
--------
Decomposition needs the scattered/pipelined dim to tile evenly over the
axis (and a gather/scatter dim distinct from the contraction dim). When it
doesn't — e.g. the serving engine's single-token decode steps — every
entry point silently falls back to the monolithic path instead of raising;
``will_decompose`` exposes the decision for tests and benchmarks. With a
quantized ``wire`` the monolithic fallbacks stay compressed (codec-encoded
gather / all-to-all reduce-scatter / flat quantized all-reduce) whenever
the shape allows, and silently stay full-precision otherwise — never an
error, never a recompile. The layer-level auto knob (``overlap_comm=None``)
additionally requires the axis size to be ≥ ``MIN_AUTO_AXIS_SIZE`` — below
that a ring is all latency and no pipelining.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import comm_compressed
from ..parallel import mesh as ps
from ..parallel.wire_codec import (CompressionConfig, decode_payload,
                                   encode_payload, payload_wire_bytes)

Array = jax.Array
Kernels = Union[Array, Sequence[Array]]


def _record_act_wire(kind: str, shape: Tuple[int, ...],
                     wire: Optional[CompressionConfig],
                     passes: float) -> None:
    """Traced-bytes accounting for one activation collective: ``shape``
    is the per-hop payload, ``passes`` the number of ring hops (or
    monolithic-equivalent passes). Runs in the public wrapper at trace
    time — never inside the compiled program (the custom_vjp internals
    are traced code; a tap there would be flagged by nxdlint and would
    double-count the per-chunk codec calls)."""
    from ..obs.accounting import record_wire_bytes
    from ..obs.metrics import get_registry

    if not get_registry().enabled:
        return
    m = 1
    for d in shape:
        m *= int(d)
    wire_b = payload_wire_bytes(shape, wire) * passes
    raw_b = 4.0 * m * passes
    record_wire_bytes(kind, wire.dtype if wire is not None else "fp32",
                      wire_b, raw_b)

#: auto mode (``overlap_comm=None``) engages only at axis sizes where the
#: ring has enough steps to pipeline; below this the monolithic collective
#: is at least as good.
MIN_AUTO_AXIS_SIZE = 4

_IMPLS = ("auto", "decomposed", "monolithic")


# ---------------------------------------------------------------------------
# shape/impl resolution
# ---------------------------------------------------------------------------

def _norm_dim(dim: int, ndim: int) -> int:
    return dim % ndim


def _dim_ok(shape: Tuple[int, ...], dim: int) -> bool:
    """The streamed dim must exist and precede the (last) contraction dim."""
    if len(shape) < 2:
        return False
    return _norm_dim(dim, len(shape)) < len(shape) - 1


def shapes_tile(x_shape: Tuple[int, ...], dim: int,
                axis_size: Optional[int], *,
                needs_divisible: bool) -> bool:
    """Pure shape-tiling predicate behind :func:`will_decompose` /
    :func:`overlap_engaged`.

    True when a ring of ``axis_size`` steps can stream ``x`` along ``dim``:
    the dim must exist and precede the (last) contraction dim, and — for the
    scatter/delivery forms (``needs_divisible=True``) — tile evenly over the
    axis. Takes the axis SIZE, not an axis name, so callers that have no
    bound mesh axis (the placement planner, the ``plan`` lint rule) share
    this exact rule instead of duplicating it. ``axis_size`` of None (axis
    unbound) or ≤ 1 never tiles.
    """
    if axis_size is None or axis_size <= 1:
        return False
    if not _dim_ok(tuple(x_shape), dim):
        return False
    if needs_divisible and x_shape[_norm_dim(dim, len(x_shape))] % axis_size:
        return False
    return True


def will_decompose(impl: str, axis, x_shape: Tuple[int, ...], dim: int,
                   *, needs_divisible: bool) -> bool:
    """Whether the decomposed ring will actually run for this call.

    False means the monolithic path is used — never an error. Mirrors the
    in-op resolution so tests/bench can assert engagement.
    """
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {_IMPLS}, got {impl!r}")
    if impl == "monolithic":
        return False
    return shapes_tile(x_shape, dim, comm._axis_size(axis),
                       needs_divisible=needs_divisible)


def _resolve_bidi(bidirectional: Optional[bool], n: int) -> bool:
    """Two-stream ring only for even axis sizes (auto: even and ≥ 4)."""
    if bidirectional is None:
        return n % 2 == 0 and n >= 4
    return bool(bidirectional) and n % 2 == 0


def overlap_engaged(overlap_comm: Optional[bool], axis,
                    x_shape: Tuple[int, ...], dim: int, *,
                    needs_divisible: bool) -> bool:
    """Layer-level engagement decision for the ``overlap_comm`` knob.

    ``None`` (auto): on when the axis is bound with size ≥
    ``MIN_AUTO_AXIS_SIZE`` and the shapes tile; ``True``: on whenever the
    shapes tile (never an error — non-tileable shapes fall back);
    ``False``: off.
    """
    if overlap_comm is False:
        return False
    if not will_decompose("decomposed", axis, x_shape, dim,
                          needs_divisible=needs_divisible):
        return False
    if overlap_comm is None:
        n = comm._axis_size(axis)
        return n is not None and n >= MIN_AUTO_AXIS_SIZE
    return True


# ---------------------------------------------------------------------------
# wire compression + reduced-sync knobs
# ---------------------------------------------------------------------------

def wire_config(dtype: Optional[str],
                block_size: int = 256) -> Optional[CompressionConfig]:
    """Activation-wire config for the ``wire=`` argument of every primitive
    here: None (no compression) for ``None``/``"fp32"``, else a hashable
    :class:`CompressionConfig` (``hierarchical``/``error_feedback`` are
    gradient-side concepts and stay off)."""
    if not dtype or dtype == "fp32":
        return None
    return CompressionConfig(dtype=dtype, block_size=int(block_size),
                             hierarchical=False, error_feedback=False)


def _norm_wire(wire: Optional[CompressionConfig]
               ) -> Optional[CompressionConfig]:
    return wire if (wire is not None and wire.quantized) else None


def tp_sync_schedule(num_layers: int,
                     sync_fraction: float) -> Tuple[bool, ...]:
    """Static per-layer schedule for reduced-sync TP (PAPERS.md
    "Tensor-Parallelism with Partially Synchronized Activations").

    ``sync_fraction`` ∈ (0, 1] is the fraction of decoder layers whose
    row-parallel exits run the full all-reduce; the rest elide it (each
    rank keeps its local partial product) and are compensated by the
    periodic residual resync the model inserts before every synced layer.
    Entry ``i`` True → layer ``i`` syncs. 1.0 → all layers sync (the
    schedule is the identity and no resync machinery is built). Synced
    layers are evenly spaced with period ``round(1/f)`` and the last layer
    always syncs so the final norm / lm-head see a fully synchronized
    residual stream. Pure and static — the schedule is baked into the
    compiled program, never a traced branch."""
    if not 0.0 < sync_fraction <= 1.0:
        raise ValueError(
            f"activation_sync_fraction must be in (0, 1], got "
            f"{sync_fraction!r}")
    if num_layers <= 0:
        return ()
    if sync_fraction >= 1.0:
        return (True,) * num_layers
    k = max(1, int(round(1.0 / sync_fraction)))
    sched = [(i % k) == (k - 1) for i in range(num_layers)]
    sched[-1] = True
    return tuple(sched)


# ---------------------------------------------------------------------------
# contraction helpers (shared by both impls so the arithmetic is identical)
# ---------------------------------------------------------------------------

def _as_tuple(ws: Kernels) -> Tuple[Array, ...]:
    if isinstance(ws, (tuple, list)):
        return tuple(ws)
    return (ws,)


def _contract(x: Array, w: Array) -> Array:
    """``x [..., K] × w [K, *rest] -> [..., *rest]`` (last-dim contraction,
    the layout every TP linear in this codebase uses)."""
    return jnp.tensordot(x, w, axes=((x.ndim - 1,), (0,)))


def _contract_sum(xs: Sequence[Array], ws: Sequence[Array]) -> Array:
    """``sum_i xs[i] @ ws[i]`` with a fixed left-to-right pair order."""
    out = _contract(xs[0], ws[0])
    for x, w in zip(xs[1:], ws[1:]):
        out = out + _contract(x, w)
    return out


def _ordered_sum(buf: Array, n: int) -> Array:
    """Left-to-right ascending-source-rank summation of a ``[n, ...]``
    contribution buffer. Callers must materialize the contributions into
    ``buf`` via ``dynamic_update_slice`` stores *before* calling: a DUS
    buffer forces the dequantization multiply to be computed to memory, so
    the backend cannot contract it into the accumulation adds as an fma
    (an optimization_barrier alone does NOT stop LLVM's fp contraction on
    CPU). The adds are then pure fp32 adds in program order, bitwise
    identical whichever program (ring or monolithic all-to-all) produced
    the buffer."""
    buf = lax.optimization_barrier(buf)
    acc = buf[0]
    for r in range(1, n):
        acc = acc + buf[r]
    return acc


def _flat_t(w: Array) -> Array:
    """``w [K, *rest] -> w^T [prod(rest), K]`` for the dual contraction."""
    return w.reshape(w.shape[0], -1).T


def _flat_rest(g: Array, w: Array) -> Array:
    """Collapse ``g``'s trailing ``rest`` dims (matching ``w [K, *rest]``)
    to one: ``[..., L, *rest] -> [..., L, R]``."""
    lead = g.ndim - (w.ndim - 1)
    return g.reshape(g.shape[:lead] + (-1,))


def _dkernel(x_full: Array, g: Array, w_shape: Tuple[int, ...]) -> Array:
    """``dw = x_full^T · g`` contracting every leading dim (batch + the
    gathered dim); one flattened matmul, identical for both impls."""
    k = x_full.shape[-1]
    xf = x_full.reshape(-1, k)
    gf = g.reshape(xf.shape[0], -1)
    return jnp.tensordot(xf, gf, axes=((0,), (0,))).reshape(w_shape)


# ---------------------------------------------------------------------------
# decomposed rings
# ---------------------------------------------------------------------------

def _shift_perm(n: int, shift: int):
    """ppermute pairs moving every shard ``shift`` ranks forward."""
    return [(i, (i + shift) % n) for i in range(n)]


def _ship(pair, axis, perm):
    """ppermute a ``(q, scales)`` wire pair one ring step; scales are
    absent (None) on the fp path, which then matches the uncompressed ring
    byte-for-byte."""
    q, s = pair
    q = comm.ppermute(q, axis, perm)
    if s is not None:
        s = comm.ppermute(s, axis, perm)
    return q, s


def _open(pair, wire, dtype):
    """Dequantize a received wire pair back into compute dtype (identity
    on the fp path)."""
    q, s = pair
    return decode_payload(q, s, wire, dtype)


def _quantized_all_gather(v: Array, axis, dim: int,
                          wire: Optional[CompressionConfig]) -> Array:
    """Monolithic all-gather with the payload codec-encoded on the wire.
    Every rank encodes identically and gathers are pure movement, so the
    result equals the ring's ``DQ(Q(shard))`` concatenation bitwise."""
    if wire is None:
        return comm.all_gather(v, axis, dim)
    q, s = encode_payload(v, wire)
    qg = comm.all_gather(q, axis, dim)
    sg = comm.all_gather(s, axis, dim)
    return decode_payload(qg, sg, wire, v.dtype)


def _quantized_all_reduce(v: Array, axis,
                          wire: Optional[CompressionConfig]) -> Array:
    """Monolithic all-reduce fallback: the codec's flat quantized
    all-reduce (works for any shape via block padding); plain ``psum``
    when uncompressed."""
    if wire is None:
        return comm.all_reduce(v, axis)
    return comm_compressed.all_reduce(
        v, axis, config=dataclasses.replace(wire, hierarchical=False),
        op="sum")


def _ag_matmul_decomposed(x: Array, ws: Tuple[Array, ...], axis, dim: int,
                          bidi: bool,
                          wire: Optional[CompressionConfig]
                          ) -> Tuple[Array, ...]:
    """Ring all-gather-matmul: remote shards stream around the ring while
    each step's block matmul (independent of the in-flight transfer) runs.
    With a quantized ``wire`` each rank encodes its shard ONCE and the
    ``(q, scales)`` pair circulates — one quantization per shard total,
    exactly what the monolithic quantized gather ships."""
    n = comm._axis_size(axis)
    idx = lax.axis_index(axis)
    dim = _norm_dim(dim, x.ndim)
    l = x.shape[dim]

    pair = encode_payload(x, wire)
    # the own block round-trips through DQ(Q(·)) too: every rank then
    # contracts identical gathered values, matching the monolithic path
    # bitwise (fp wire: encode/open are identities and this is just x)
    own = _open(pair, wire, x.dtype)

    outs = []
    for w in ws:
        shape = list(x.shape[:-1]) + list(w.shape[1:])
        shape[dim] = n * l
        outs.append(jnp.zeros(tuple(shape), jnp.result_type(own, w)))

    def write(outs, chunk, src):
        return [lax.dynamic_update_slice_in_dim(o, _contract(chunk, w),
                                                src * l, axis=dim)
                for o, w in zip(outs, ws)]

    outs = write(outs, own, idx)  # own block first — no transfer needed
    if not bidi:
        for t in range(1, n):
            # receive the next shard from the right neighbour; the matmul
            # below consumes the *previous* chunk's successor, so transfer
            # t+1 can fly while block t multiplies
            pair = _ship(pair, axis, _shift_perm(n, -1))
            outs = write(outs, _open(pair, wire, x.dtype), (idx + t) % n)
        return tuple(outs)
    fwd = bwd = pair
    for t in range(1, n // 2 + 1):
        fwd = _ship(fwd, axis, _shift_perm(n, -1))
        outs = write(outs, _open(fwd, wire, x.dtype), (idx + t) % n)
        if t != n - t:  # at t == n/2 both streams carry the same shard
            bwd = _ship(bwd, axis, _shift_perm(n, +1))
            outs = write(outs, _open(bwd, wire, x.dtype), (idx - t) % n)
    return tuple(outs)


def _ag_matmul_monolithic(x: Array, ws: Tuple[Array, ...], axis, dim: int,
                          wire: Optional[CompressionConfig]
                          ) -> Tuple[Array, ...]:
    xg = _quantized_all_gather(x, axis, _norm_dim(dim, x.ndim), wire)
    return tuple(_contract(xg, w) for w in ws)


def _mm_rs_decomposed(xs: Tuple[Array, ...], ws: Tuple[Array, ...], axis,
                      dim: int, bidi: bool,
                      wire: Optional[CompressionConfig]) -> Array:
    """Ring matmul-reduce-scatter: each destination's partial block is
    computed, shipped straight to its owner (shift-``t`` ppermute — one
    hop's worth of latency per step regardless of distance on a torus),
    buffered by source rank, and summed once left-to-right in ascending
    rank order — the exact addition order of XLA's ``psum_scatter``. With
    a quantized ``wire`` each partial block is encoded before its ppermute
    and the accumulation happens in the dequantized domain, preserving
    that same ascending-rank order."""
    n = comm._axis_size(axis)
    idx = lax.axis_index(axis)
    dim = _norm_dim(dim, xs[0].ndim)
    big = xs[0].shape[dim]
    l = big // n

    def block(j):
        parts = [lax.dynamic_slice_in_dim(x, j * l, l, axis=dim)
                 for x in xs]
        return _contract_sum(parts, ws)

    p_own = block(idx)
    dt = p_own.dtype
    # the own partial round-trips through DQ(Q(·)) like every shipped one,
    # so rank position doesn't change which contributions are exact —
    # identical to the quantized monolithic all-to-all (fp: identity)
    own = _open(encode_payload(p_own, wire), wire, dt)
    buf = jnp.zeros((n,) + own.shape, own.dtype)

    def store(buf, p, src):
        return lax.dynamic_update_slice(
            buf, p[None], (src,) + (0,) * p.ndim)

    buf = store(buf, own, idx)
    if not bidi:
        for t in range(1, n):
            p = encode_payload(block((idx + t) % n), wire)
            p = _ship(p, axis, _shift_perm(n, t))
            buf = store(buf, _open(p, wire, dt), (idx - t) % n)
    else:
        for t in range(1, n // 2 + 1):
            p = encode_payload(block((idx + t) % n), wire)
            p = _ship(p, axis, _shift_perm(n, t))
            buf = store(buf, _open(p, wire, dt), (idx - t) % n)
            if t != n - t:
                q = encode_payload(block((idx - t) % n), wire)
                q = _ship(q, axis, _shift_perm(n, -t))
                buf = store(buf, _open(q, wire, dt), (idx + t) % n)
    return _ordered_sum(buf, n)


def _mm_rs_monolithic(xs: Tuple[Array, ...], ws: Tuple[Array, ...], axis,
                      dim: int,
                      wire: Optional[CompressionConfig]) -> Array:
    y = _contract_sum(list(xs), list(ws))
    dim = _norm_dim(dim, y.ndim)
    if wire is None:
        return comm.reduce_scatter(y, axis, dim)
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    if not names or n is None or n == 1:
        return y
    if y.shape[dim] % n:
        # can't form per-destination blocks; the fp collective has the
        # same divisibility contract and raises the pointed error
        return comm.reduce_scatter(y, axis, dim)
    ax = names if len(names) > 1 else names[0]
    # stack the n destination slices, quantize each (trailing-dim blocks —
    # slicing a non-trailing dim never moves a block boundary, so these
    # are the ring's per-destination partials bit-for-bit), all-to-all the
    # wire pair, and sum the received contributions in ascending source
    # rank order in the dequantized domain: bitwise equal to the ring.
    lead = jnp.moveaxis(y, dim, 0)
    stacked = lead.reshape((n, lead.shape[0] // n) + lead.shape[1:])
    q, s = encode_payload(stacked, wire)
    qr = lax.all_to_all(q, ax, split_axis=0, concat_axis=0, tiled=True)
    sr = lax.all_to_all(s, ax, split_axis=0, concat_axis=0, tiled=True)
    dq = decode_payload(qr, sr, wire, y.dtype)
    # Materialize each source's contribution into the ring's contribution
    # buffer (output layout, dynamic_update_slice per source) before the
    # ordered sum. The DUS buffer forces the dequantize multiply to
    # materialize, so XLA cannot contract it into the accumulation adds as
    # an fma here while leaving the ring's adds uncontracted — both
    # programs then perform identical mul-then-add arithmetic.
    first = jnp.moveaxis(dq[0], 0, dim)
    buf = jnp.zeros((n,) + first.shape, first.dtype)
    for r in range(n):
        piece = first if r == 0 else jnp.moveaxis(dq[r], 0, dim)
        buf = lax.dynamic_update_slice(
            buf, piece[None], (r,) + (0,) * piece.ndim)
    return _ordered_sum(buf, n)


def _mm_rs_impl(xs, ws, axis, dim, decomposed, bidi, wire):
    if decomposed:
        return _mm_rs_decomposed(xs, ws, axis, dim, bidi, wire)
    return _mm_rs_monolithic(xs, ws, axis, dim, wire)


def _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi, wire):
    if decomposed:
        return _ag_matmul_decomposed(x, ws, axis, dim, bidi, wire)
    return _ag_matmul_monolithic(x, ws, axis, dim, wire)


# ---------------------------------------------------------------------------
# custom_vjp primitives (dual decomposition in the backward)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _ag_matmul(x, ws, axis, dim, decomposed, bidi, wire):
    return _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi, wire)


def _ag_matmul_fwd(x, ws, axis, dim, decomposed, bidi, wire):
    return _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi, wire), (x, ws)


def _ag_matmul_bwd(axis, dim, decomposed, bidi, wire, res, gs):
    x, ws = res
    # dx: the dual — partial input-grads reduce-scattered back onto the
    # gathered dim, overlapped (and wire-quantized) when the forward was
    g2s = tuple(_flat_rest(g, w) for g, w in zip(gs, ws))
    wts = tuple(_flat_t(w) for w in ws)
    dx = _mm_rs_impl(g2s, wts, axis, dim, decomposed, bidi, wire)
    dx = dx.astype(x.dtype)
    # dw: needs the gathered input; quantized, the re-gather reconstructs
    # the same DQ(Q(x)) the forward contracted, so dw differentiates the
    # function the forward actually computed
    x_full = _quantized_all_gather(x, axis, _norm_dim(dim, x.ndim), wire)
    dws = tuple(_dkernel(x_full, g, w.shape).astype(w.dtype)
                for g, w in zip(gs, ws))
    return dx, dws


_ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _mm_rs(x, w, axis, dim, decomposed, bidi, wire):
    return _mm_rs_impl((x,), (w,), axis, dim, decomposed, bidi, wire)


def _mm_rs_fwd(x, w, axis, dim, decomposed, bidi, wire):
    return _mm_rs_impl((x,), (w,), axis, dim, decomposed, bidi, wire), (x, w)


def _mm_rs_bwd(axis, dim, decomposed, bidi, wire, res, g):
    x, w = res
    # dx: all-gather-matmul of the scattered cotangent against w^T (the
    # cotangent payload rides the same quantized wire — straight-through
    # w.r.t. the forward's quantizer, see docs/tp_overlap.md)
    g2 = _flat_rest(g, w)
    (dx,) = _ag_matmul_impl(g2, (_flat_t(w),), axis, dim, decomposed, bidi,
                            wire)
    dx = dx.astype(x.dtype)
    g_full = _quantized_all_gather(g, axis, _norm_dim(dim, g.ndim), wire)
    dw = _dkernel(x, g_full, w.shape).astype(w.dtype)
    return dx, dw


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _mm_ar(x, w, axis, dim, decomposed, bidi, wire):
    if decomposed:
        y = _mm_rs_decomposed((x,), (w,), axis, dim, bidi, wire)
        return _quantized_all_gather(y, axis, _norm_dim(dim, y.ndim), wire)
    return _quantized_all_reduce(_contract(x, w), axis, wire)


def _mm_ar_fwd(x, w, axis, dim, decomposed, bidi, wire):
    return _mm_ar(x, w, axis, dim, decomposed, bidi, wire), (x, w)


def _mm_ar_bwd(axis, dim, decomposed, bidi, wire, res, g):
    x, w = res
    # the all-reduce's cotangent is replicated: dx needs no collective
    # (identical formula both impls — cf. reduce_from_tensor_parallel_region
    # whose backward is the identity)
    dx = _contract(_flat_rest(g, w), _flat_t(w)).astype(x.dtype)
    dw = _dkernel(x, g, w.shape).astype(w.dtype)
    return dx, dw


_mm_ar.defvjp(_mm_ar_fwd, _mm_ar_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _copy_mm(x, ws, axis, dim, decomposed, bidi, wire):
    return tuple(_contract(x, w) for w in ws)


def _copy_mm_fwd(x, ws, axis, dim, decomposed, bidi, wire):
    return tuple(_contract(x, w) for w in ws), (x, ws)


def _copy_mm_bwd(axis, dim, decomposed, bidi, wire, res, gs):
    x, ws = res
    # dx = psum(sum_i g_i w_i^T): decomposed as reduce-scatter (overlapped
    # with the per-block matmuls) + all-gather, cotangents wire-quantized
    g2s = tuple(_flat_rest(g, w) for g, w in zip(gs, ws))
    wts = tuple(_flat_t(w) for w in ws)
    if decomposed:
        dx = _mm_rs_decomposed(g2s, wts, axis, dim, bidi, wire)
        dx = _quantized_all_gather(dx, axis, _norm_dim(dim, dx.ndim), wire)
    else:
        dx = _quantized_all_reduce(_contract_sum(g2s, wts), axis, wire)
    dx = dx.astype(x.dtype)
    # kernels are axis-sharded: dw is local (x is replicated)
    dws = tuple(_dkernel(x, g, w.shape).astype(w.dtype)
                for g, w in zip(gs, ws))
    return dx, dws


_copy_mm.defvjp(_copy_mm_fwd, _copy_mm_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _prep(impl: str, axis, x: Array, dim: int, needs_divisible: bool,
          bidirectional: Optional[bool]):
    decomposed = will_decompose(impl, axis, x.shape, dim,
                                needs_divisible=needs_divisible)
    n = comm._axis_size(axis) or 1
    return decomposed, (_resolve_bidi(bidirectional, n) if decomposed
                        else False)


def _unwrap(outs: Tuple[Array, ...], kernels: Kernels):
    if isinstance(kernels, (tuple, list)):
        return outs
    return outs[0]


def _scatter_block_shape(x: Array, kernel: Array, dim: int,
                         n: int) -> Tuple[int, ...]:
    """Per-hop payload shape of a matmul-RS/AR: the output block destined
    for one rank — ``x @ kernel``'s shape with ``dim`` cut by ``n``."""
    y_shape = tuple(x.shape[:-1]) + tuple(kernel.shape[1:])
    d = dim % len(y_shape)
    return tuple(max(1, s // n) if i == d else s
                 for i, s in enumerate(y_shape))


def all_gather_matmul(x: Array, kernels: Kernels, axis=ps.TP_AXIS,
                      gather_dim: int = 1, *, impl: str = "auto",
                      bidirectional: Optional[bool] = None,
                      wire: Optional[CompressionConfig] = None,
                      error: Optional[Array] = None):
    """``all_gather(x, gather_dim) @ w`` for one kernel or a fused tuple
    (e.g. Q/K/V share one gathered stream), decomposed into a ppermute
    ring. ``x [..., gather_dim: l_local, ..., K]``, each kernel
    ``[K, *rest]``; returns ``[..., n*l_local, ..., *rest]`` per kernel.

    The sequence-parallel entry of a column-parallel linear. Backward:
    ``dx`` is a (decomposed) matmul-reduce-scatter, ``dw`` a re-gather +
    single flattened matmul. A quantized ``wire`` codec-encodes the ring
    payloads (fwd shards AND bwd cotangents).

    ``error`` threads cross-step error feedback for the gathered payload —
    the same contract as the gradient collectives' ``comm_error``: pass
    last step's residue buffer (``x``'s shape, fp32) and the return becomes
    ``(out, new_error)`` where ``new_error = (x + e) − DQ(Q(x + e))``.
    The residue is stop-gradiented state, not a differentiable input.
    """
    ws = _as_tuple(kernels)
    wire = _norm_wire(wire)
    decomposed, bidi = _prep(impl, axis, x, gather_dim, False, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        out = _unwrap(tuple(_contract(x, w) for w in ws), kernels)
        return (out, error) if error is not None else out
    new_error = None
    if error is not None:
        if wire is None:
            new_error = jnp.zeros_like(error)
        else:
            x = x + lax.stop_gradient(error).astype(x.dtype)
            q, s = encode_payload(lax.stop_gradient(x), wire)
            dq = decode_payload(q, s, wire, jnp.float32)
            new_error = lax.stop_gradient(
                x.astype(jnp.float32) - dq).astype(error.dtype)
    # ring: each rank's shard takes n-1 hops (monolithic AG moves the same)
    _record_act_wire("act_all_gather_matmul", tuple(x.shape), wire, n - 1)
    out = _unwrap(_ag_matmul(x, ws, axis, gather_dim, decomposed, bidi,
                             wire), kernels)
    return (out, new_error) if error is not None else out


def matmul_reduce_scatter(x: Array, kernel: Array, axis=ps.TP_AXIS,
                          scatter_dim: int = 1, *, impl: str = "auto",
                          bidirectional: Optional[bool] = None,
                          wire: Optional[CompressionConfig] = None) -> Array:
    """``reduce_scatter(x @ kernel, scatter_dim)`` decomposed so each
    destination's partial block ships while the next block multiplies.

    The sequence-parallel exit of a row-parallel linear. Requires
    ``x.shape[scatter_dim] % axis_size == 0`` to decompose; falls back to
    the monolithic collective otherwise (never an error). A quantized
    ``wire`` encodes each partial block before its ppermute (or the
    all-to-all fallback) — accumulation stays in the dequantized domain in
    ascending rank order.
    """
    wire = _norm_wire(wire)
    decomposed, bidi = _prep(impl, axis, x, scatter_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _contract(x, kernel)
    _record_act_wire("act_matmul_reduce_scatter",
                     _scatter_block_shape(x, kernel, scatter_dim, n),
                     wire, n - 1)
    return _mm_rs(x, kernel, axis, scatter_dim, decomposed, bidi, wire)


def matmul_all_reduce(x: Array, kernel: Array, axis=ps.TP_AXIS,
                      pipeline_dim: int = 1, *, impl: str = "auto",
                      bidirectional: Optional[bool] = None,
                      wire: Optional[CompressionConfig] = None) -> Array:
    """``all_reduce(x @ kernel)`` decomposed as matmul-reduce-scatter over
    ``pipeline_dim`` (overlapped) followed by an all-gather (movement).

    The plain-TP exit of a row-parallel linear. A quantized ``wire``
    compresses both legs when decomposed, and falls back to the codec's
    flat quantized all-reduce monolithically (any shape — the serving
    engine's single-token decode steps stay compressed AND compile-once).
    """
    wire = _norm_wire(wire)
    decomposed, bidi = _prep(impl, axis, x, pipeline_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _contract(x, kernel)
    # RS leg + AG leg, each n-1 hops over the same per-destination block
    _record_act_wire("act_matmul_all_reduce",
                     _scatter_block_shape(x, kernel, pipeline_dim, n),
                     wire, 2 * (n - 1))
    return _mm_ar(x, kernel, axis, pipeline_dim, decomposed, bidi, wire)


def copy_matmul(x: Array, kernels: Kernels, axis=ps.TP_AXIS,
                pipeline_dim: int = 1, *, impl: str = "auto",
                bidirectional: Optional[bool] = None,
                wire: Optional[CompressionConfig] = None):
    """Plain-TP column entry: forward is a local matmul on the replicated
    input (identical for both impls); the *backward* input-grad all-reduce
    is decomposed into overlapped reduce-scatter + all-gather over
    ``pipeline_dim`` (cotangents wire-quantized when ``wire`` is)."""
    ws = _as_tuple(kernels)
    wire = _norm_wire(wire)
    decomposed, bidi = _prep(impl, axis, x, pipeline_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _unwrap(tuple(_contract(x, w) for w in ws), kernels)
    return _unwrap(_copy_mm(x, ws, axis, pipeline_dim, decomposed, bidi,
                            wire), kernels)
