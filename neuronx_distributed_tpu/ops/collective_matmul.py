"""Decomposed (latency-hiding) tensor-parallel collective-matmuls.

The TP layers' hot path interleaves matmuls with collectives: the
sequence-parallel entry all-gathers activations before the column matmul,
and the row matmul exits through a reduce-scatter (or, in plain TP, an
all-reduce). Issued monolithically those collectives serialize with the
compute they feed — the wire sits idle during the matmul and the MXU sits
idle during the collective. This module decomposes each pair into a
``ppermute`` ring that streams remote shards (or partial products) while
each step's partial matmul runs, so XLA can overlap the per-step transfer
with the independent per-step compute (the reference hides the same
latency with hand-scheduled async all-reduce in
``LinearWithAsyncCommunication``, ``parallel_layers/layers.py:434-504``;
see also PAPERS.md on multi-node comm/compute overlap).

Four primitives, each a ``custom_vjp`` whose backward uses the *dual*
decomposition (grad of an all-gather-matmul is a matmul-reduce-scatter and
vice versa):

======================  ===========================  =======================
op                      forward                      backward (dx)
======================  ===========================  =======================
all_gather_matmul       AG(x, dim) @ w  (ring)       matmul_reduce_scatter
matmul_reduce_scatter   RS(x @ w, dim)  (ring)       all_gather_matmul
matmul_all_reduce       AR(x @ w) = AG(RS(x @ w))    x-free: g @ w^T
copy_matmul             x @ w (x replicated)         AR(g @ w^T) = AG(RS(.))
======================  ===========================  =======================

Bit-exactness contract
----------------------
``impl="decomposed"`` and ``impl="monolithic"`` are bit-identical in fp32
(fwd AND grad), by construction rather than by tolerance:

* XLA accumulates ``psum`` / ``psum_scatter`` contributions left-to-right
  in ascending rank order, so the decomposed reduce-scatter delivers each
  partial block directly to its destination (per-step shifted ppermutes),
  buffers them by *source rank*, and performs one ordered left-to-right
  summation — the same additions in the same order as the monolithic
  collective.
* matmuls are row-block stable: block ``j`` of ``concat(shards) @ w``
  equals ``shard_j @ w`` bit-for-bit, so the ring's per-step partial
  matmuls reproduce the monolithic product exactly.
* gathers are pure data movement and cannot perturb bits.

Bidirectional (two-stream) variants split the ring into clockwise and
counter-clockwise halves for even axis sizes — each shard travels at most
``n/2`` hops instead of ``n-1``, halving ring latency on bidirectional ICI
links. The buffered ordered summation makes the result independent of the
streaming direction, so uni/bidi are bit-identical too.

Fallback
--------
Decomposition needs the scattered/pipelined dim to tile evenly over the
axis (and a gather/scatter dim distinct from the contraction dim). When it
doesn't — e.g. the serving engine's single-token decode steps — every
entry point silently falls back to the monolithic path instead of raising;
``will_decompose`` exposes the decision for tests and benchmarks. The
layer-level auto knob (``overlap_comm=None``) additionally requires the
axis size to be ≥ ``MIN_AUTO_AXIS_SIZE`` — below that a ring is all
latency and no pipelining.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import comm
from ..parallel import mesh as ps

Array = jax.Array
Kernels = Union[Array, Sequence[Array]]

#: auto mode (``overlap_comm=None``) engages only at axis sizes where the
#: ring has enough steps to pipeline; below this the monolithic collective
#: is at least as good.
MIN_AUTO_AXIS_SIZE = 4

_IMPLS = ("auto", "decomposed", "monolithic")


# ---------------------------------------------------------------------------
# shape/impl resolution
# ---------------------------------------------------------------------------

def _norm_dim(dim: int, ndim: int) -> int:
    return dim % ndim


def _dim_ok(shape: Tuple[int, ...], dim: int) -> bool:
    """The streamed dim must exist and precede the (last) contraction dim."""
    if len(shape) < 2:
        return False
    return _norm_dim(dim, len(shape)) < len(shape) - 1


def shapes_tile(x_shape: Tuple[int, ...], dim: int,
                axis_size: Optional[int], *,
                needs_divisible: bool) -> bool:
    """Pure shape-tiling predicate behind :func:`will_decompose` /
    :func:`overlap_engaged`.

    True when a ring of ``axis_size`` steps can stream ``x`` along ``dim``:
    the dim must exist and precede the (last) contraction dim, and — for the
    scatter/delivery forms (``needs_divisible=True``) — tile evenly over the
    axis. Takes the axis SIZE, not an axis name, so callers that have no
    bound mesh axis (the placement planner, the ``plan`` lint rule) share
    this exact rule instead of duplicating it. ``axis_size`` of None (axis
    unbound) or ≤ 1 never tiles.
    """
    if axis_size is None or axis_size <= 1:
        return False
    if not _dim_ok(tuple(x_shape), dim):
        return False
    if needs_divisible and x_shape[_norm_dim(dim, len(x_shape))] % axis_size:
        return False
    return True


def will_decompose(impl: str, axis, x_shape: Tuple[int, ...], dim: int,
                   *, needs_divisible: bool) -> bool:
    """Whether the decomposed ring will actually run for this call.

    False means the monolithic path is used — never an error. Mirrors the
    in-op resolution so tests/bench can assert engagement.
    """
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {_IMPLS}, got {impl!r}")
    if impl == "monolithic":
        return False
    return shapes_tile(x_shape, dim, comm._axis_size(axis),
                       needs_divisible=needs_divisible)


def _resolve_bidi(bidirectional: Optional[bool], n: int) -> bool:
    """Two-stream ring only for even axis sizes (auto: even and ≥ 4)."""
    if bidirectional is None:
        return n % 2 == 0 and n >= 4
    return bool(bidirectional) and n % 2 == 0


def overlap_engaged(overlap_comm: Optional[bool], axis,
                    x_shape: Tuple[int, ...], dim: int, *,
                    needs_divisible: bool) -> bool:
    """Layer-level engagement decision for the ``overlap_comm`` knob.

    ``None`` (auto): on when the axis is bound with size ≥
    ``MIN_AUTO_AXIS_SIZE`` and the shapes tile; ``True``: on whenever the
    shapes tile (never an error — non-tileable shapes fall back);
    ``False``: off.
    """
    if overlap_comm is False:
        return False
    if not will_decompose("decomposed", axis, x_shape, dim,
                          needs_divisible=needs_divisible):
        return False
    if overlap_comm is None:
        n = comm._axis_size(axis)
        return n is not None and n >= MIN_AUTO_AXIS_SIZE
    return True


# ---------------------------------------------------------------------------
# contraction helpers (shared by both impls so the arithmetic is identical)
# ---------------------------------------------------------------------------

def _as_tuple(ws: Kernels) -> Tuple[Array, ...]:
    if isinstance(ws, (tuple, list)):
        return tuple(ws)
    return (ws,)


def _contract(x: Array, w: Array) -> Array:
    """``x [..., K] × w [K, *rest] -> [..., *rest]`` (last-dim contraction,
    the layout every TP linear in this codebase uses)."""
    return jnp.tensordot(x, w, axes=((x.ndim - 1,), (0,)))


def _contract_sum(xs: Sequence[Array], ws: Sequence[Array]) -> Array:
    """``sum_i xs[i] @ ws[i]`` with a fixed left-to-right pair order."""
    out = _contract(xs[0], ws[0])
    for x, w in zip(xs[1:], ws[1:]):
        out = out + _contract(x, w)
    return out


def _flat_t(w: Array) -> Array:
    """``w [K, *rest] -> w^T [prod(rest), K]`` for the dual contraction."""
    return w.reshape(w.shape[0], -1).T


def _flat_rest(g: Array, w: Array) -> Array:
    """Collapse ``g``'s trailing ``rest`` dims (matching ``w [K, *rest]``)
    to one: ``[..., L, *rest] -> [..., L, R]``."""
    lead = g.ndim - (w.ndim - 1)
    return g.reshape(g.shape[:lead] + (-1,))


def _dkernel(x_full: Array, g: Array, w_shape: Tuple[int, ...]) -> Array:
    """``dw = x_full^T · g`` contracting every leading dim (batch + the
    gathered dim); one flattened matmul, identical for both impls."""
    k = x_full.shape[-1]
    xf = x_full.reshape(-1, k)
    gf = g.reshape(xf.shape[0], -1)
    return jnp.tensordot(xf, gf, axes=((0,), (0,))).reshape(w_shape)


# ---------------------------------------------------------------------------
# decomposed rings
# ---------------------------------------------------------------------------

def _shift_perm(n: int, shift: int):
    """ppermute pairs moving every shard ``shift`` ranks forward."""
    return [(i, (i + shift) % n) for i in range(n)]


def _ag_matmul_decomposed(x: Array, ws: Tuple[Array, ...], axis, dim: int,
                          bidi: bool) -> Tuple[Array, ...]:
    """Ring all-gather-matmul: remote shards stream around the ring while
    each step's block matmul (independent of the in-flight transfer) runs."""
    n = comm._axis_size(axis)
    idx = lax.axis_index(axis)
    dim = _norm_dim(dim, x.ndim)
    l = x.shape[dim]

    outs = []
    for w in ws:
        shape = list(x.shape[:-1]) + list(w.shape[1:])
        shape[dim] = n * l
        outs.append(jnp.zeros(tuple(shape), jnp.result_type(x, w)))

    def write(outs, chunk, src):
        return [lax.dynamic_update_slice_in_dim(o, _contract(chunk, w),
                                                src * l, axis=dim)
                for o, w in zip(outs, ws)]

    outs = write(outs, x, idx)  # own block first — no transfer needed
    if not bidi:
        chunk = x
        for t in range(1, n):
            # receive the next shard from the right neighbour; the matmul
            # below consumes the *previous* chunk's successor, so transfer
            # t+1 can fly while block t multiplies
            chunk = comm.ppermute(chunk, axis, _shift_perm(n, -1))
            outs = write(outs, chunk, (idx + t) % n)
        return tuple(outs)
    fwd = bwd = x
    for t in range(1, n // 2 + 1):
        fwd = comm.ppermute(fwd, axis, _shift_perm(n, -1))
        outs = write(outs, fwd, (idx + t) % n)
        if t != n - t:  # at t == n/2 both streams carry the same shard
            bwd = comm.ppermute(bwd, axis, _shift_perm(n, +1))
            outs = write(outs, bwd, (idx - t) % n)
    return tuple(outs)


def _ag_matmul_monolithic(x: Array, ws: Tuple[Array, ...], axis,
                          dim: int) -> Tuple[Array, ...]:
    xg = comm.all_gather(x, axis, dim)
    return tuple(_contract(xg, w) for w in ws)


def _mm_rs_decomposed(xs: Tuple[Array, ...], ws: Tuple[Array, ...], axis,
                      dim: int, bidi: bool) -> Array:
    """Ring matmul-reduce-scatter: each destination's partial block is
    computed, shipped straight to its owner (shift-``t`` ppermute — one
    hop's worth of latency per step regardless of distance on a torus),
    buffered by source rank, and summed once left-to-right in ascending
    rank order — the exact addition order of XLA's ``psum_scatter``."""
    n = comm._axis_size(axis)
    idx = lax.axis_index(axis)
    dim = _norm_dim(dim, xs[0].ndim)
    big = xs[0].shape[dim]
    l = big // n

    def block(j):
        parts = [lax.dynamic_slice_in_dim(x, j * l, l, axis=dim)
                 for x in xs]
        return _contract_sum(parts, ws)

    own = block(idx)
    buf = jnp.zeros((n,) + own.shape, own.dtype)

    def store(buf, p, src):
        return lax.dynamic_update_slice(
            buf, p[None], (src,) + (0,) * p.ndim)

    buf = store(buf, own, idx)
    if not bidi:
        for t in range(1, n):
            p = block((idx + t) % n)
            p = comm.ppermute(p, axis, _shift_perm(n, t))
            buf = store(buf, p, (idx - t) % n)
    else:
        for t in range(1, n // 2 + 1):
            p = block((idx + t) % n)
            p = comm.ppermute(p, axis, _shift_perm(n, t))
            buf = store(buf, p, (idx - t) % n)
            if t != n - t:
                q = block((idx - t) % n)
                q = comm.ppermute(q, axis, _shift_perm(n, -t))
                buf = store(buf, q, (idx + t) % n)
    acc = buf[0]
    for r in range(1, n):  # ascending source rank, left-to-right
        acc = acc + buf[r]
    return acc


def _mm_rs_monolithic(xs: Tuple[Array, ...], ws: Tuple[Array, ...], axis,
                      dim: int) -> Array:
    y = _contract_sum(list(xs), list(ws))
    return comm.reduce_scatter(y, axis, _norm_dim(dim, y.ndim))


def _mm_rs_impl(xs, ws, axis, dim, decomposed, bidi):
    if decomposed:
        return _mm_rs_decomposed(xs, ws, axis, dim, bidi)
    return _mm_rs_monolithic(xs, ws, axis, dim)


def _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi):
    if decomposed:
        return _ag_matmul_decomposed(x, ws, axis, dim, bidi)
    return _ag_matmul_monolithic(x, ws, axis, dim)


# ---------------------------------------------------------------------------
# custom_vjp primitives (dual decomposition in the backward)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _ag_matmul(x, ws, axis, dim, decomposed, bidi):
    return _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi)


def _ag_matmul_fwd(x, ws, axis, dim, decomposed, bidi):
    return _ag_matmul_impl(x, ws, axis, dim, decomposed, bidi), (x, ws)


def _ag_matmul_bwd(axis, dim, decomposed, bidi, res, gs):
    x, ws = res
    # dx: the dual — partial input-grads reduce-scattered back onto the
    # gathered dim, overlapped when the forward was
    g2s = tuple(_flat_rest(g, w) for g, w in zip(gs, ws))
    wts = tuple(_flat_t(w) for w in ws)
    dx = _mm_rs_impl(g2s, wts, axis, dim, decomposed, bidi)
    dx = dx.astype(x.dtype)
    # dw: needs the gathered input; re-gathering is pure movement so both
    # impls see identical bits
    x_full = comm.all_gather(x, axis, _norm_dim(dim, x.ndim))
    dws = tuple(_dkernel(x_full, g, w.shape).astype(w.dtype)
                for g, w in zip(gs, ws))
    return dx, dws


_ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _mm_rs(x, w, axis, dim, decomposed, bidi):
    return _mm_rs_impl((x,), (w,), axis, dim, decomposed, bidi)


def _mm_rs_fwd(x, w, axis, dim, decomposed, bidi):
    return _mm_rs_impl((x,), (w,), axis, dim, decomposed, bidi), (x, w)


def _mm_rs_bwd(axis, dim, decomposed, bidi, res, g):
    x, w = res
    # dx: all-gather-matmul of the scattered cotangent against w^T
    g2 = _flat_rest(g, w)
    (dx,) = _ag_matmul_impl(g2, (_flat_t(w),), axis, dim, decomposed, bidi)
    dx = dx.astype(x.dtype)
    g_full = comm.all_gather(g, axis, _norm_dim(dim, g.ndim))
    dw = _dkernel(x, g_full, w.shape).astype(w.dtype)
    return dx, dw


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _mm_ar(x, w, axis, dim, decomposed, bidi):
    if decomposed:
        y = _mm_rs_decomposed((x,), (w,), axis, dim, bidi)
        return comm.all_gather(y, axis, _norm_dim(dim, y.ndim))
    return comm.all_reduce(_contract(x, w), axis)


def _mm_ar_fwd(x, w, axis, dim, decomposed, bidi):
    return _mm_ar(x, w, axis, dim, decomposed, bidi), (x, w)


def _mm_ar_bwd(axis, dim, decomposed, bidi, res, g):
    x, w = res
    # the all-reduce's cotangent is replicated: dx needs no collective
    # (identical formula both impls — cf. reduce_from_tensor_parallel_region
    # whose backward is the identity)
    dx = _contract(_flat_rest(g, w), _flat_t(w)).astype(x.dtype)
    dw = _dkernel(x, g, w.shape).astype(w.dtype)
    return dx, dw


_mm_ar.defvjp(_mm_ar_fwd, _mm_ar_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _copy_mm(x, ws, axis, dim, decomposed, bidi):
    return tuple(_contract(x, w) for w in ws)


def _copy_mm_fwd(x, ws, axis, dim, decomposed, bidi):
    return tuple(_contract(x, w) for w in ws), (x, ws)


def _copy_mm_bwd(axis, dim, decomposed, bidi, res, gs):
    x, ws = res
    # dx = psum(sum_i g_i w_i^T): decomposed as reduce-scatter (overlapped
    # with the per-block matmuls) + all-gather
    g2s = tuple(_flat_rest(g, w) for g, w in zip(gs, ws))
    wts = tuple(_flat_t(w) for w in ws)
    if decomposed:
        dx = _mm_rs_decomposed(g2s, wts, axis, dim, bidi)
        dx = comm.all_gather(dx, axis, _norm_dim(dim, dx.ndim))
    else:
        dx = comm.all_reduce(_contract_sum(g2s, wts), axis)
    dx = dx.astype(x.dtype)
    # kernels are axis-sharded: dw is local (x is replicated)
    dws = tuple(_dkernel(x, g, w.shape).astype(w.dtype)
                for g, w in zip(gs, ws))
    return dx, dws


_copy_mm.defvjp(_copy_mm_fwd, _copy_mm_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _prep(impl: str, axis, x: Array, dim: int, needs_divisible: bool,
          bidirectional: Optional[bool]):
    decomposed = will_decompose(impl, axis, x.shape, dim,
                                needs_divisible=needs_divisible)
    n = comm._axis_size(axis) or 1
    return decomposed, (_resolve_bidi(bidirectional, n) if decomposed
                        else False)


def _unwrap(outs: Tuple[Array, ...], kernels: Kernels):
    if isinstance(kernels, (tuple, list)):
        return outs
    return outs[0]


def all_gather_matmul(x: Array, kernels: Kernels, axis=ps.TP_AXIS,
                      gather_dim: int = 1, *, impl: str = "auto",
                      bidirectional: Optional[bool] = None):
    """``all_gather(x, gather_dim) @ w`` for one kernel or a fused tuple
    (e.g. Q/K/V share one gathered stream), decomposed into a ppermute
    ring. ``x [..., gather_dim: l_local, ..., K]``, each kernel
    ``[K, *rest]``; returns ``[..., n*l_local, ..., *rest]`` per kernel.

    The sequence-parallel entry of a column-parallel linear. Backward:
    ``dx`` is a (decomposed) matmul-reduce-scatter, ``dw`` a re-gather +
    single flattened matmul.
    """
    ws = _as_tuple(kernels)
    decomposed, bidi = _prep(impl, axis, x, gather_dim, False, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _unwrap(tuple(_contract(x, w) for w in ws), kernels)
    return _unwrap(_ag_matmul(x, ws, axis, gather_dim, decomposed, bidi),
                   kernels)


def matmul_reduce_scatter(x: Array, kernel: Array, axis=ps.TP_AXIS,
                          scatter_dim: int = 1, *, impl: str = "auto",
                          bidirectional: Optional[bool] = None) -> Array:
    """``reduce_scatter(x @ kernel, scatter_dim)`` decomposed so each
    destination's partial block ships while the next block multiplies.

    The sequence-parallel exit of a row-parallel linear. Requires
    ``x.shape[scatter_dim] % axis_size == 0`` to decompose; falls back to
    the monolithic collective otherwise (never an error).
    """
    decomposed, bidi = _prep(impl, axis, x, scatter_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _contract(x, kernel)
    return _mm_rs(x, kernel, axis, scatter_dim, decomposed, bidi)


def matmul_all_reduce(x: Array, kernel: Array, axis=ps.TP_AXIS,
                      pipeline_dim: int = 1, *, impl: str = "auto",
                      bidirectional: Optional[bool] = None) -> Array:
    """``all_reduce(x @ kernel)`` decomposed as matmul-reduce-scatter over
    ``pipeline_dim`` (overlapped) followed by an all-gather (movement).

    The plain-TP exit of a row-parallel linear.
    """
    decomposed, bidi = _prep(impl, axis, x, pipeline_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _contract(x, kernel)
    return _mm_ar(x, kernel, axis, pipeline_dim, decomposed, bidi)


def copy_matmul(x: Array, kernels: Kernels, axis=ps.TP_AXIS,
                pipeline_dim: int = 1, *, impl: str = "auto",
                bidirectional: Optional[bool] = None):
    """Plain-TP column entry: forward is a local matmul on the replicated
    input (identical for both impls); the *backward* input-grad all-reduce
    is decomposed into overlapped reduce-scatter + all-gather over
    ``pipeline_dim``."""
    ws = _as_tuple(kernels)
    decomposed, bidi = _prep(impl, axis, x, pipeline_dim, True, bidirectional)
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return _unwrap(tuple(_contract(x, w) for w in ws), kernels)
    return _unwrap(_copy_mm(x, ws, axis, pipeline_dim, decomposed, bidi),
                   kernels)
