"""Shared Mosaic/Pallas configuration for the TPU kernels."""

from __future__ import annotations


def compiler_params():
    """Mosaic params for the compiled TPU path. The default 16 MiB scoped
    VMEM limit rejects 7B-scale tiles (fp32 staging of one (h, 2, block_i)
    weight tile is already ~8 MiB); v5e has 128 MiB physical VMEM."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
