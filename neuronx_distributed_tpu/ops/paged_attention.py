"""Paged decode attention over a shared block pool.

Decode-time attention where K/V live in the paged pool of
:mod:`..inference.paging` (``[num_blocks, block_size, KV, D]`` per layer)
and each query token reads the blocks named by its slot's block table —
the attention half of the vLLM design, on the fixed-shape serving step.

Two implementations behind one signature, following
:mod:`.flash_attention` / :mod:`.flash_decoding`:

* ``_paged_attention_xla`` — pure-``jnp`` gather-based reference. It
  mirrors the contiguous cache path's numerics exactly (same fp32
  einsums, same ``-1e30`` position-sentinel masking), so paged decode is
  bit-for-bit comparable with :func:`..models.llama.llama_forward_with_cache`
  on the contiguous cache; runs everywhere and is the tier-1/CPU path.
* ``_paged_attention_pallas`` — a Mosaic TPU kernel: grid ``(tokens,
  max_blocks_per_seq)``, the block table scalar-prefetched into SMEM so
  each grid step DMAs exactly one pool block into VMEM (online-softmax
  m/l/acc in VMEM scratch). Unmapped table entries clamp to block 0 —
  consecutive same-block DMAs are elided — and are masked in-kernel.

Auto-dispatch picks the kernel on TPU when the shapes tile; CPU runs the
kernel in interpret mode when forced (CI coverage of the mask path).

Both paths are strictly *read-only* over the pool: they gather blocks by
table entry and never scatter back. That is what makes copy-on-write
prefix sharing (:class:`..inference.paging.PrefixCache`) safe — two
tokens' tables may name the same block ids and each still attends to
identical K/V; writers are diverted to private clones by the engine
before the step runs (verified by the shared-table invariance test in
``tests/test_prefix_sharing.py``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..inference.kv_cache import PAD_POSITION, dequantize_kv
from ..modules.attention import repeat_kv
from .pallas_utils import compiler_params as _compiler_params


def _paged_attention_xla(q, k_pool, v_pool, pool_pos, tables, q_pos,
                         k_scale, v_scale, scale, combine_axis=None):
    t, n, d = q.shape
    nb, bs, kv, _ = k_pool.shape
    n_rep = n // kv
    safe = jnp.clip(tables, 0, nb - 1)
    kg = k_pool[safe]                          # [T, maxb, bs, KV, D]
    vg = v_pool[safe]
    pg = pool_pos[safe]                        # [T, maxb, bs]
    # entries gathered through an unmapped (-1) table slot are another
    # sequence's data — force their stored position to the pad sentinel
    pg = jnp.where(tables[:, :, None] >= 0, pg, PAD_POSITION)
    if k_scale is not None:
        kg = dequantize_kv(kg, k_scale[safe], q.dtype)
        vg = dequantize_kv(vg, v_scale[safe], q.dtype)
    length = tables.shape[1] * bs
    k_full = repeat_kv(kg.reshape(t, length, kv, d).astype(q.dtype), n_rep)
    v_full = repeat_kv(vg.reshape(t, length, kv, d).astype(q.dtype), n_rep)
    pg = pg.reshape(t, length)
    scores = jnp.einsum("bqnd,bknd->bnqk", q[:, None].astype(jnp.float32),
                        k_full.astype(jnp.float32)) * scale
    mask = q_pos[:, None, None, None] >= pg[:, None, None, :]
    scores = jnp.where(mask, scores, -1e30)
    if combine_axis is None:
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bnqk,bknd->bqnd", probs,
                         v_full.astype(jnp.float32))
        return out[:, 0].astype(q.dtype)
    # flash-decoding combine over CP-sharded resident blocks: each rank
    # attends its local gather; one pmax + two psums merge the partials
    # (reference combine_kv_on_device, trace/spmd.py:74). The global max
    # makes fully-masked shards (a token with no resident blocks on this
    # rank) contribute exp(-1e30 - m) == 0 rather than a local uniform.
    m = jax.lax.pmax(jnp.max(scores, axis=-1), combine_axis)   # [T,N,1]
    p = jnp.exp(scores - m[..., None])
    l = jax.lax.psum(jnp.sum(p, axis=-1), combine_axis)        # [T,N,1]
    o = jax.lax.psum(
        jnp.einsum("bnqk,bknd->bqnd", p, v_full.astype(jnp.float32)),
        combine_axis)                                          # [T,1,N,D]
    out = o / jnp.maximum(l[..., 0], 1e-30)[:, None, :, None]
    return out[:, 0].astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _paged_kernel(tables_ref, qpos_ref, q_ref, k_ref, v_ref, pos_ref, *rest,
                  num_blocks_per_seq: int, n_rep: int, scale: float,
                  quantized: bool):
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    t = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale           # [N, D]
    k = k_ref[0]                                       # [BS, KV, D]
    v = v_ref[0]
    if quantized:
        k = k.astype(jnp.float32) * ks_ref[0][..., None]
        v = v.astype(jnp.float32) * vs_ref[0][..., None]
    k = jnp.repeat(k.astype(jnp.float32), n_rep, axis=1)   # [BS, N, D]
    v = jnp.repeat(v.astype(jnp.float32), n_rep, axis=1)
    # s[n, slot] = q[n] . k[slot, n] — batch over heads, contract head_dim:
    # lhs [N, D], rhs [N, BS, D] -> [N, BS]
    s = jax.lax.dot_general(q, jnp.swapaxes(k, 0, 1),
                            (((1,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    valid = (qpos_ref[t] >= pos_ref[0]) & (tables_ref[t, j] >= 0)  # [BS]
    s = jnp.where(valid[None, :], s, -jnp.inf)
    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe[:, None]), 0.0)
    corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    m_ref[:] = m_new
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1)
    acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
        p, jnp.swapaxes(v, 0, 1), (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)

    @pl.when(j == num_blocks_per_seq - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pool, v_pool, pool_pos, tables, q_pos,
                            k_scale, v_scale, scale, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, n, d = q.shape
    nb, bs, kv, _ = k_pool.shape
    maxb = tables.shape[1]
    n_rep = n // kv
    quantized = k_scale is not None

    # unmapped (-1) entries clamp to block 0: the DMA is elided when the
    # previous grid step already held it, and the kernel masks the rows
    def blk(ti, j, tables_s, qpos_s):
        return (jnp.maximum(tables_s[ti, j], 0), 0, 0, 0)

    def blk2(ti, j, tables_s, qpos_s):
        return (jnp.maximum(tables_s[ti, j], 0), 0)

    def blk3(ti, j, tables_s, qpos_s):
        return (jnp.maximum(tables_s[ti, j], 0), 0, 0)

    in_specs = [
        pl.BlockSpec((1, n, d), lambda ti, j, *_: (ti, 0, 0)),
        pl.BlockSpec((1, bs, kv, d), blk),
        pl.BlockSpec((1, bs, kv, d), blk),
        pl.BlockSpec((1, bs), blk2),
    ]
    operands = [q, k_pool, v_pool, pool_pos]
    if quantized:
        in_specs += [pl.BlockSpec((1, bs, kv), blk3),
                     pl.BlockSpec((1, bs, kv), blk3)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(t, maxb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n, d), lambda ti, j, *_: (ti, 0, 0)),
        scratch_shapes=[pltpu.VMEM((n,), jnp.float32),
                        pltpu.VMEM((n,), jnp.float32),
                        pltpu.VMEM((n, d), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, num_blocks_per_seq=maxb,
                          n_rep=n_rep, scale=scale, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, n, d), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else _compiler_params(),
    )(tables.astype(jnp.int32), q_pos.astype(jnp.int32), *operands)
    return out


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    pool_pos: jax.Array, tables: jax.Array,
                    q_pos: jax.Array,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None,
                    scale: Optional[float] = None,
                    force_pallas: Optional[bool] = None,
                    combine_axis: Optional[str] = None) -> jax.Array:
    """Paged decode attention.

    ``q [T, N, D]`` one query row per packed token; ``k_pool``/``v_pool``
    ``[num_blocks, block_size, KV, D]`` (int8 when ``k_scale``/``v_scale``
    ``[num_blocks, block_size, KV]`` are given); ``pool_pos [num_blocks,
    block_size]`` stored token positions (PAD_POSITION = empty);
    ``tables [T, max_blocks_per_seq]`` per-token block table (-1 =
    unmapped); ``q_pos [T]`` query positions. Returns ``[T, N, D]``.

    ``force_pallas``: ``True`` forces the TPU kernel (interpret mode off
    TPU), ``False`` forces the XLA reference, ``None`` auto-selects.

    ``combine_axis``: name of a bound mesh axis over which the block pool
    is sharded (context-parallel serving). Each rank gathers only its
    resident blocks (``tables`` carry rank-local ids, -1 elsewhere) and
    the partials merge with the flash-decoding log-sum-exp combine —
    one pmax and two psums regardless of session length. Must be called
    inside ``shard_map`` with the axis bound; implies the XLA path (the
    Pallas kernel computes no cross-rank combine).
    """
    t, n, d = q.shape
    nb, bs, kv, _ = k_pool.shape
    if n % kv != 0:
        raise ValueError(f"q heads {n} not a multiple of kv heads {kv}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    scale_ = (1.0 / math.sqrt(d)) if scale is None else scale

    tileable = d % 128 == 0 and bs % 128 == 0 and n % 8 == 0
    if combine_axis is not None:
        # the CP merge lives in XLA-land (collectives between the local
        # gather and the normalisation); the kernel path has no axis
        return _paged_attention_xla(q, k_pool, v_pool, pool_pos, tables,
                                    q_pos, k_scale, v_scale, scale_,
                                    combine_axis=combine_axis)
    if force_pallas:
        interpret = jax.default_backend() == "cpu"
        if not interpret and not tileable:
            raise ValueError(
                f"force_pallas: paged shapes (d={d}, block_size={bs}, "
                f"heads={n}) don't tile for the TPU kernel; non-tiling "
                "shapes are only valid in CPU interpret mode")
        return _paged_attention_pallas(q, k_pool, v_pool, pool_pos, tables,
                                       q_pos, k_scale, v_scale, scale_,
                                       interpret=interpret)
    if force_pallas is None and \
            jax.default_backend() in ("tpu", "axon") and tileable:
        return _paged_attention_pallas(q, k_pool, v_pool, pool_pos, tables,
                                       q_pos, k_scale, v_scale, scale_)
    return _paged_attention_xla(q, k_pool, v_pool, pool_pos, tables, q_pos,
                                k_scale, v_scale, scale_)
