"""Ulysses-style all-to-all sequence parallelism for long context.

The second context-parallel attention formulation next to ring attention
(the reference exposes both strategies for its CP degree — ring via
``kernels/ring_attention_kernel.py``, all-to-all head-sharding via the same
``context_parallel_size`` machinery; cf. DeepSpeed-Ulysses): each cp rank
holds a sequence slice; one all-to-all converts seq-sharding into
head-sharding, attention runs over the FULL sequence for this rank's head
group (the Pallas flash kernel applies unchanged — no cross-step online
merge needed), and a second all-to-all converts back.

Trade-off vs ring: two all-to-alls of activation size instead of cp-1
ppermutes of KV size, but no bubble and the plain flash kernel; preferable
when heads >= cp and KV is large (GQA-expanded). Causality is trivial —
each head group sees the whole sequence.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel import comm, mappings
from ..parallel import mesh as ps


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis: str = ps.CP_AXIS, causal: bool = True,
                      scale: Optional[float] = None,
                      dropout_p: float = 0.0,
                      dropout_seed: Optional[jax.Array] = None) -> jax.Array:
    """All-to-all context-parallel attention.

    ``q: [B, S_local, N, D]``; ``k/v: [B, S_local, KV, D]`` may carry the
    *raw* GQA kv heads — when ``KV % cp == 0`` the all-to-alls move the
    unexpanded kv (group-factor less traffic) and expansion happens after
    the reshard; otherwise kv is expanded first. Requires ``N % cp == 0``.
    Must be called with ``axis`` bound; falls back to plain attention when
    cp is absent/1. Differentiable (the all-to-alls are the custom_vjp
    expert-region pair, whose transpose is the reverse all-to-all).

    ``dropout_p``: attention dropout on the post-reshard full-sequence
    view. The cp rank index is folded into the seed so head groups on
    different ranks draw independent masks; the result is deterministic
    and fwd/bwd-consistent but not bit-identical to the unsharded
    model's (ring_attention gives bit-exact masks; the torch reference's
    per-rank RNG streams likewise decorrelate ranks without matching the
    single-device draw).
    """
    from ..modules.attention import repeat_kv

    cp = comm._axis_size(axis)
    n = q.shape[2]
    if cp is None or cp == 1:
        from ..modules.attention import sdpa_reference

        rep = n // k.shape[2]
        return sdpa_reference(q, repeat_kv(k, rep), repeat_kv(v, rep),
                              causal=causal, scale=scale,
                              dropout_p=dropout_p,
                              dropout_seed=dropout_seed)
    if n % cp != 0:
        raise ValueError(
            f"ulysses attention requires heads {n} divisible by cp {cp}")
    if k.shape[2] % cp != 0:
        # kv heads don't split over cp: expand to q heads before the a2a
        rep = n // k.shape[2]
        k, v = repeat_kv(k, rep), repeat_kv(v, rep)

    def seq_to_heads(x):
        # [B, s_local, N, D] -> [B, S, N/cp, D]
        return mappings.enter_expert_parallel_region(
            x, axis, split_dim=2, concat_dim=1)

    def heads_to_seq(x):
        return mappings.exit_expert_parallel_region(
            x, axis, split_dim=1, concat_dim=2)

    if dropout_p > 0.0:
        # flash_attention hashes LOCAL head indices (0..n/cp-1), identical
        # on every rank — without a per-rank seed offset the same mask
        # would repeat across the cp head groups
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed")
        rank = jax.lax.axis_index(axis).astype(jnp.uint32)
        dropout_seed = (jnp.asarray(dropout_seed, jnp.uint32)
                        + rank * jnp.uint32(0x9E3779B1))

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if kh.shape[2] != qh.shape[2]:
        # expand after the reshard: repeat_kv is adjacent (kv head j
        # serves q heads [j*rep, (j+1)*rep)), so a contiguous q-head block
        # matches the contiguous kv-head block of its rank
        rep = qh.shape[2] // kh.shape[2]
        kh, vh = repeat_kv(kh, rep), repeat_kv(vh, rep)
    from .flash_attention import flash_attention

    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale_,
                          dropout_p=dropout_p, dropout_seed=dropout_seed)
    return heads_to_seq(out)


# -- nxdlint jaxpr-audit entry point ---------------------------------------

from ..analysis.audit_registry import BuiltEntry, register_entry_point


@register_entry_point(
    "ulysses-attention",
    description="cp all-to-all (Ulysses) attention: the enter/exit "
                "expert-parallel-region pair resharding seq <-> heads",
    tags=("train", "serve"),
    in_shardings=((None, "cp", None, None),) * 3,
    max_replicated_bytes=1 << 20,
)
def _audit_ulysses_attention() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``/``--mesh-protocol``: the a2a
    reshard pair on a 4-way cp mesh with heads divisible by cp, so both
    all-to-alls move the unexpanded kv."""
    from jax.sharding import PartitionSpec as P

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    mesh = ps.initialize_model_parallel(context_parallel_size=4)
    fn = jax.jit(ps.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v),
        mesh, in_specs=(P(None, "cp", None, None),) * 3,
        out_specs=P(None, "cp", None, None)))
    q = jnp.zeros((2, 32, 4, 8), jnp.float32)
    return BuiltEntry(fn=fn, args=(q, q, q), mesh=mesh)
