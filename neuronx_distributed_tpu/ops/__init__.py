"""Hot-path ops (reference: ``kernels/`` + ``operators/`` NKI wrappers).

TPU equivalents are Pallas kernels with XLA fallbacks; every op keeps a
reference implementation for CPU/interpret-mode testing, mirroring the
reference's torch golden fallbacks (``moe/blockwise.py:326``).
"""

from . import flash_attention
from . import operators
from . import ring_attention
from .flash_attention import flash_attention as flash_attention_fn
from .ring_attention import ring_attention as ring_attention_fn

__all__ = ["flash_attention", "operators", "ring_attention", "flash_attention_fn",
           "ring_attention_fn"]
