"""Hot-path ops (reference: ``kernels/`` + ``operators/`` NKI wrappers).

TPU equivalents are Pallas kernels with XLA fallbacks; every op keeps a
reference implementation for CPU/interpret-mode testing, mirroring the
reference's torch golden fallbacks (``moe/blockwise.py:326``).
"""

from . import blockwise_moe
from . import collective_matmul
from . import flash_attention
from . import flash_decoding
from . import operators
from . import ring_attention
from . import ulysses
from .blockwise_moe import (grouped_glu, grouped_glu_decode,
                            grouped_glu_reference)
from .collective_matmul import (all_gather_matmul, copy_matmul,
                                matmul_all_reduce, matmul_reduce_scatter,
                                overlap_engaged, shapes_tile,
                                will_decompose)
from .flash_attention import flash_attention as flash_attention_fn
from .flash_decoding import flash_decode_attention
from .ring_attention import ring_attention as ring_attention_fn
from .ring_attention import ring_attention_pallas
from .ulysses import ulysses_attention

__all__ = ["blockwise_moe", "collective_matmul", "flash_attention",
           "flash_decoding", "operators", "ring_attention", "ulysses",
           "grouped_glu", "grouped_glu_decode", "grouped_glu_reference",
           "all_gather_matmul",
           "copy_matmul", "matmul_all_reduce", "matmul_reduce_scatter",
           "overlap_engaged", "shapes_tile", "will_decompose",
           "flash_attention_fn", "flash_decode_attention",
           "ring_attention_fn", "ring_attention_pallas",
           "ulysses_attention"]
