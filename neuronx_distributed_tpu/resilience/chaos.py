"""Deterministic fault injection for checkpoint storage and serving.

:class:`ChaosCheckpointStorage` wraps any ``BaseCheckpointStorage`` and
injects faults according to a :class:`FaultPlan` — a small, seed-driven DSL
of :class:`FaultRule` entries. Faults are *deterministic* for a given
(seed, op sequence): the same plan replayed over the same operations injects
the same faults, so chaos tests are reproducible bit-for-bit.

Storage fault kinds:

* ``transient`` — raises :class:`InjectedFault` (a ``ConnectionError``
  subclass carrying a throttle marker) that ``_is_transient`` classifies as
  retriable; proves the retry/backoff path heals real hiccups.
* ``permanent`` — raises ``OSError(ENOSPC)``, a deterministic local
  condition that must surface immediately (no retries burned).
* ``latency`` — sleeps ``latency_s`` before the op (host-side only; never
  inside traced code).

Serving fault kinds (the router drills of ``inference/router.py``, where
``op`` is the lifecycle point — ``step`` — and ``path`` is the replica
name):

* ``crash`` — raises :class:`ReplicaCrashed`: the replica process/host is
  gone and every in-flight request on it must fail over.
* ``exhaust`` — a KV block-pool exhaustion storm signal (raised as
  ``CacheExhaustedError`` through :meth:`FaultPlan.apply`).
* ``preempt`` — a SIGTERM-style eviction notice mid-flight (spot/
  maintenance): unlike ``crash``, the replica gets a drain window, so the
  router *migrates* its live sessions instead of failing them over.
  Consult-only — :meth:`FaultPlan.apply` treats it as a no-op directive.
* ``scale_burst`` — a fleet-level load-spike signal (matched against the
  router's ``consult("scale", "fleet")`` tick) directing an immediate
  scale-up; also consult-only.
* ``bitflip`` — a silent-data-corruption event: the consulting layer
  flips one bit at the seeded position (``bit=<n>``, or drawn from the
  plan RNG when unset) in whatever it guards — a param leaf at an
  integrity cadence boundary (``consult_detail("integrity", "params")``),
  a decoded token on a serving replica, a wire payload. Consult-only like
  ``preempt``: corruption is injected by the caller, never raised. See
  ``resilience/integrity.py`` and ``bench.py --sdc``.

Link fault kinds (the DCN handoff fabric of ``inference/transport.py``,
where ``op`` is ``"link"`` and ``path`` is the route, e.g. ``p0->d0``;
all consult-only — the :class:`~..inference.transport.DcnLink` carrier
enacts them on the chunk in transit):

* ``link_drop`` — the chunk vanishes in transit (never delivered); the
  sender heals it through ACK-timeout retransmission.
* ``link_corrupt`` — one bit of the chunk payload flips in transit
  (``bit=<n>`` or drawn from the plan RNG); the receiver's fingerprint
  check NACKs it and the sender retransmits.
* ``link_delay`` — the chunk arrives ``latency=<s>`` late (virtual time;
  out-of-order arrival at the receiver, duplicate retransmits possible).
* ``link_partition`` — the link goes down for ``latency=<s>`` seconds
  (indefinitely when unset): in-flight chunks are lost and later sends
  die silently, so the sender's bounded retransmit budget exhausts, the
  stream aborts, and the router falls back to local re-prefill.

The router consults the plan through :meth:`FaultPlan.consult`, which
*returns* the directive instead of raising/sleeping, so injected latency is
virtual (deterministic under fake clocks) and the caller decides how a
crash or an exhaustion storm manifests.

The plan is buildable programmatically or parsed from a compact spec string
usable from the CLI (``bench.py --chaos`` / ``--router``)::

    seed=7; save_text|*/checkpoint : transient, p=0.5, times=2; * : latency=0.01
    step|r1 : crash, after=6, times=1        # kill replica r1 at its 7th step

Each ``;``-separated clause is ``op[|pathglob] : kind-and-options`` where
options are ``p=<prob>``, ``after=<n calls>``, ``times=<max fires>``,
``latency=<seconds>``, ``bit=<position>`` (bitflip rules only). A leading
``seed=<int>`` clause seeds the RNG.
"""

from __future__ import annotations

import dataclasses
import errno
import fnmatch
import random
import threading
import time
from typing import Any, List, Optional, Tuple

from ..trainer.checkpoint_storage import (BaseCheckpointStorage,
                                          retry_with_backoff)


class InjectedFault(ConnectionError):
    """A chaos-injected transient fault. The message carries a throttle
    marker so ``_is_transient`` classifies it exactly like a real S3
    503 slow-down."""


class ReplicaCrashed(RuntimeError):
    """A chaos-injected (or observed) serving-replica death: the engine
    behind it is gone and its in-flight requests must be resubmitted."""


@dataclasses.dataclass
class FaultRule:
    """One injection rule; all matching is AND-ed.

    ``op``/``path`` are ``fnmatch`` globs over the storage method name and
    its path argument. ``after`` skips the first N matching calls; ``times``
    caps how often the rule fires (-1 = unlimited); ``prob`` is the
    per-matching-call fire probability drawn from the plan's seeded RNG.
    """

    op: str = "*"
    path: str = "*"
    kind: str = "transient"  # transient|permanent|latency|crash|exhaust
    prob: float = 1.0        # |preempt|scale_burst|bitflip|link_*
    after: int = 0
    times: int = -1
    latency_s: float = 0.0
    bit: int = -1            # bitflip position; -1 = draw from plan RNG

    _KINDS = ("transient", "permanent", "latency", "crash", "exhaust",
              "preempt", "scale_burst", "bitflip",
              "link_drop", "link_corrupt", "link_delay", "link_partition")

    def __post_init__(self) -> None:
        if self.kind not in self._KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"({' | '.join(self._KINDS)})")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")

    def matches(self, op: str, path: str) -> bool:
        return (fnmatch.fnmatch(op, self.op)
                and fnmatch.fnmatch(path, self.path))


class FaultPlan:
    """A seeded sequence of :class:`FaultRule` with per-rule fire state.

    Thread-safe: async commit threads and the training thread hit the same
    storage object concurrently, so match counting and the RNG draw are
    serialized under one lock.
    """

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._matched = [0] * len(self.rules)
        self._fired = [0] * len(self.rules)
        self.injected: List[str] = []  # audit log: "kind op path"

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the spec DSL (see module docstring)."""
        seed = 0
        rules: List[FaultRule] = []
        for clause in (c.strip() for c in spec.split(";")):
            if not clause:
                continue
            if clause.replace(" ", "").startswith("seed="):
                seed = int(clause.split("=", 1)[1])
                continue
            if ":" not in clause:
                raise ValueError(
                    f"bad fault clause {clause!r}: expected "
                    "'op[|pathglob] : kind-and-options'")
            target, opts = (s.strip() for s in clause.split(":", 1))
            op, _, path = (s.strip() for s in target.partition("|"))
            kw: dict = {"op": op or "*", "path": path or "*"}
            kind = None
            for item in (o.strip() for o in opts.split(",")):
                if not item:
                    continue
                if "=" in item:
                    k, v = (s.strip() for s in item.split("=", 1))
                    if k == "p":
                        kw["prob"] = float(v)
                    elif k == "after":
                        kw["after"] = int(v)
                    elif k == "times":
                        kw["times"] = int(v)
                    elif k == "latency":
                        kw["latency_s"] = float(v)
                        kind = kind or "latency"
                    elif k == "bit":
                        kw["bit"] = int(v)
                        kind = kind or "bitflip"
                    else:
                        raise ValueError(f"unknown fault option {k!r}")
                else:
                    kind = item
            kw["kind"] = kind or "transient"
            rules.append(FaultRule(**kw))
        return cls(rules, seed=seed)

    def fire_count(self) -> int:
        with self._lock:
            return sum(self._fired)

    def _fire(self, op: str, path: str) -> Tuple[Optional[str], float, dict]:
        """Match + fire every rule for (op, path) under the lock; returns
        ``(first_raising_kind_or_None, max_latency_s, detail)``. Fire
        bookkeeping (``after``/``times``/``prob`` draws, the audit log)
        happens here so :meth:`apply` and :meth:`consult` share one
        deterministic stream. ``detail`` carries rule payloads the caller
        needs to enact a directive (``bit`` for bitflips — pinned by the
        rule, or drawn from the seeded RNG so drills replay bit-for-bit)."""
        kind: Optional[str] = None
        latency_s = 0.0
        detail: dict = {}
        with self._lock:
            for i, rule in enumerate(self.rules):
                if not rule.matches(op, path):
                    continue
                self._matched[i] += 1
                if self._matched[i] <= rule.after:
                    continue
                if rule.times >= 0 and self._fired[i] >= rule.times:
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                self._fired[i] += 1
                self.injected.append(f"{rule.kind} {op} {path}")
                if rule.kind == "latency":
                    latency_s = max(latency_s, rule.latency_s)
                elif kind is None:
                    kind = rule.kind
                    if rule.kind in ("bitflip", "link_corrupt"):
                        detail["bit"] = (rule.bit if rule.bit >= 0
                                         else self._rng.getrandbits(20))
                    if rule.kind in ("link_delay", "link_partition"):
                        # the rule's latency payload rides in detail: a
                        # delay's added transit time / a partition's
                        # healing window (0 = partitioned indefinitely)
                        detail["latency_s"] = rule.latency_s
        return kind, latency_s, detail

    def consult(self, op: str, path: str) -> Tuple[Optional[str], float]:
        """Like :meth:`apply` but *returns* the directive instead of
        raising/sleeping: ``(kind | None, latency_s)``. Serving chaos goes
        through here — the router interprets ``crash``/``exhaust`` itself
        and treats latency as virtual time, so drills stay deterministic
        under fake clocks."""
        kind, latency_s, _ = self._fire(op, path)
        return kind, latency_s

    def consult_detail(self, op: str, path: str) -> Tuple[Optional[str],
                                                          float, dict]:
        """:meth:`consult` plus the firing rule's payload — ``detail``
        holds ``{"bit": <position>}`` when a ``bitflip`` directive fires
        (the integrity monitor and the router's SDC drill need the seeded
        position to enact the flip deterministically)."""
        return self._fire(op, path)

    def apply(self, op: str, path: str) -> None:
        """Consult every rule for this (op, path); raise/sleep as directed.

        The first raising rule wins; latency rules sleep and keep going so a
        latency+transient combination behaves like a slow failing store.
        """
        kind, sleep_s, _ = self._fire(op, path)
        if sleep_s > 0:
            time.sleep(sleep_s)
        if kind == "transient":
            raise InjectedFault(
                f"chaos: injected transient fault on {op}({path!r}) "
                "— 503 slow down")
        if kind == "permanent":
            raise OSError(
                errno.ENOSPC,
                f"chaos: injected permanent fault on {op}({path!r})"
                " — no space left on device")
        if kind == "crash":
            raise ReplicaCrashed(
                f"chaos: injected replica crash on {op}({path!r})")
        if kind == "exhaust":
            # lazy import: resilience must not depend on inference at
            # module load (the router imports this package)
            from ..inference.paging import CacheExhaustedError

            raise CacheExhaustedError(
                f"chaos: injected pool-exhaustion storm on {op}({path!r})")
        # preempt / scale_burst / bitflip and the link_* kinds are
        # consult-only directives: they model orchestrator signals
        # (eviction notice, load spike) or in-band transit faults the
        # caller must enact itself (the DcnLink carrier), not storage
        # failures, so apply() has nothing to raise for them.


class ChaosCheckpointStorage(BaseCheckpointStorage):
    """Fault-injecting wrapper over any storage backend.

    Every control-plane op consults the plan *before* delegating, then runs
    under the same ``retry_with_backoff`` policy the object-store backend
    uses — injected transients heal through real retries, injected
    permanents surface immediately, exercising the full classification
    path (``retries=False`` bypasses the retry layer to observe raw
    faults).
    """

    def __init__(self, inner: BaseCheckpointStorage, plan: FaultPlan,
                 retries: bool = True, **retry_kwargs: Any):
        super().__init__(inner.dirname())
        self.inner = inner
        self.plan = plan
        self._retries = retries
        self._retry_kwargs = retry_kwargs

    def _run(self, op: str, path: str, fn):
        def attempt():
            self.plan.apply(op, path)
            return fn()
        if self._retries:
            return retry_with_backoff(**self._retry_kwargs)(attempt)()
        return attempt()

    def dir_exists(self, dirname: str) -> bool:
        return self._run("dir_exists", dirname,
                         lambda: self.inner.dir_exists(dirname))

    def file_exists(self, filename: str) -> bool:
        return self._run("file_exists", filename,
                         lambda: self.inner.file_exists(filename))

    def create_dir(self, dirname: str) -> None:
        return self._run("create_dir", dirname,
                         lambda: self.inner.create_dir(dirname))

    def list_dirs(self, dirname: str) -> List[str]:
        return self._run("list_dirs", dirname,
                         lambda: self.inner.list_dirs(dirname))

    def list_files(self, dirname: str):
        return self._run("list_files", dirname,
                         lambda: self.inner.list_files(dirname))

    def file_size(self, filename: str):
        return self._run("file_size", filename,
                         lambda: self.inner.file_size(filename))

    def remove_dir(self, dirname: str) -> None:
        return self._run("remove_dir", dirname,
                         lambda: self.inner.remove_dir(dirname))

    def remove_file(self, filename: str) -> None:
        return self._run("remove_file", filename,
                         lambda: self.inner.remove_file(filename))

    def save_text(self, text: str, filename: str) -> None:
        return self._run("save_text", filename,
                         lambda: self.inner.save_text(text, filename))

    def load_text(self, filename: str) -> str:
        return self._run("load_text", filename,
                         lambda: self.inner.load_text(filename))

    def read_bytes(self, filename: str):
        return self._run("read_bytes", filename,
                         lambda: self.inner.read_bytes(filename))


def wrapper_for_plan(plan: FaultPlan, retries: bool = True,
                     **retry_kwargs: Any):
    """A factory suitable for ``checkpoint_storage.install_storage_wrapper``
    — every storage the engine creates gets chaos-wrapped with ``plan``."""
    def wrap(inner: BaseCheckpointStorage) -> ChaosCheckpointStorage:
        if isinstance(inner, ChaosCheckpointStorage):
            return inner  # never stack chaos on chaos
        return ChaosCheckpointStorage(inner, plan, retries=retries,
                                      **retry_kwargs)
    return wrap
