"""Resilience subsystem: fault injection, preemption-safe checkpointing,
training watchdog, and verified resume.

The reference NxD stack treats durability as a first-class concern (async
commit protocol with done-markers, tenacity-style storage retries,
``finalize_checkpoint`` atexit flush). This package makes those guarantees
*provable* and *actionable*:

* :mod:`chaos` — :class:`FaultPlan` / :class:`ChaosCheckpointStorage`:
  deterministic, seed-driven fault injection over any
  ``BaseCheckpointStorage`` so the retry/backoff and commit-protocol
  invariants are testable (and exercisable from ``bench.py --chaos``).
* :mod:`preemption` — :class:`PreemptionGuard`: SIGTERM/SIGINT turns into a
  synchronous emergency checkpoint at the next step boundary, then a
  resumable exit (:data:`EXIT_PREEMPTED`), with a grace deadline.
* :mod:`watchdog` — :class:`Watchdog`: non-finite loss/grad detection with
  ``halt`` / ``skip_step`` / ``rewind`` policies, loss-spike z-score
  detection, and a host-side stall timer for hung collectives or stalled
  data loaders.
* :mod:`manifest` — per-tag save manifests (file list + sizes +
  per-shard content digests + metadata checksum) behind verified resume:
  ``load_checkpoint`` falls back to the newest *prior* complete tag on
  corruption.
* :mod:`integrity` — silent-data-corruption defense: jit-safe on-device
  fingerprints at a train-step cadence, cross-dp-replica consensus with
  majority vote, wire-payload spot checks, and the
  :class:`IntegrityMonitor` callback composing detection with the
  watchdog's rewind (driven by the chaos ``bitflip`` fault kind;
  ``bench.py --sdc``).

See ``docs/resilience.md``.
"""

from .chaos import (ChaosCheckpointStorage, FaultPlan, FaultRule,
                    InjectedFault, ReplicaCrashed)
from .integrity import (IntegrityError, IntegrityMonitor,
                        dp_consensus_fingerprints, fingerprint_array,
                        fingerprint_array_np, fingerprint_tree,
                        kv_payload_fingerprints, majority_vote,
                        payload_fingerprint)
from .manifest import (MANIFEST_FILE, build_manifest, verify_manifest)
from .preemption import (EXIT_PREEMPTED, PreemptionGuard, TrainingPreempted)
from .watchdog import SpikeDetector, StallTimer, Watchdog, WatchdogHalt

__all__ = [
    "IntegrityError",
    "IntegrityMonitor",
    "dp_consensus_fingerprints",
    "fingerprint_array",
    "fingerprint_array_np",
    "fingerprint_tree",
    "kv_payload_fingerprints",
    "majority_vote",
    "payload_fingerprint",
    "ChaosCheckpointStorage",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "ReplicaCrashed",
    "MANIFEST_FILE",
    "build_manifest",
    "verify_manifest",
    "EXIT_PREEMPTED",
    "PreemptionGuard",
    "TrainingPreempted",
    "SpikeDetector",
    "StallTimer",
    "Watchdog",
    "WatchdogHalt",
]
