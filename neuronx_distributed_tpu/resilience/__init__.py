"""Resilience subsystem: fault injection, preemption-safe checkpointing,
training watchdog, and verified resume.

The reference NxD stack treats durability as a first-class concern (async
commit protocol with done-markers, tenacity-style storage retries,
``finalize_checkpoint`` atexit flush). This package makes those guarantees
*provable* and *actionable*:

* :mod:`chaos` — :class:`FaultPlan` / :class:`ChaosCheckpointStorage`:
  deterministic, seed-driven fault injection over any
  ``BaseCheckpointStorage`` so the retry/backoff and commit-protocol
  invariants are testable (and exercisable from ``bench.py --chaos``).
* :mod:`preemption` — :class:`PreemptionGuard`: SIGTERM/SIGINT turns into a
  synchronous emergency checkpoint at the next step boundary, then a
  resumable exit (:data:`EXIT_PREEMPTED`), with a grace deadline.
* :mod:`watchdog` — :class:`Watchdog`: non-finite loss/grad detection with
  ``halt`` / ``skip_step`` / ``rewind`` policies, loss-spike z-score
  detection, and a host-side stall timer for hung collectives or stalled
  data loaders.
* :mod:`manifest` — per-tag save manifests (file list + sizes + metadata
  checksum) behind verified resume: ``load_checkpoint`` falls back to the
  newest *prior* complete tag on corruption.

See ``docs/resilience.md``.
"""

from .chaos import (ChaosCheckpointStorage, FaultPlan, FaultRule,
                    InjectedFault, ReplicaCrashed)
from .manifest import (MANIFEST_FILE, build_manifest, verify_manifest)
from .preemption import (EXIT_PREEMPTED, PreemptionGuard, TrainingPreempted)
from .watchdog import SpikeDetector, StallTimer, Watchdog, WatchdogHalt

__all__ = [
    "ChaosCheckpointStorage",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "ReplicaCrashed",
    "MANIFEST_FILE",
    "build_manifest",
    "verify_manifest",
    "EXIT_PREEMPTED",
    "PreemptionGuard",
    "TrainingPreempted",
    "SpikeDetector",
    "StallTimer",
    "Watchdog",
    "WatchdogHalt",
]
