"""Silent-data-corruption (SDC) defense: jit-safe integrity fingerprints.

At production scale, silent data corruption — flaky cores, bad HBM rows,
lossy links — is a when-not-if event, and this stack is *more* exposed
than most: every gradient/activation collective rides an int8/fp8 wire
(``parallel/wire_codec.py``) and live KV-session migration ships raw
blocks between replicas (``inference/engine.py``). The watchdog only sees
the downstream *symptom* (a loss-spike z-score); this module detects
corruption at its source. Three layers:

* **On-device fingerprints** — :func:`fingerprint_array` folds the raw
  bits of an array (uint32 view) into a small int32 digest with pure
  ``jnp`` ops, so it traces under ``jit``/``shard_map`` and runs inside
  the compiled train step at a cadence ``integrity_every=K`` (see
  ``make_train_step``). One host readback per cadence boundary;
  ``compile_count()`` is unchanged because the cadence gate is a
  ``lax.cond`` on the step counter, not a Python branch.
  :func:`fingerprint_array_np` is the bit-exact host (numpy) mirror, used
  to verify KV-session tickets and checkpoint payloads without touching
  the device.
* **Cross-dp-replica consensus** — post-allreduce params are bit-identical
  across data-parallel replicas *by construction*, so an ``all_gather`` of
  per-replica fingerprint vectors (:func:`dp_consensus_fingerprints`)
  plus :func:`majority_vote` localizes a divergent replica/leaf without
  keeping any reference copy of the params.
* **Wire spot checks** — :func:`payload_fingerprint` digests an encoded
  ``wire_codec`` payload ``(q, scales)`` so sampled ring hops can compare
  a sender-side fingerprint against a receiver-side recompute (see
  ``wire_codec.spot_check_roundtrip``); 4 bytes of overhead per sampled
  hop.

:class:`IntegrityMonitor` wires detection into the training loop: at each
cadence boundary it compares the step-reported fingerprint against an
independent host-triggered recompute of the live params, emits an
``integrity_mismatch`` obs event on divergence, and composes with the
:class:`~neuronx_distributed_tpu.resilience.watchdog.Watchdog`'s rewind
discipline (``report_anomaly``) to restore the newest *content-verified*
checkpoint (manifests carry per-shard digests; see ``manifest.py``). The
chaos ``bitflip`` fault kind drives deterministic drills end to end
(``bench.py --sdc``).

See docs/resilience.md ("Silent data corruption").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.events import emit_event
from ..obs.metrics import get_registry
from ..utils.logger import get_logger

logger = get_logger(__name__)

# Odd multiplicative constants (Knuth / splitmix-style). The fold is
# position-weighted so permutations don't cancel, and avalanched so a
# single flipped bit flips ~half the digest. Not cryptographic — SDC is
# random, not adversarial.
_C_WORD = 2654435761   # 0x9E3779B1
_C_POS = 2654435769    # 0x9E3779B9
_C_MIX1 = 2246822519   # 0x85EBCA77
_C_MIX2 = 3266489917   # 0xC2B2AE3D


class IntegrityError(RuntimeError):
    """An integrity fingerprint mismatch that no recovery policy absorbed
    (no watchdog to rewind through, or a corrupted KV-session ticket)."""


# ---------------------------------------------------------------------------
# device-side (jnp) fingerprints — trace-safe, usable inside jit/shard_map
# ---------------------------------------------------------------------------


def _as_words(x: jax.Array) -> jax.Array:
    """Flatten ``x`` to a uint32 bit view. Floats are bitcast through
    float32 (exact for bf16/fp16/fp32 — a flipped mantissa/exponent bit
    survives the widening); bools/ints wrap into uint32."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.floating):
        bits = jax.lax.bitcast_convert_type(
            x.astype(jnp.float32), jnp.uint32)
    else:
        bits = x.astype(jnp.uint32)
    return bits.reshape(-1)


def _fold(bits: jax.Array, blocks: int) -> jax.Array:
    """Position-weighted additive fold of a flat uint32 vector into
    ``blocks`` uint32 words, with a final avalanche."""
    n = bits.size
    pad = (-n) % blocks if blocks else 0
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((pad,), jnp.uint32)])
    bits = bits.reshape(blocks, -1)
    pos = jnp.arange(1, bits.shape[1] + 1, dtype=jnp.uint32)
    mixed = (bits * jnp.uint32(_C_WORD)) ^ (pos * jnp.uint32(_C_POS))
    # the reduction is ADD mod 2**32, not xor: integer add is exactly
    # associative/commutative (any partitioning gives the same words),
    # and partitioned add-reduce is XLA's first-class path on every
    # backend — xor reduce computations are rejected or mis-assembled
    # by the CPU SPMD partitioner inside sharded train steps
    h = jnp.sum(mixed, axis=1, dtype=jnp.uint32) ^ jnp.uint32(n)
    h = (h ^ (h >> 15)) * jnp.uint32(_C_MIX1)
    h = (h ^ (h >> 13)) * jnp.uint32(_C_MIX2)
    return h ^ (h >> 16)


def fingerprint_array(x: jax.Array, blocks: int = 1) -> jax.Array:
    """Blockwise int32 fingerprint of ``x``'s raw bits — pure ``jnp``, so
    it is trace-safe (use this, never ``hashlib``/host digests, inside
    jitted code; the nxdlint ``integrity`` rule enforces it). Returns an
    ``int32[blocks]`` vector; element ``b`` digests the ``b``-th
    contiguous slice of the flattened array, localizing corruption to a
    block. Empty arrays fingerprint to the avalanche of zero."""
    if blocks < 1:
        raise ValueError(f"blocks must be >= 1, got {blocks}")
    words = _as_words(x)
    if words.size == 0:
        words = jnp.zeros((blocks,), jnp.uint32)
    return jax.lax.bitcast_convert_type(_fold(words, blocks), jnp.int32)


def fingerprint_tree(tree: Any) -> jax.Array:
    """Per-leaf scalar fingerprints of a pytree, stacked into an
    ``int32[n_leaves]`` vector (leaf order = ``tree_leaves`` order). The
    fixed shape makes it a legal train-step metric at every step."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:  # nxdlint: disable=trace-safety  -- structure is static
        return jnp.zeros((0,), jnp.int32)
    return jnp.concatenate([fingerprint_array(leaf) for leaf in leaves])


def combine_fingerprints(fps: jax.Array) -> jax.Array:
    """Fold a vector of fingerprints into one scalar int32 (e.g. a whole
    param-tree digest, or a ``(q, scales)`` wire-payload pair)."""
    return fingerprint_array(jnp.asarray(fps))[0]


def payload_fingerprint(q: jax.Array,
                        scales: Optional[jax.Array] = None) -> jax.Array:
    """Scalar fingerprint of an encoded ``wire_codec`` payload — digests
    the quantized words and (when present) the per-block scales, so a
    flipped bit in either leg of the wire is visible. Trace-safe; this is
    what sampled ring hops ship alongside the payload (4 bytes)."""
    fp_q = fingerprint_array(q)
    if scales is None:
        return fp_q[0]
    return combine_fingerprints(
        jnp.concatenate([fp_q, fingerprint_array(scales)]))


# ---------------------------------------------------------------------------
# host-side (numpy) mirror — bit-exact parity with the jnp fold
# ---------------------------------------------------------------------------


def _as_words_np(x: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(x)
    if a.dtype == np.bool_:
        return a.astype(np.uint32).reshape(-1)
    # jnp.issubdtype (not np.) so ml_dtypes floats (bf16, fp8) route
    # through the float32 bitcast exactly like the device fold
    if jnp.issubdtype(a.dtype, jnp.floating):
        return a.astype(np.float32).view(np.uint32).reshape(-1)
    with np.errstate(over="ignore"):
        return a.astype(np.uint32).reshape(-1)


def fingerprint_array_np(x: np.ndarray, blocks: int = 1) -> np.ndarray:
    """Host mirror of :func:`fingerprint_array`: same fold, same
    constants, bit-identical output — so a fingerprint computed on-device
    inside the train step can be verified against host bytes (checkpoint
    payloads, KV-session tickets) without re-staging them."""
    if blocks < 1:
        raise ValueError(f"blocks must be >= 1, got {blocks}")
    words = _as_words_np(np.asarray(x))
    if words.size == 0:
        words = np.zeros((blocks,), np.uint32)
    n = words.size
    pad = (-n) % blocks
    if pad:
        words = np.concatenate([words, np.zeros((pad,), np.uint32)])
    words = words.reshape(blocks, -1)
    with np.errstate(over="ignore"):
        pos = np.arange(1, words.shape[1] + 1, dtype=np.uint32)
        mixed = (words * np.uint32(_C_WORD)) ^ (pos * np.uint32(_C_POS))
        # dtype pinned: np.sum would widen uint32 to uint64 and break
        # bit-parity with the device fold's mod-2**32 wraparound
        h = np.add.reduce(mixed, axis=1, dtype=np.uint32) ^ np.uint32(n)
        h = (h ^ (h >> np.uint32(15))) * np.uint32(_C_MIX1)
        h = (h ^ (h >> np.uint32(13))) * np.uint32(_C_MIX2)
        h = h ^ (h >> np.uint32(16))
    return h.view(np.int32)


def fingerprint_blocks_np(arr: np.ndarray, axis: int) -> List[int]:
    """Per-slice fingerprints of a host array along ``axis`` (e.g. the
    block axis of an extracted KV payload): one int per block, so a
    corrupted shipped block is localized, not just detected."""
    moved = np.moveaxis(np.asarray(arr), axis, 0)
    return [int(fingerprint_array_np(moved[i])[0])
            for i in range(moved.shape[0])]


def kv_payload_fingerprints(payload: Dict[str, np.ndarray],
                            block_axes: Dict[str, int]) -> Dict[str, List[int]]:
    """Fingerprint every tensor of an extracted KV payload per block.
    ``block_axes`` maps payload key -> block axis (``paging.extract_blocks``
    layouts differ: ``k``/``v`` carry blocks on axis 1, ``pos``/scales on
    axis 0)."""
    return {name: fingerprint_blocks_np(arr, block_axes[name])
            for name, arr in payload.items()}


# ---------------------------------------------------------------------------
# cross-dp-replica consensus
# ---------------------------------------------------------------------------


def dp_consensus_fingerprints(tree: Any, axis_name: str) -> jax.Array:
    """Inside ``shard_map``/``pmap`` over the dp axis: fingerprint the
    local replica's (replicated) params and all-gather the vectors along
    ``axis_name``. Returns ``int32[dp, n_leaves]`` — every replica holds
    the full matrix, so the majority vote needs no designated leader and
    no reference copy of the params."""
    fp = fingerprint_tree(tree)
    return jax.lax.all_gather(fp, axis_name)


def majority_vote(fp_matrix: np.ndarray) -> Tuple[np.ndarray,
                                                  Dict[int, List[int]]]:
    """Majority vote over an ``[replicas, n_leaves]`` fingerprint matrix.

    Returns ``(consensus[n_leaves], divergent)`` where ``divergent`` maps
    replica index -> leaf indices disagreeing with the majority. Because
    post-allreduce params are bit-identical across dp by construction, any
    nonempty ``divergent`` is evidence of corruption on that replica's
    slice (ties blame every holdout — with 2 replicas you get detection
    but not localization, which the docs call out)."""
    fps = np.asarray(fp_matrix)
    if fps.ndim != 2:
        raise ValueError(f"expected [replicas, n_leaves], got {fps.shape}")
    n_rep, n_leaves = fps.shape
    consensus = np.empty((n_leaves,), fps.dtype)
    divergent: Dict[int, List[int]] = {}
    for col in range(n_leaves):
        values, counts = np.unique(fps[:, col], return_counts=True)
        maj = values[int(np.argmax(counts))]
        consensus[col] = maj
        for rep in np.nonzero(fps[:, col] != maj)[0]:
            divergent.setdefault(int(rep), []).append(col)
    return consensus, divergent


# ---------------------------------------------------------------------------
# training-loop monitor
# ---------------------------------------------------------------------------


def _metrics():
    reg = get_registry()
    return (
        reg.counter("nxd_integrity_checks_total",
                    "Integrity fingerprint verifications performed"),
        reg.counter("nxd_integrity_mismatch_total",
                    "Integrity fingerprint mismatches detected",
                    labels=("scope",)),
    )


class IntegrityMonitor:
    """Trainer callback closing the detection loop at cadence boundaries.

    ``make_train_step(integrity_every=K)`` computes the params fingerprint
    *inside* the compiled step (metric ``integrity_fp``, populated on
    steps where ``step % K == 0``). At each boundary this callback
    re-fingerprints the live ``trainer.state.params`` with an independent
    jitted recompute and compares: the step-reported vector digests the
    params the device *wrote*, the recompute digests the params the next
    step will *read* — any corruption landing between the two (bad HBM,
    a flipped readback bit) surfaces as a mismatch within one cadence
    window. On mismatch it emits the ``integrity_mismatch`` obs event and
    delegates recovery to the watchdog's rewind discipline
    (``Watchdog.report_anomaly``), which restores the newest
    content-verified checkpoint; without a watchdog it raises
    :class:`IntegrityError` (fail-stop beats training on garbage).

    ``chaos`` hooks the deterministic drill: at each boundary the plan is
    consulted at ``("integrity", "params")`` and a ``bitflip`` directive
    flips the seeded bit in the largest param leaf *before* verification —
    modeling corruption at rest between device write and host read.
    Mid-window flips are the dp-consensus layer's job
    (:func:`dp_consensus_fingerprints`); see the failure matrix in
    docs/resilience.md.
    """

    needs_prev_state = False

    def __init__(self, every: int, watchdog: Any = None,
                 chaos: Any = None) -> None:
        if every < 1:
            raise ValueError(f"integrity cadence must be >= 1, got {every}")
        self.every = every
        self.watchdog = watchdog
        self.chaos = chaos
        self.checks = 0
        self.mismatches = 0
        self.flips_injected = 0
        self._fp_fn = None

    # -- Callback protocol -------------------------------------------------

    def on_train_start(self, trainer) -> None: ...

    def on_eval_end(self, trainer, metrics) -> None: ...

    def on_train_end(self, trainer) -> None: ...

    def on_step_end(self, trainer, metrics: Dict) -> None:
        step = trainer.host_step
        if step % self.every != 0:
            return
        if "integrity_fp" not in metrics:
            raise IntegrityError(
                "IntegrityMonitor needs the in-step fingerprint metric: "
                "build the step with make_train_step(..., "
                f"integrity_every={self.every})")
        if self.chaos is not None:
            kind, _lat, detail = self.chaos.consult_detail(
                "integrity", "params")
            if kind == "bitflip":
                self._flip_param_bit(trainer, int(detail.get("bit", 0)))
        reported = np.asarray(jax.device_get(metrics["integrity_fp"]))
        actual = self._host_fingerprint(trainer.state.params)
        self.checks += 1
        checks, mismatches = _metrics()
        checks.inc()
        if np.array_equal(reported, actual):
            return
        bad = [int(i) for i in np.nonzero(reported != actual)[0]]
        self.mismatches += 1
        mismatches.labels(scope="params").inc()
        emit_event("integrity_mismatch", scope="params", step=step,
                   leaves=bad, cadence=self.every)
        reason = (f"integrity fingerprint mismatch at step {step} "
                  f"(divergent leaves {bad})")
        if self.watchdog is not None:
            self.watchdog.report_anomaly(trainer, reason)
        else:
            raise IntegrityError(reason)

    # -- internals ---------------------------------------------------------

    def _host_fingerprint(self, params) -> np.ndarray:
        if self._fp_fn is None:
            self._fp_fn = jax.jit(fingerprint_tree)
        return np.asarray(jax.device_get(self._fp_fn(params)))

    def _flip_param_bit(self, trainer, bit: int) -> None:
        """Chaos drill injection: flip one (seeded) bit in the largest
        param leaf, host-side, and write it back — simulating an HBM/
        readback corruption between the step's device write and the next
        read. Deterministic given the plan seed."""
        leaves, treedef = jax.tree_util.tree_flatten(trainer.state.params)
        li = max(range(len(leaves)), key=lambda i: leaves[i].size)
        host = np.array(jax.device_get(leaves[li]))
        flat = host.reshape(-1).view(np.uint8)
        pos = (bit // 8) % flat.size
        flat[pos] ^= np.uint8(1 << (bit % 8))
        leaves[li] = jax.device_put(host, leaves[li].sharding)
        trainer.state = trainer.state.replace(
            params=jax.tree_util.tree_unflatten(treedef, leaves))
        self.flips_injected += 1
        logger.info("chaos: flipped bit %d of param leaf %d", bit, li)
