"""Preemption-safe checkpointing.

Cloud schedulers preempt with SIGTERM and a short grace window. A naive run
loses up to ``every`` steps of work; a handler that checkpoints *inside the
signal handler* corrupts in-flight async commits. :class:`PreemptionGuard`
does neither: the handler only records the request, and ``Trainer.fit``
honors it at the next step boundary with a synchronous emergency
``save_checkpoint``, then raises :class:`TrainingPreempted` — a
``SystemExit`` carrying :data:`EXIT_PREEMPTED` so an unhandled preemption
exits the process with a distinct, resumable status the launcher can key
restarts on.

The grace deadline bounds the emergency save: when storage is too slow to
finish inside the remaining grace, the save degrades to flushing in-flight
async commits (the last periodic checkpoint stays the resume point instead
of a half-written emergency tag).
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Iterable, Optional

from ..utils.logger import get_logger, log_event

logger = get_logger(__name__)

#: Resumable exit status (BSD ``EX_TEMPFAIL``): "failed for a transient
#: reason — rerun me". Distinct from 0 (done), 1 (crash) and 128+signum
#: (killed without cleanup), so launch scripts can requeue on exactly this.
EXIT_PREEMPTED = 75


class TrainingPreempted(SystemExit):
    """Raised by ``Trainer.fit`` after the emergency save. Subclasses
    ``SystemExit(EXIT_PREEMPTED)``: uncaught, the process exits resumable;
    caught, ``step``/``saved_tag`` say where training can pick up."""

    def __init__(self, step: int, saved_tag: Optional[str] = None):
        super().__init__(EXIT_PREEMPTED)
        self.step = step
        self.saved_tag = saved_tag

    def __str__(self) -> str:
        return (f"training preempted at step {self.step} "
                f"(emergency checkpoint: {self.saved_tag or 'none'}; "
                f"exit code {EXIT_PREEMPTED})")


class PreemptionGuard:
    """Turns SIGTERM/SIGINT into a step-boundary checkpoint request.

    Usage::

        guard = PreemptionGuard(checkpoint_path=ckpt_dir, grace_s=30)
        trainer = Trainer(step_fn, state, callbacks=[...],
                          preemption_guard=guard)
        trainer.fit(batches)   # raises TrainingPreempted on SIGTERM

    The handler is async-signal-safe by construction: it records a
    timestamp and sets an event — no IO, no locks. Everything heavy happens
    on the training thread at the next step boundary.

    ``signal.signal`` requires the main thread; ``install()`` raises
    elsewhere rather than silently not protecting the run.
    """

    def __init__(self, checkpoint_path: Optional[str] = None,
                 grace_s: float = 30.0,
                 signals: Iterable[int] = (signal.SIGTERM, signal.SIGINT)):
        self.checkpoint_path = checkpoint_path
        self.grace_s = float(grace_s)
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._requested_at: Optional[float] = None
        self._signum: Optional[int] = None
        self._old_handlers: dict = {}
        self.installed = False

    # ---- signal side (async-signal-safe: no IO, no allocation-heavy work)

    def _handler(self, signum, frame) -> None:
        if self._requested_at is None:
            self._requested_at = time.monotonic()
            self._signum = signum
        self._event.set()

    # ---- control side

    def install(self) -> "PreemptionGuard":
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionGuard.install() must run on the main thread "
                "(signal.signal requirement)")
        for s in self.signals:
            self._old_handlers[s] = signal.signal(s, self._handler)
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for s, old in self._old_handlers.items():
            signal.signal(s, old)
        self._old_handlers.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def remaining_grace(self) -> float:
        """Seconds of grace left for the emergency save (full grace when no
        preemption has been requested)."""
        if self._requested_at is None:
            return self.grace_s
        return max(0.0, self.grace_s
                   - (time.monotonic() - self._requested_at))

    def reset(self) -> None:
        """Clear a handled request (tests / supervisors that decide to keep
        running after draining)."""
        self._event.clear()
        self._requested_at = None
        self._signum = None

    def announce(self, step: int) -> None:
        """Log the machine-parseable preemption event (called by the
        trainer once, at the boundary that honors the request)."""
        log_event(logger, "preemption_requested", step=step,
                  signum=self._signum, grace_s=self.grace_s,
                  remaining_grace_s=round(self.remaining_grace(), 3))
