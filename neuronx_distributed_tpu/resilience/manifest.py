"""Per-tag checkpoint manifests behind verified resume.

``save_checkpoint`` writes ``manifest.json`` into the tag dir *after* the
tensor payload is durable and *before* the done-marker, so a complete tag
always carries a verifiable inventory:

.. code-block:: json

    {"version": 2, "tag": "100",
     "files": [["state/...", 4096, "<sha256>"],
               ["user_content.json", 17, "<sha256>"]],
     "meta_sha256": "..."}

``files`` lists every file under the tag dir (relative, '/'-separated)
except the done-marker and the manifest itself, with byte sizes and a
SHA-256 *content digest* of each shard — "verified resume" means verified
bytes, not just a complete inventory. Size catches truncation (the
dominant corruption after a mid-write kill); the digest catches silent
bit rot in the payload itself, which is what a watchdog rewind triggered
by an integrity mismatch must never restore (``resilience/integrity.py``).
Digesting happens once at save time on the async commit thread, off the
training critical path; verification re-reads the tag being restored —
which the restore was about to read anyway. ``meta_sha256`` is the
SHA-256 of the canonical JSON of ``files``, guarding the manifest's own
metadata.

Backends that cannot serve raw bytes (``read_bytes`` returning ``None``)
degrade to inventory+size entries. Version-1 manifests (pre-digest) and
digest-less entries still verify by size — with a once-per-process
warning that content verification was skipped.

``load_checkpoint`` verifies the manifest and, in auto-resume mode, falls
back to the newest *prior* complete tag on mismatch, logging what was
skipped. Tags saved before this format existed carry no manifest and are
accepted as-is (legacy).
"""

from __future__ import annotations

import hashlib
import json
import logging
from typing import List, Optional, Tuple

from ..trainer.checkpoint_storage import BaseCheckpointStorage

logger = logging.getLogger(__name__)

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 2

#: control-plane files excluded from the inventory: the done-marker is
#: written after the manifest, and the manifest cannot list itself.
_EXCLUDED = ("checkpoint", MANIFEST_FILE)

#: once-per-process flag: digest-less manifests (v1 tags, or backends
#: without read_bytes) are still accepted, but say so exactly once.
_warned_no_digest = False


def _meta_sha256(files: List[List]) -> str:
    canon = json.dumps(files, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def _digest(storage: BaseCheckpointStorage, tag_dir: str,
            relpath: str) -> Optional[str]:
    data = storage.read_bytes(f"{tag_dir}/{relpath}")
    if data is None:
        return None
    return hashlib.sha256(data).hexdigest()


def _warn_no_digest(reason: str) -> None:
    global _warned_no_digest
    if not _warned_no_digest:
        _warned_no_digest = True
        logger.warning(
            "checkpoint manifest carries no content digests (%s): resume "
            "verification degrades to inventory+size — re-save to upgrade "
            "to verified bytes", reason)


def build_manifest(storage: BaseCheckpointStorage, tag_dir: str,
                   tag: str) -> Optional[dict]:
    """Inventory ``tag_dir`` into a manifest dict, or ``None`` when the
    backend cannot enumerate files (verification is then skipped on load —
    never a hard failure on exotic backends). Entries are
    ``[relpath, size, sha256]``; the digest is dropped (entry shrinks to
    ``[relpath, size]``) when the backend cannot read raw bytes."""
    listing = storage.list_files(tag_dir)
    if listing is None:
        return None
    files = []
    for p, size in sorted(listing):
        if p in _EXCLUDED:
            continue
        digest = _digest(storage, tag_dir, p)
        files.append([p, int(size)] if digest is None
                     else [p, int(size), digest])
    return {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "files": files,
        "meta_sha256": _meta_sha256(files),
    }


def verify_manifest(storage: BaseCheckpointStorage, tag_dir: str,
                    manifest_path: str) -> Tuple[bool, str]:
    """``(ok, detail)``: does the tag dir match its manifest, *byte for
    byte* where digests are recorded?

    Missing manifest (legacy tag) and unenumerable backends verify
    vacuously — the commit protocol's done-marker remains the baseline
    guarantee; the manifest strengthens it where available. Digest-less
    entries (v1 manifests, digest-incapable backends) fall back to the
    size check and warn once per process.
    """
    if not storage.file_exists(manifest_path):
        return True, "no manifest (legacy tag)"
    try:
        manifest = storage.load_object(manifest_path)
    except Exception as e:
        return False, f"unreadable manifest: {e!r}"
    files = manifest.get("files")
    if not isinstance(files, list):
        return False, "malformed manifest: no file list"
    recorded_sha = manifest.get("meta_sha256")
    if recorded_sha != _meta_sha256(files):
        return False, "manifest metadata checksum mismatch"
    listing = storage.list_files(tag_dir)
    if listing is None:
        return True, "backend cannot enumerate files; skipped"
    actual = {p: int(size) for p, size in listing if p not in _EXCLUDED}
    checked = unverified = 0
    for entry in files:
        path, size = entry[0], int(entry[1])
        if path not in actual:
            return False, f"missing file {path!r}"
        if actual[path] != size:
            return False, (f"size mismatch for {path!r}: manifest {size}, "
                           f"on storage {actual[path]}")
        recorded = entry[2] if len(entry) > 2 else None
        if recorded is None:
            unverified += 1
            continue
        current = _digest(storage, tag_dir, path)
        if current is None:
            unverified += 1
            continue
        if current != recorded:
            return False, (f"content digest mismatch for {path!r}: the "
                           "shard's bytes changed after save (silent "
                           "corruption)")
        checked += 1
    if unverified:
        _warn_no_digest(f"{unverified} of {len(files)} entries under "
                        f"{tag_dir!r}")
        return True, f"ok ({checked} digests verified, {unverified} by size)"
    return True, f"ok ({checked} digests verified)"
