"""Per-tag checkpoint manifests behind verified resume.

``save_checkpoint`` writes ``manifest.json`` into the tag dir *after* the
tensor payload is durable and *before* the done-marker, so a complete tag
always carries a verifiable inventory:

.. code-block:: json

    {"version": 1, "tag": "100",
     "files": [["state/...", 4096], ["user_content.json", 17]],
     "meta_sha256": "..."}

``files`` lists every file under the tag dir (relative, '/'-separated)
except the done-marker and the manifest itself, with byte sizes.
``meta_sha256`` is the SHA-256 of the canonical JSON of ``files`` — an
integrity check over the *host-side metadata*; tensor payloads are verified
by existence + size (checksumming multi-GB TensorStore shards on every
resume would dwarf the restore itself; size catches truncation, the
dominant real-world corruption after a mid-write kill).

``load_checkpoint`` verifies the manifest and, in auto-resume mode, falls
back to the newest *prior* complete tag on mismatch, logging what was
skipped. Tags saved before this format existed carry no manifest and are
accepted as-is (legacy).
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional, Tuple

from ..trainer.checkpoint_storage import BaseCheckpointStorage

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1

#: control-plane files excluded from the inventory: the done-marker is
#: written after the manifest, and the manifest cannot list itself.
_EXCLUDED = ("checkpoint", MANIFEST_FILE)


def _meta_sha256(files: List[List]) -> str:
    canon = json.dumps(files, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def build_manifest(storage: BaseCheckpointStorage, tag_dir: str,
                   tag: str) -> Optional[dict]:
    """Inventory ``tag_dir`` into a manifest dict, or ``None`` when the
    backend cannot enumerate files (verification is then skipped on load —
    never a hard failure on exotic backends)."""
    listing = storage.list_files(tag_dir)
    if listing is None:
        return None
    files = sorted([p, int(size)] for p, size in listing
                   if p not in _EXCLUDED)
    return {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "files": files,
        "meta_sha256": _meta_sha256(files),
    }


def verify_manifest(storage: BaseCheckpointStorage, tag_dir: str,
                    manifest_path: str) -> Tuple[bool, str]:
    """``(ok, detail)``: does the tag dir match its manifest?

    Missing manifest (legacy tag) and unenumerable backends verify
    vacuously — the commit protocol's done-marker remains the baseline
    guarantee; the manifest strengthens it where available.
    """
    if not storage.file_exists(manifest_path):
        return True, "no manifest (legacy tag)"
    try:
        manifest = storage.load_object(manifest_path)
    except Exception as e:
        return False, f"unreadable manifest: {e!r}"
    files = manifest.get("files")
    if not isinstance(files, list):
        return False, "malformed manifest: no file list"
    recorded_sha = manifest.get("meta_sha256")
    if recorded_sha != _meta_sha256(files):
        return False, "manifest metadata checksum mismatch"
    listing = storage.list_files(tag_dir)
    if listing is None:
        return True, "backend cannot enumerate files; skipped"
    actual = {p: int(size) for p, size in listing if p not in _EXCLUDED}
    for entry in files:
        path, size = entry[0], int(entry[1])
        if path not in actual:
            return False, f"missing file {path!r}"
        if actual[path] != size:
            return False, (f"size mismatch for {path!r}: manifest {size}, "
                           f"on storage {actual[path]}")
    return True, "ok"
