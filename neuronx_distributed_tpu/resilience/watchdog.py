"""Training watchdog: anomaly detection with recovery policies.

A NaN loss, an exploding gradient, or a hung collective each wedge a run in
a different way: the first two silently destroy the model while steps keep
"succeeding"; the last produces no steps at all. :class:`Watchdog` is a
``Trainer`` callback covering all three:

* **non-finite loss / grad-norm** → configurable policy:

  - ``halt``: raise :class:`WatchdogHalt` (default — fail loudly);
  - ``skip_step``: roll ``trainer.state`` back to the pre-step snapshot and
    continue with the next batch (requires a non-donating ``step_fn``; the
    on-device equivalent is ``make_train_step(skip_nonfinite=True)``);
  - ``rewind``: restore the newest complete checkpoint from
    ``checkpoint_path`` and continue from there.

* **loss spikes** — rolling z-score over the last ``spike_window`` finite
  losses; a spike logs a machine-parseable event (and optionally applies
  the anomaly policy when ``spike_is_anomaly=True``).

* **stalls** — a host-side daemon thread watches a heartbeat updated at
  every step boundary; a step exceeding ``stall_timeout_s`` wall-clock
  (hung collective, stalled ``data/native_loader`` iterator) fires
  ``on_stall`` — by default logging CRITICAL and interrupting the main
  thread so the run dies visibly instead of burning a reservation.

The watchdog reads ``float(metrics[...])`` and is therefore *the* host sync
point of the loop — by design: anomaly detection needs the value, and a
single fetch per step is the price of catching divergence the step it
happens.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, Optional

from ..utils.logger import get_logger, log_event

logger = get_logger(__name__)

_POLICIES = ("halt", "skip_step", "rewind")


class WatchdogHalt(RuntimeError):
    """Training halted by the watchdog (non-finite metrics with policy
    ``halt``, or a recovery policy that ran out of budget)."""


def _state_step(state) -> Optional[int]:
    step = getattr(state, "step", None)
    if step is None and isinstance(state, dict):
        step = state.get("step")
    try:
        return None if step is None else int(step)
    except Exception:
        return None


class Watchdog:
    """See module docstring. Construct and pass via ``callbacks=[...]``."""

    #: tells Trainer.fit to keep a pre-step state snapshot for skip_step
    needs_prev_state = True

    def __init__(self, policy: str = "halt",
                 checkpoint_path: Optional[str] = None,
                 max_consecutive_skips: int = 5,
                 max_rewinds: int = 3,
                 spike_window: int = 32,
                 spike_zscore: float = 8.0,
                 spike_min_steps: int = 8,
                 spike_is_anomaly: bool = False,
                 stall_timeout_s: Optional[float] = None,
                 on_stall: Optional[Callable] = None):
        if policy not in _POLICIES:
            raise ValueError(f"unknown watchdog policy {policy!r}; "
                             f"expected one of {_POLICIES}")
        if policy == "rewind" and checkpoint_path is None:
            raise ValueError("policy='rewind' requires checkpoint_path")
        self.policy = policy
        self.checkpoint_path = checkpoint_path
        self.max_consecutive_skips = max_consecutive_skips
        self.max_rewinds = max_rewinds
        self.spike_window = spike_window
        self.spike_zscore = spike_zscore
        self.spike_min_steps = max(spike_min_steps, 2)
        self.spike_is_anomaly = spike_is_anomaly
        self.stall_timeout_s = stall_timeout_s
        self._on_stall = on_stall or self._default_on_stall
        self._losses: collections.deque = collections.deque(
            maxlen=spike_window)
        self._consecutive_skips = 0
        self._rewinds = 0
        self.anomalies = 0
        self.spikes = 0
        self.stalls = 0
        self._heartbeat = time.monotonic()
        self._stop = threading.Event()
        self._stall_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- stalls

    def _default_on_stall(self, trainer) -> None:
        logger.critical(
            "watchdog: step exceeded the %.1fs wall-clock budget — hung "
            "collective or stalled data loader; interrupting the run",
            self.stall_timeout_s)
        import _thread

        _thread.interrupt_main()

    def _stall_loop(self) -> None:
        assert self.stall_timeout_s is not None
        poll = min(1.0, self.stall_timeout_s / 4.0)
        fired_for = None
        while not self._stop.wait(poll):
            hb = self._heartbeat
            if time.monotonic() - hb > self.stall_timeout_s:
                if fired_for == hb:
                    continue  # one shot per stalled step
                fired_for = hb
                self.stalls += 1
                log_event(logger, "watchdog_stall",
                          budget_s=self.stall_timeout_s,
                          stalled_for_s=round(time.monotonic() - hb, 3))
                try:
                    self._on_stall(self._trainer)
                except Exception:
                    logger.exception("watchdog: on_stall callback failed")

    # ---------------------------------------------------- Callback hooks

    def on_train_start(self, trainer) -> None:
        self._trainer = trainer
        self._heartbeat = time.monotonic()
        if self.stall_timeout_s is not None and self._stall_thread is None:
            self._stop.clear()
            self._stall_thread = threading.Thread(
                target=self._stall_loop, daemon=True,
                name="nxd-watchdog-stall")
            self._stall_thread.start()

    def on_step_end(self, trainer, metrics: Dict) -> None:
        self._heartbeat = time.monotonic()
        loss = float(metrics.get("loss", float("nan")))
        grad_norm = float(metrics.get("grad_norm", 0.0))
        if not (math.isfinite(loss) and math.isfinite(grad_norm)):
            self._anomaly(trainer,
                          f"non-finite metrics at step {trainer.host_step}: "
                          f"loss={loss} grad_norm={grad_norm}")
            return
        self._consecutive_skips = 0
        self._check_spike(trainer, loss)
        self._losses.append(loss)

    def on_eval_end(self, trainer, metrics: Dict) -> None: ...

    def on_train_end(self, trainer) -> None:
        self._stop.set()
        if self._stall_thread is not None:
            self._stall_thread.join(timeout=5.0)
            self._stall_thread = None

    # ----------------------------------------------------------- spikes

    def _check_spike(self, trainer, loss: float) -> None:
        if len(self._losses) < self.spike_min_steps:
            return
        mean = sum(self._losses) / len(self._losses)
        var = sum((x - mean) ** 2 for x in self._losses) / len(self._losses)
        std = math.sqrt(var)
        z = (loss - mean) / max(std, 1e-8)
        if z > self.spike_zscore:
            self.spikes += 1
            log_event(logger, "watchdog_loss_spike",
                      step=trainer.host_step, loss=round(loss, 6),
                      rolling_mean=round(mean, 6), zscore=round(z, 2))
            if self.spike_is_anomaly:
                self._anomaly(trainer,
                              f"loss spike at step {trainer.host_step}: "
                              f"loss={loss:.4g} z={z:.1f}")

    # --------------------------------------------------------- anomalies

    def _anomaly(self, trainer, reason: str) -> None:
        self.anomalies += 1
        log_event(logger, "watchdog_anomaly", policy=self.policy,
                  step=trainer.host_step, reason=reason)
        if self.policy == "halt":
            raise WatchdogHalt(reason)
        if self.policy == "skip_step":
            prev = getattr(trainer, "_prev_state", None)
            if prev is None:
                raise WatchdogHalt(
                    f"{reason} — skip_step needs the pre-step state; use a "
                    "non-donating step_fn (make_train_step(donate=False)) "
                    "or the on-device skip_nonfinite=True")
            self._consecutive_skips += 1
            if self._consecutive_skips > self.max_consecutive_skips:
                raise WatchdogHalt(
                    f"{reason} — {self._consecutive_skips} consecutive "
                    "skipped steps; the run is not recovering")
            trainer.state = prev
            trainer.host_step = max(trainer.host_step - 1, 0)
            logger.warning("watchdog: skipped bad update, retrying from "
                           "step %d", trainer.host_step)
            return
        # rewind
        from ..trainer import checkpoint as ckpt

        if self._rewinds >= self.max_rewinds:
            raise WatchdogHalt(
                f"{reason} — rewound {self._rewinds} times already; "
                "the run is not recovering")
        if not ckpt.has_checkpoint(self.checkpoint_path):
            raise WatchdogHalt(
                f"{reason} — no complete checkpoint under "
                f"{self.checkpoint_path} to rewind to")
        import jax

        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding),
            trainer.state)
        trainer.state, _ = ckpt.load_checkpoint(self.checkpoint_path,
                                                tag=None, target=target)
        self._rewinds += 1
        step = _state_step(trainer.state)
        if step is not None:
            trainer.host_step = step
        self._losses.clear()
        logger.warning("watchdog: rewound to checkpoint step %s "
                       "(rewind %d/%d)", step, self._rewinds,
                       self.max_rewinds)
