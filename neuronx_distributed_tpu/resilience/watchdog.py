"""Training watchdog: anomaly detection with recovery policies.

A NaN loss, an exploding gradient, or a hung collective each wedge a run in
a different way: the first two silently destroy the model while steps keep
"succeeding"; the last produces no steps at all. :class:`Watchdog` is a
``Trainer`` callback covering all three:

* **non-finite loss / grad-norm** → configurable policy:

  - ``halt``: raise :class:`WatchdogHalt` (default — fail loudly);
  - ``skip_step``: roll ``trainer.state`` back to the pre-step snapshot and
    continue with the next batch (requires a non-donating ``step_fn``; the
    on-device equivalent is ``make_train_step(skip_nonfinite=True)``);
  - ``rewind``: restore the newest complete checkpoint from
    ``checkpoint_path`` and continue from there.

* **loss spikes** — rolling z-score over the last ``spike_window`` finite
  losses; a spike logs a machine-parseable event (and optionally applies
  the anomaly policy when ``spike_is_anomaly=True``).

* **stalls** — a host-side daemon thread watches a heartbeat updated at
  every step boundary; a step exceeding ``stall_timeout_s`` wall-clock
  (hung collective, stalled ``data/native_loader`` iterator) fires
  ``on_stall`` — by default logging CRITICAL and interrupting the main
  thread so the run dies visibly instead of burning a reservation.

The watchdog reads ``float(metrics[...])`` and is therefore *the* host sync
point of the loop — by design: anomaly detection needs the value, and a
single fetch per step is the price of catching divergence the step it
happens.

The two detection primitives are factored out as :class:`SpikeDetector`
(rolling z-score) and :class:`StallTimer` (heartbeat staleness) so the
serving-side replica health monitor (``inference/router.py``) reuses the
exact same statistics over *step latency* that training runs over *loss*.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..utils.logger import get_logger, log_event

logger = get_logger(__name__)

_POLICIES = ("halt", "skip_step", "rewind")


class WatchdogHalt(RuntimeError):
    """Training halted by the watchdog (non-finite metrics with policy
    ``halt``, or a recovery policy that ran out of budget)."""


class SpikeDetector:
    """Rolling z-score spike detector over a bounded window of finite
    observations.

    Factored out of the training watchdog so serving health monitors can
    reuse the exact same statistic: training feeds *loss*, the replica
    router (``inference/router.py``) feeds *step latency*. An observation
    is compared against the window **before** being appended, so a spike
    does not poison the baseline it is judged against — but it does enter
    the window afterwards, matching the original watchdog semantics
    (a sustained level shift stops spiking once the window absorbs it).
    """

    def __init__(self, window: int = 32, zscore: float = 8.0,
                 min_steps: int = 8):
        self.window = window
        self.zscore = zscore
        self.min_steps = max(min_steps, 2)
        self.values: collections.deque = collections.deque(maxlen=window)
        self.spikes = 0

    def __len__(self) -> int:
        return len(self.values)

    def clear(self) -> None:
        self.values.clear()

    def observe(self, value: float) -> Optional[Tuple[float, float]]:
        """Feed one finite observation. Returns ``(z, rolling_mean)`` when
        it spikes past the threshold (and counts it), else None. No spike
        is ever reported before ``min_steps`` observations exist."""
        spike = None
        if len(self.values) >= self.min_steps:
            mean = sum(self.values) / len(self.values)
            var = sum((x - mean) ** 2
                      for x in self.values) / len(self.values)
            z = (value - mean) / max(math.sqrt(var), 1e-8)
            if z > self.zscore:
                self.spikes += 1
                spike = (z, mean)
        self.values.append(value)
        return spike


class StallTimer:
    """Heartbeat-staleness detector, factored from the watchdog's stall
    thread so the serving router reuses it instead of duplicating.

    Three usage shapes share one fire-once-per-heartbeat state machine:

    * **threaded** (``start()``/``stop()``): a daemon thread polls and
      calls ``on_stall(stalled_for_s)`` when a heartbeat goes stale — the
      training-watchdog mode (a hung collective never returns control, so
      only another thread can notice);
    * **passive** (``beat()`` + ``check()``): the owner polls on its own
      schedule against an injectable ``clock`` — deterministic under the
      fake clocks serving tests drive;
    * **post-hoc** (``observe(elapsed_s)``): the owner measured a step's
      duration itself (possibly including chaos-injected virtual latency)
      and asks whether it blew the budget.
    """

    def __init__(self, timeout_s: float,
                 on_stall: Optional[Callable[[float], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "nxd-stall-timer"):
        self.timeout_s = float(timeout_s)
        self._on_stall = on_stall
        self._clock = clock
        self._name = name
        self.stalls = 0
        self._heartbeat = clock()
        self._fired_for: Optional[float] = None
        self._stop = threading.Event()
        self.thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._heartbeat = self._clock()

    def stalled_for(self) -> float:
        return self._clock() - self._heartbeat

    def check(self) -> bool:
        """True exactly once per stale heartbeat (re-arms on ``beat()``)."""
        hb = self._heartbeat
        if self._clock() - hb > self.timeout_s and self._fired_for != hb:
            self._fired_for = hb
            self.stalls += 1
            return True
        return False

    def observe(self, elapsed_s: float) -> bool:
        """Record a step that took ``elapsed_s``; True when it exceeds the
        budget. Counts every over-budget step (each is its own stall)."""
        self.beat()
        if elapsed_s > self.timeout_s:
            self.stalls += 1
            return True
        return False

    # ---- threaded mode ---------------------------------------------------

    def _loop(self) -> None:
        poll = min(1.0, self.timeout_s / 4.0)
        while not self._stop.wait(poll):
            if self.check() and self._on_stall is not None:
                try:
                    self._on_stall(self.stalled_for())
                except Exception:
                    logger.exception("stall timer: on_stall callback failed")

    def start(self) -> "StallTimer":
        if self.thread is None:
            self._stop.clear()
            self.beat()
            self.thread = threading.Thread(target=self._loop, daemon=True,
                                           name=self._name)
            self.thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.thread is not None:
            self.thread.join(timeout=5.0)
            self.thread = None


def _state_step(state) -> Optional[int]:
    step = getattr(state, "step", None)
    if step is None and isinstance(state, dict):
        step = state.get("step")
    try:
        return None if step is None else int(step)
    except Exception:
        return None


class Watchdog:
    """See module docstring. Construct and pass via ``callbacks=[...]``."""

    #: tells Trainer.fit to keep a pre-step state snapshot for skip_step
    needs_prev_state = True

    def __init__(self, policy: str = "halt",
                 checkpoint_path: Optional[str] = None,
                 max_consecutive_skips: int = 5,
                 max_rewinds: int = 3,
                 spike_window: int = 32,
                 spike_zscore: float = 8.0,
                 spike_min_steps: int = 8,
                 spike_is_anomaly: bool = False,
                 stall_timeout_s: Optional[float] = None,
                 on_stall: Optional[Callable] = None):
        if policy not in _POLICIES:
            raise ValueError(f"unknown watchdog policy {policy!r}; "
                             f"expected one of {_POLICIES}")
        if policy == "rewind" and checkpoint_path is None:
            raise ValueError("policy='rewind' requires checkpoint_path")
        self.policy = policy
        self.checkpoint_path = checkpoint_path
        self.max_consecutive_skips = max_consecutive_skips
        self.max_rewinds = max_rewinds
        self.spike_window = spike_window
        self.spike_zscore = spike_zscore
        self.spike_min_steps = max(spike_min_steps, 2)
        self.spike_is_anomaly = spike_is_anomaly
        self.stall_timeout_s = stall_timeout_s
        self._on_stall = on_stall or self._default_on_stall
        self._detector = SpikeDetector(window=spike_window,
                                       zscore=spike_zscore,
                                       min_steps=spike_min_steps)
        self._consecutive_skips = 0
        self._rewinds = 0
        self.anomalies = 0
        self._timer: Optional[StallTimer] = None
        self._stalls_base = 0  # stalls from timers already stopped

    # ------------------------------------------------------------- stalls

    @property
    def spikes(self) -> int:
        return self._detector.spikes

    @property
    def stalls(self) -> int:
        live = self._timer.stalls if self._timer is not None else 0
        return self._stalls_base + live

    @property
    def _stall_thread(self) -> Optional[threading.Thread]:
        return self._timer.thread if self._timer is not None else None

    def _default_on_stall(self, trainer) -> None:
        logger.critical(
            "watchdog: step exceeded the %.1fs wall-clock budget — hung "
            "collective or stalled data loader; interrupting the run",
            self.stall_timeout_s)
        import _thread

        _thread.interrupt_main()

    def _handle_stall(self, stalled_for_s: float) -> None:
        log_event(logger, "watchdog_stall", budget_s=self.stall_timeout_s,
                  stalled_for_s=round(stalled_for_s, 3))
        self._on_stall(self._trainer)

    # ---------------------------------------------------- Callback hooks

    def on_train_start(self, trainer) -> None:
        self._trainer = trainer
        if self.stall_timeout_s is not None and self._timer is None:
            self._timer = StallTimer(self.stall_timeout_s,
                                     on_stall=self._handle_stall,
                                     name="nxd-watchdog-stall").start()

    def on_step_end(self, trainer, metrics: Dict) -> None:
        if self._timer is not None:
            self._timer.beat()
        loss = float(metrics.get("loss", float("nan")))
        grad_norm = float(metrics.get("grad_norm", 0.0))
        if not (math.isfinite(loss) and math.isfinite(grad_norm)):
            self._anomaly(trainer,
                          f"non-finite metrics at step {trainer.host_step}: "
                          f"loss={loss} grad_norm={grad_norm}")
            return
        self._consecutive_skips = 0
        self._check_spike(trainer, loss)

    def on_eval_end(self, trainer, metrics: Dict) -> None: ...

    def on_train_end(self, trainer) -> None:
        if self._timer is not None:
            self._timer.stop()
            self._stalls_base += self._timer.stalls
            self._timer = None

    # ----------------------------------------------------------- spikes

    def _check_spike(self, trainer, loss: float) -> None:
        spike = self._detector.observe(loss)
        if spike is not None:
            z, mean = spike
            log_event(logger, "watchdog_loss_spike",
                      step=trainer.host_step, loss=round(loss, 6),
                      rolling_mean=round(mean, 6), zscore=round(z, 2))
            if self.spike_is_anomaly:
                self._anomaly(trainer,
                              f"loss spike at step {trainer.host_step}: "
                              f"loss={loss:.4g} z={z:.1f}")

    # --------------------------------------------------------- anomalies

    def report_anomaly(self, trainer, reason: str) -> None:
        """External anomaly entry point: other detectors (the integrity
        monitor's fingerprint mismatch, a custom data-quality check) feed
        the same halt / skip_step / rewind discipline as the built-in
        non-finite and spike detectors — one recovery policy, one rewind
        budget, regardless of who detected the problem."""
        self._anomaly(trainer, reason)

    def _anomaly(self, trainer, reason: str) -> None:
        self.anomalies += 1
        log_event(logger, "watchdog_anomaly", policy=self.policy,
                  step=trainer.host_step, reason=reason)
        if self.policy == "halt":
            raise WatchdogHalt(reason)
        if self.policy == "skip_step":
            prev = getattr(trainer, "_prev_state", None)
            if prev is None:
                raise WatchdogHalt(
                    f"{reason} — skip_step needs the pre-step state; use a "
                    "non-donating step_fn (make_train_step(donate=False)) "
                    "or the on-device skip_nonfinite=True")
            self._consecutive_skips += 1
            if self._consecutive_skips > self.max_consecutive_skips:
                raise WatchdogHalt(
                    f"{reason} — {self._consecutive_skips} consecutive "
                    "skipped steps; the run is not recovering")
            trainer.state = prev
            trainer.host_step = max(trainer.host_step - 1, 0)
            logger.warning("watchdog: skipped bad update, retrying from "
                           "step %d", trainer.host_step)
            return
        # rewind
        from ..trainer import checkpoint as ckpt

        if self._rewinds >= self.max_rewinds:
            raise WatchdogHalt(
                f"{reason} — rewound {self._rewinds} times already; "
                "the run is not recovering")
        # quiesce in-flight async saves first: the newest verified tag is
        # often the one committed by this very boundary's CheckpointCallback
        ckpt.finalize_checkpoint()
        if not ckpt.has_checkpoint(self.checkpoint_path):
            raise WatchdogHalt(
                f"{reason} — no complete checkpoint under "
                f"{self.checkpoint_path} to rewind to")
        import jax

        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding),
            trainer.state)
        trainer.state, _ = ckpt.load_checkpoint(self.checkpoint_path,
                                                tag=None, target=target)
        self._rewinds += 1
        step = _state_step(trainer.state)
        if step is not None:
            trainer.host_step = step
        self._detector.clear()
        logger.warning("watchdog: rewound to checkpoint step %s "
                       "(rewind %d/%d)", step, self._rewinds,
                       self.max_rewinds)
