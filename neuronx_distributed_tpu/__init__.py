"""neuronx_distributed_tpu — a TPU-native distributed training & inference
framework with the capabilities of aws-neuron/neuronx-distributed, built on
JAX/XLA/Pallas.

Public API mirrors the reference's top-level exports
(``src/neuronx_distributed/__init__.py:1-19``).
"""

from .config import (
    NxDConfig,
    ParallelConfig,
    OptimizerConfig,
    MixedPrecisionConfig,
    ActivationCheckpointConfig,
    PipelineConfig,
    CheckpointConfig,
    neuronx_distributed_config,
    configure_model,
)
from . import obs
from . import parallel
from . import inference
from . import lora
from . import quantization
from . import utils
from . import data
from . import plan
from . import scripts

__version__ = "0.1.0"

__all__ = [
    "NxDConfig",
    "ParallelConfig",
    "OptimizerConfig",
    "MixedPrecisionConfig",
    "ActivationCheckpointConfig",
    "PipelineConfig",
    "CheckpointConfig",
    "neuronx_distributed_config",
    "configure_model",
    "obs",
    "parallel",
    "inference",
    "lora",
    "quantization",
    "utils",
    "data",
    "plan",
    "scripts",
]
