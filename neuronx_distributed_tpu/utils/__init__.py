"""Utilities (reference: ``utils/``)."""

from . import batch_utils
from . import logger
from . import tensor_capture
from . import timeline
from .logger import get_logger, rmsg

__all__ = ["batch_utils", "logger", "tensor_capture", "timeline",
           "get_logger", "rmsg"]
