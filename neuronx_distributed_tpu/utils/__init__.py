"""Utilities (reference: ``utils/``)."""

from . import batch_utils

__all__ = ["batch_utils"]
