"""Remat (activation checkpointing) policy resolution.

Analogue of the reference's activation-checkpoint config plumbing
(``trainer/trainer.py:147`` applying ``activation_checkpoint_config``): a
single place mapping policy NAMES to ``jax.checkpoint_policies`` so model
configs stay JSON-serialisable.

Policy guide (v5e, 350M llama slice, bs=8 seq=2048, measured r3):

* ``"nothing"`` — recompute everything (min memory; the r2 default);
* ``"dots"`` — save matmul outputs without batch dims
  (``dots_with_no_batch_dims_saveable``): +3.6% step throughput over
  "nothing" at modest extra memory — the better default when activations
  fit;
* ``"save_attention"`` — save the flash-attention outputs + log-sum-exp
  (named residuals ``flash_out``/``flash_lse`` tagged in
  ``ops/flash_attention.py::_flash_pallas_vjp_fwd``) so the backward skips
  re-running the attention forward kernel — the single biggest recompute
  item (~13% of step compute at bench shapes);
* ``"dots_and_attention"`` — the union of "dots" and "save_attention"
  (``save_from_both_policies``): both measured levers at once, for when
  activation memory allows (``tpu_bench_sweep.py`` has its sweep column);
* any other name resolves via ``getattr(jax.checkpoint_policies, name)``.
"""

from __future__ import annotations

import jax

_ALIASES = {
    "nothing": "nothing_saveable",
    "dots": "dots_with_no_batch_dims_saveable",
    "dots_batch": "dots_saveable",
}

# named-residual policies: factory calls, not plain attributes
_NAMED = {
    "save_attention": ("flash_out", "flash_lse"),
}

# unions of other registry entries (save_from_both_policies)
_COMBINED = {
    "dots_and_attention": ("dots", "save_attention"),
}


def resolve_remat_policy(name: str = "nothing"):
    """Policy name -> jax.checkpoint policy callable."""
    if name in _COMBINED:
        return jax.checkpoint_policies.save_from_both_policies(
            *(resolve_remat_policy(part) for part in _COMBINED[name]))
    if name in _NAMED:
        return jax.checkpoint_policies.save_only_these_names(*_NAMED[name])
    resolved = _ALIASES.get(name, name)
    try:
        return getattr(jax.checkpoint_policies, resolved)
    except AttributeError as e:
        raise ValueError(
            f"unknown remat policy {name!r} (aliases: "
            f"{sorted(_ALIASES) + sorted(_NAMED) + sorted(_COMBINED)}; "
            "else any jax.checkpoint_policies name)") from e


def validate_remat_policy(name: str) -> None:
    """Raise ValueError for unknown policy names (config __post_init__
    hook); resolution itself is deferred to model build time."""
    resolve_remat_policy(name)
