"""Remat (activation checkpointing) policy resolution.

Analogue of the reference's activation-checkpoint config plumbing
(``trainer/trainer.py:147`` applying ``activation_checkpoint_config``): a
single place mapping policy NAMES to ``jax.checkpoint_policies`` so model
configs stay JSON-serialisable.

Policy guide (v5e, 350M llama slice, bs=8 seq=2048, measured r3):

* ``"nothing"`` — recompute everything (min memory; the r2 default);
* ``"dots"`` — save matmul outputs without batch dims
  (``dots_with_no_batch_dims_saveable``): +3.6% step throughput over
  "nothing" at modest extra memory — the better default when activations
  fit;
* any other name resolves via ``getattr(jax.checkpoint_policies, name)``.
"""

from __future__ import annotations

import jax

_ALIASES = {
    "nothing": "nothing_saveable",
    "dots": "dots_with_no_batch_dims_saveable",
    "dots_batch": "dots_saveable",
}


def resolve_remat_policy(name: str = "nothing"):
    """Policy name -> jax.checkpoint policy callable."""
    resolved = _ALIASES.get(name, name)
    try:
        return getattr(jax.checkpoint_policies, resolved)
    except AttributeError as e:
        raise ValueError(
            f"unknown remat policy {name!r} (aliases: "
            f"{sorted(_ALIASES)}; else any jax.checkpoint_policies "
            "name)") from e
