"""Debug tensor capture and replacement.

Analogue of the reference's ``utils/tensor_capture/`` (hook-based capture of
intermediate tensors, ``api.py:16``) and ``utils/tensor_replacement/``
(inject replacement tensors into the forward). Flax provides both natively:

* capture: ``module.apply(..., capture_intermediates=...)`` records every
  (or a filtered set of) submodule output into the ``intermediates``
  collection;
* replacement: :func:`apply_with_replacements` swaps chosen param leaves
  before the forward (the functional analogue of hooking a module input).

Plus :func:`max_diff`, the reference's capture-comparison helper for
debugging parallel-vs-reference divergence.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def capture_intermediates(module, variables, *args,
                          filter_fn: Optional[Callable] = None,
                          method=None, **kwargs) -> Tuple[Any, Dict]:
    """Run a forward capturing intermediate outputs.

    Returns ``(outputs, intermediates)`` where intermediates is a nested
    dict of sown tensors keyed by module path (reference
    ``enable_tensor_capture``).
    """
    flt = filter_fn if filter_fn is not None else (lambda mdl, m: True)
    out, mods = module.apply(variables, *args, method=method,
                             capture_intermediates=flt,
                             mutable=["intermediates"], **kwargs)
    return out, mods.get("intermediates", {})


def apply_with_replacements(module, variables, replacements: Dict[str, Any],
                            *args, method=None, **kwargs):
    """Forward with selected param leaves replaced (reference
    ``tensor_replacement``). ``replacements`` maps '/'-joined param paths
    (e.g. ``"params/model/norm/scale"``) to arrays."""
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): leaf
            for path, leaf in jax.tree_util.tree_leaves_with_path(variables)}
    missing = set(replacements) - set(flat)
    if missing:
        raise KeyError(f"replacement paths not found: {sorted(missing)}; "
                       f"available e.g. {sorted(flat)[:5]}")

    def substitute(path, leaf):
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        return replacements.get(key, leaf)

    patched = jax.tree_util.tree_map_with_path(substitute, variables)
    return module.apply(patched, *args, method=method, **kwargs)


def max_diff(a: Any, b: Any) -> Dict[str, float]:
    """Max abs difference per leaf between two pytrees (reference capture
    comparison)."""
    out = {}
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = dict(jax.tree_util.tree_leaves_with_path(b))
    for path, leaf in fa:
        other = fb.get(path)
        key = jax.tree_util.keystr(path)
        if other is None:
            out[key] = float("nan")
        else:
            out[key] = float(jnp.max(jnp.abs(
                jnp.asarray(leaf, jnp.float32)
                - jnp.asarray(other, jnp.float32))))
    return out
