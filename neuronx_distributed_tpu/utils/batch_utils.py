"""Context-parallel batch utilities.

Analogue of the reference's ``utils/batch_utils.py`` (``shift_labels:4``,
``get_batch_on_this_context_parallel_rank:19``): labels are shifted BEFORE
the sequence is sliced across cp ranks, so token ``t``'s label (token
``t+1``) stays on the same shard even at slice boundaries.

Host-side slicing is only needed when feeding pre-sharded per-rank data; in
the SPMD path the same slicing happens declaratively via a
``PartitionSpec(dp, cp)`` on the batch's sequence dim.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np


def shift_labels(labels, ignore_index: int = -100):
    """Shift left by one for next-token prediction (reference
    ``shift_labels:4``)."""
    shifted = np.roll(np.asarray(labels), -1, axis=1).copy()
    shifted[:, -1] = ignore_index
    return shifted


def get_batch_on_this_context_parallel_rank(batch: Dict, cp_rank: int,
                                            cp_size: int) -> Dict:
    """Slice every [B, S, ...] tensor's sequence dim for one cp rank
    (reference ``get_batch_on_this_context_parallel_rank:19``)."""
    if cp_size == 1:
        return batch
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if v.ndim >= 2:
            if v.shape[1] % cp_size != 0:
                raise ValueError(
                    f"batch[{k!r}] sequence length {v.shape[1]} not "
                    f"divisible by cp_size {cp_size}")
            chunk = v.shape[1] // cp_size
            out[k] = v[:, cp_rank * chunk:(cp_rank + 1) * chunk]
        else:
            out[k] = v
    return out
