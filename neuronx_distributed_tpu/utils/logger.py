"""Rank-aware logging.

Analogue of the reference's ``utils/logger.py`` (``get_logger:52``,
env-controlled level via ``NXD_LOG_LEVEL``) and the ``rmsg`` rank-prefix
helper (``parallel_state.py:1648-1682``). In single-controller JAX there is
one process per host (not per chip); "rank 0" gating maps to
``jax.process_index() == 0``.
"""

from __future__ import annotations

import logging
import os

_LOGGERS = {}


class _Rank0Filter(logging.Filter):
    """Drop sub-WARNING records on non-zero processes.

    The process-index check runs lazily at emit time: ``get_logger`` is
    called at module import all over the package, and ``jax.process_index``
    initializes the XLA backend — which would freeze device flags (e.g.
    ``--xla_force_host_platform_device_count``) before callers get a chance
    to set them. An uninitialized backend means we can't know the rank yet,
    so the record passes through rather than forcing initialization.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            return True
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return True
            import jax

            return jax.process_index() == 0
        except Exception:
            return True


_RANK0_FILTER = _Rank0Filter()


_WARNED_BAD_LEVELS = set()


def get_log_level() -> int:
    raw = os.environ.get("NXD_LOG_LEVEL", "INFO")
    level = getattr(logging, raw.upper(), None)
    if isinstance(level, int) and not isinstance(level, bool):
        return level
    # Bad value: fall back to INFO, but say so (once per offending value)
    # instead of silently swallowing the typo forever.
    if raw not in _WARNED_BAD_LEVELS:
        _WARNED_BAD_LEVELS.add(raw)
        logging.getLogger("neuronx_distributed_tpu").warning(
            "NXD_LOG_LEVEL=%r is not a valid logging level; "
            "falling back to INFO", raw)
    return logging.INFO


def get_logger(name: str = "neuronx_distributed_tpu",
               rank0_only: bool = True) -> logging.Logger:
    """Reference ``get_logger:52``: on non-zero processes, rank0_only
    loggers drop everything below WARNING."""
    key = (name, rank0_only)
    if key in _LOGGERS:
        logger = _LOGGERS[key]
        # Re-resolve the level on every call: NXD_LOG_LEVEL may have
        # changed since the logger was first built (tests, notebooks,
        # long-lived drivers) and caching the first value forever made
        # the env knob a one-shot.
        level = get_log_level()
        if logger.level != level:
            logger.setLevel(level)
        return logger
    logger = logging.getLogger(name)
    logger.setLevel(get_log_level())
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
        logger.addHandler(h)
        logger.propagate = False
    if rank0_only and _RANK0_FILTER not in logger.filters:
        logger.addFilter(_RANK0_FILTER)
    _LOGGERS[key] = logger
    return logger


def log_event(logger: logging.Logger, event: str, **fields) -> None:
    """One-line machine-parseable event record: ``NXD_EVENT {json}``.

    The resilience subsystem (preemption, watchdog, chaos drills) emits its
    operational events through this so ``bench.py`` and launch tooling can
    grep/parse them without scraping free-form log text. WARNING level:
    rank0_only loggers on non-zero processes drop below WARNING, and a
    resilience event from *any* rank must stay visible.

    Routed through the ``obs`` event channel: the same call also bumps
    ``nxd_events_total{event=...}`` and fans out to subscribers, so the
    NXD_EVENT log lines and the metrics registry share one source of
    truth. The log-line format is unchanged.
    """
    from ..obs.events import emit_event  # lazy: obs imports this module

    emit_event(event, logger=logger, **fields)


def rmsg(msg: str) -> str:
    """Prefix a message with the mesh position (reference ``rmsg``:
    tp/pp/dp rank prefix). Host-side: reports process index and mesh shape;
    per-shard ranks only exist inside shard_map."""
    try:
        import jax

        from ..parallel import mesh as ps

        if ps.model_parallel_is_initialized():
            shape = dict(ps.get_mesh().shape)
            return f"[proc {jax.process_index()} mesh {shape}] {msg}"
        return f"[proc {jax.process_index()}] {msg}"
    except Exception:
        return msg
