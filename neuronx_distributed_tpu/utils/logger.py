"""Rank-aware logging.

Analogue of the reference's ``utils/logger.py`` (``get_logger:52``,
env-controlled level via ``NXD_LOG_LEVEL``) and the ``rmsg`` rank-prefix
helper (``parallel_state.py:1648-1682``). In single-controller JAX there is
one process per host (not per chip); "rank 0" gating maps to
``jax.process_index() == 0``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

_LOGGERS = {}


class _Rank0Filter(logging.Filter):
    """Drop sub-WARNING records on non-zero processes.

    The process-index check runs lazily at emit time: ``get_logger`` is
    called at module import all over the package, and ``jax.process_index``
    initializes the XLA backend — which would freeze device flags (e.g.
    ``--xla_force_host_platform_device_count``) before callers get a chance
    to set them. An uninitialized backend means we can't know the rank yet,
    so the record passes through rather than forcing initialization.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            return True
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return True
            import jax

            return jax.process_index() == 0
        except Exception:
            return True


_RANK0_FILTER = _Rank0Filter()


def get_log_level() -> int:
    level = os.environ.get("NXD_LOG_LEVEL", "INFO").upper()
    return getattr(logging, level, logging.INFO)


def get_logger(name: str = "neuronx_distributed_tpu",
               rank0_only: bool = True) -> logging.Logger:
    """Reference ``get_logger:52``: on non-zero processes, rank0_only
    loggers drop everything below WARNING."""
    key = (name, rank0_only)
    if key in _LOGGERS:
        return _LOGGERS[key]
    logger = logging.getLogger(name)
    logger.setLevel(get_log_level())
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
        logger.addHandler(h)
        logger.propagate = False
    if rank0_only and _RANK0_FILTER not in logger.filters:
        logger.addFilter(_RANK0_FILTER)
    _LOGGERS[key] = logger
    return logger


def log_event(logger: logging.Logger, event: str, **fields) -> None:
    """One-line machine-parseable event record: ``NXD_EVENT {json}``.

    The resilience subsystem (preemption, watchdog, chaos drills) emits its
    operational events through this so ``bench.py`` and launch tooling can
    grep/parse them without scraping free-form log text. WARNING level:
    rank0_only loggers on non-zero processes drop below WARNING, and a
    resilience event from *any* rank must stay visible.
    """
    payload = {"event": event, **fields}
    logger.warning("NXD_EVENT %s",
                   json.dumps(payload, sort_keys=True, default=str))


def rmsg(msg: str) -> str:
    """Prefix a message with the mesh position (reference ``rmsg``:
    tp/pp/dp rank prefix). Host-side: reports process index and mesh shape;
    per-shard ranks only exist inside shard_map."""
    try:
        import jax

        from ..parallel import mesh as ps

        if ps.model_parallel_is_initialized():
            shape = dict(ps.get_mesh().shape)
            return f"[proc {jax.process_index()} mesh {shape}] {msg}"
        return f"[proc {jax.process_index()}] {msg}"
    except Exception:
        return msg
