"""Execution timelines and profiling glue — thin shims over ``obs``.

Analogue of the reference's chrome-trace ``Timeline`` (``utils/timeline.py:
15-141``) and ``PPTimeline`` (``pipeline/timeline.py:10``). The actual
recorder now lives in ``neuronx_distributed_tpu.obs.tracing.SpanTracer``;
this module keeps the historical names so existing callers and scripts
keep working. New code should use ``obs.get_tracer()`` directly — it adds
nested spans with attributes, per-span-name latency stats, and shares the
process-wide enable switch with the metrics registry.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from ..obs.tracing import SpanTracer


class Timeline:
    """Host-side chrome-trace event recorder (reference ``Timeline``).

    Shim over a private :class:`SpanTracer`. Keeping a tracer per
    Timeline preserves the old semantics: separate Timelines do not see
    each other's events and carry their own ``enabled`` flag independent
    of the global ``obs`` switch.

    ``save`` snapshots under the tracer lock and emits still-open spans
    as zero-duration ``{"incomplete": true}`` events — the previous
    implementation read the event list without the lock (racing writer
    threads) and silently dropped open spans.
    """

    def __init__(self, output_file: str = "timeline.json",
                 enabled: bool = True):
        self.output_file = output_file
        self._tracer = SpanTracer(enabled=enabled)

    @property
    def enabled(self) -> bool:
        return self._tracer.enabled

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self._tracer.enabled = value

    def mark_event_start(self, name: str) -> None:
        self._tracer.mark_event_start(name)

    def mark_event_end(self, name: str) -> None:
        self._tracer.mark_event_end(name)

    def event(self, name: str):
        return self._tracer.event(name)

    def save(self, path: Optional[str] = None) -> str:
        return self._tracer.save(path or self.output_file)


@contextlib.contextmanager
def profile_step(logdir: str = "/tmp/nxd_profile"):
    """Capture an XLA device trace for the enclosed step(s); view with
    Perfetto / TensorBoard (SURVEY §5: 'jax.profiler traces + Perfetto').

    Shim over ``obs.get_tracer().profile_step`` — the device trace is
    additionally recorded as a host span (with the logdir attribute) when
    tracing is enabled.
    """
    from ..obs.tracing import get_tracer

    with get_tracer().profile_step(logdir) as d:
        yield d
