"""Execution timelines and profiling glue.

Analogue of the reference's chrome-trace ``Timeline`` (``utils/timeline.py:
15-141``: mark_event_start/end, per-step JSON chrome events) and
``PPTimeline`` (``pipeline/timeline.py:10``). On TPU the heavy lifting is
``jax.profiler`` (XLA traces viewable in Perfetto/TensorBoard); this module
keeps the reference's lightweight host-side event timeline for schedule
debugging, and wraps the jax profiler for one-call step captures.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class Timeline:
    """Host-side chrome-trace event recorder (reference ``Timeline``)."""

    def __init__(self, output_file: str = "timeline.json",
                 enabled: bool = True):
        self.output_file = output_file
        self.enabled = enabled
        self._events: List[Dict[str, Any]] = []
        self._open: Dict[str, float] = {}
        self._lock = threading.Lock()

    def mark_event_start(self, name: str) -> None:
        if self.enabled:
            with self._lock:
                self._open[name] = time.perf_counter_ns() / 1000.0

    def mark_event_end(self, name: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            start = self._open.pop(name, None)
            if start is None:
                return
            now = time.perf_counter_ns() / 1000.0
            self._events.append({
                "name": name, "ph": "X", "ts": start, "dur": now - start,
                "pid": os.getpid(), "tid": threading.get_ident() % 10000,
            })

    @contextlib.contextmanager
    def event(self, name: str):
        self.mark_event_start(name)
        try:
            yield
        finally:
            self.mark_event_end(name)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.output_file
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events}, f)
        return path


@contextlib.contextmanager
def profile_step(logdir: str = "/tmp/nxd_profile"):
    """Capture an XLA device trace for the enclosed step(s); view with
    Perfetto / TensorBoard (SURVEY §5: 'jax.profiler traces + Perfetto')."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
