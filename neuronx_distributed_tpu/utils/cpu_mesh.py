"""Force the host (CPU) backend with a virtual multi-device mesh.

The axon TPU plugin's sitecustomize overrides the ``JAX_PLATFORMS`` env var,
and its backend init can hang indefinitely when the tunnel is wedged
(observed 2026-07-28: even ``jax.devices()`` blocked forever).  The
in-process config update below is the only reliable way to bypass it; it
must run before the first backend use.  ``XLA_FLAGS`` is likewise read at
backend init, so topping up the virtual device count here works as long as
no jax computation ran earlier in this process.

Single home for the workaround used by ``tests/conftest.py``,
``__graft_entry__.dryrun_multichip`` and ``bench.py``'s CPU fallback.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int = 8) -> None:
    """Pin jax to the CPU backend with >= ``n_devices`` virtual devices.

    Must be called before the first backend use in the process.  If an
    ``xla_force_host_platform_device_count`` flag is already present with a
    smaller count, it is raised to ``n_devices``; a larger count is kept.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_COUNT_FLAG}={n_devices}")

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialised; caller's device check decides
