"""Optimizer construction with ZeRO-1 sharding and grad clipping.

Analogue of the reference's ``trainer/optimizer.py`` (``NxDOptimizer:10``) and
``optimizer/zero_redundancy_optimizer.py`` (``NeuronZero1Optimizer:30``).

TPU-native ZeRO-1: the reference subclasses torch_xla's
``ZeroRedundancyOptimizer`` to reduce-scatter grads over DP, update a local
shard, and all-gather params. Under GSPMD the same dataflow is *declarative*:
optimizer state (Adam moments + master weights) is given a sharding that
additionally partitions over the ``dp`` (× ``cp``, reference
``parallel_state.py:1684``) axes, and XLA inserts the reduce-scatter /
all-gather pair around the update. No optimizer subclass needed — just
sharding specs.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec

from ..config import NxDConfig
from ..parallel import comm_compressed as cc
from ..parallel import mesh as ps


def make_optimizer(cfg: NxDConfig, learning_rate: Any = 1e-4,
                   weight_decay: float = 0.01,
                   b1: float = 0.9, b2: float = 0.95,
                   eps: float = 1e-8) -> optax.GradientTransformation:
    """AdamW with optional global-norm clipping (reference:
    ``optimizer_config`` grad_clipping/max_grad_norm,
    ``trainer/optimizer.py:122`` + ``grads.py:192``)."""
    chain = []
    if cfg.optimizer.grad_clipping:
        chain.append(optax.clip_by_global_norm(cfg.optimizer.max_grad_norm))
    chain.append(optax.adamw(learning_rate=learning_rate, b1=b1, b2=b2,
                             eps=eps, weight_decay=weight_decay))
    return optax.chain(*chain)


def _zero1_extend_spec(spec: PartitionSpec, shape: Tuple[int, ...],
                       zero_axes: Tuple[str, ...]) -> PartitionSpec:
    """Extend a param PartitionSpec so the largest unsharded dim is also
    partitioned over the ZeRO axes (dp×cp), if divisible.

    Expert-view specs (naming ``ep``/``dp_exp``) live on the expert mesh,
    whose data-parallel dimension is ``dp_exp``: their optimizer state is
    ZeRO-sharded over expert-DP instead (reference
    ``NeuronEPZero1Optimizer``, ``zero_redundancy_optimizer.py:163``).
    """
    if not shape:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    sizes = {**dict(zip(("pp", "dp", "cp", "tp"),
                        (1, 1, 1, 1)))}
    expert_view = ps.spec_uses_expert_axes(spec)
    if expert_view:
        zero_axes = (ps.EXP_DP_AXIS,)
    if ps.model_parallel_is_initialized():
        m = ps.get_expert_mesh() if expert_view else ps.get_mesh()
        sizes = {k: m.shape[k] for k in m.axis_names}
    zero_size = 1
    for a in zero_axes:
        zero_size *= sizes.get(a, 1)
    if zero_size == 1:
        return spec
    # pick the largest dim not already sharded whose size divides evenly
    cand = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in cand:
        if parts[i] is None and shape[i] % zero_size == 0 and shape[i] >= zero_size:
            parts[i] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
            return PartitionSpec(*parts)
    return spec


def zero1_state_specs(opt_state: Any, param_specs: Any,
                      param_shapes: Any,
                      zero_axes: Tuple[str, ...] = (ps.DP_AXIS, ps.CP_AXIS),
                      enabled: bool = True) -> Any:
    """Sharding specs for the optimizer state pytree.

    Any subtree of the optimizer state whose structure equals the params tree
    (Adam ``mu``/``nu``, master weights) gets the param specs — extended over
    the ZeRO axes when ``enabled`` — and everything else (step counters, …)
    is replicated. The merged dp×cp ZeRO sharding group matches the
    reference's (``parallel_state.py:1684``).
    """
    params_treedef = jax.tree_util.tree_structure(param_specs)

    def extended_specs():
        if not enabled:
            return param_specs
        return jax.tree_util.tree_map(
            lambda spec, shape: _zero1_extend_spec(
                spec, tuple(shape.shape) if hasattr(shape, "shape")
                else tuple(shape), zero_axes),
            param_specs, param_shapes)

    ext = extended_specs()

    # Recursive structural walk: substitute param-shaped subtrees, replicate
    # every other leaf (step counters etc.).
    def rec(node):
        try:
            if jax.tree_util.tree_structure(node) == params_treedef:
                return ext
        except Exception:
            pass
        children, treedef = jax.tree_util.tree_flatten(
            node, is_leaf=lambda x: x is not node)
        if jax.tree_util.treedef_is_leaf(treedef):
            return PartitionSpec()
        return jax.tree_util.tree_unflatten(
            treedef, [rec(c) for c in children])

    return rec(opt_state)


# ---------------------------------------------------------------------------
# Explicit ZeRO-1 gradient dataflow (reference NeuronZero1Optimizer:30 —
# reduce-scatter grads over DP, update the local 1/N shard, all-gather the
# updated params). The declarative zero1_state_specs path above lets GSPMD
# insert this pair itself but always at fp32; these helpers ARE the
# reduce-scatter / all-gather, so they can ride the compressed collectives.
# Both run *inside* shard_map over the zero axes (leaves replicated across
# them, the usual explicit-path layout).
# ---------------------------------------------------------------------------

def zero1_reduce_scatter_gradients(
    grads: Any,
    zero_axes: Tuple[str, ...] = (ps.DP_AXIS, ps.CP_AXIS),
    compression: Optional[cc.CompressionConfig] = None,
    error: Optional[Any] = None,
) -> Any:
    """Mean-reduce each gradient leaf over the zero axes and keep this
    rank's flat 1/N chunk (zero-padded to whole quantization blocks).

    ``compression`` selects the wire dtype (None = fp32); ``error`` is the
    per-rank error-feedback tree (leaf shapes match ``grads``) — when given,
    returns ``(chunks, new_error)``. Feed the chunks to the local optimizer
    shard and rebuild params with :func:`zero1_all_gather_params`.
    """
    if error is None:
        return jax.tree_util.tree_map(
            lambda g: cc.reduce_scatter_flat(
                g, zero_axes, config=compression, op="mean"), grads)
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(error)
    outs = [cc.reduce_scatter_flat(g, zero_axes, config=compression,
                                   op="mean", error=e)
            for g, e in zip(flat_g, flat_e)]
    chunks = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
    new_error = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
    return chunks, new_error


def zero1_all_gather_params(
    chunks: Any,
    shapes: Any,
    zero_axes: Tuple[str, ...] = (ps.DP_AXIS, ps.CP_AXIS),
    compression: Optional[cc.CompressionConfig] = None,
) -> Any:
    """Inverse of :func:`zero1_reduce_scatter_gradients`: gather every
    rank's flat chunk, drop block padding, reshape to ``shapes`` (a tree of
    shape tuples or template arrays). Quantizing this leg compresses the
    param all-gather exactly like ZeRO++'s qwZ."""
    def gather(c, s):
        shape = tuple(s.shape) if hasattr(s, "shape") else tuple(s)
        return cc.all_gather_flat(c, shape, zero_axes, config=compression)

    return jax.tree_util.tree_map(
        gather, chunks, shapes,
        is_leaf=lambda x: isinstance(x, (tuple, list)))
