"""Learning-rate schedules.

The reference's launchers drive ``get_linear_schedule_with_warmup`` /
cosine variants from ``transformers.optimization``
(``examples/training/llama/tp_zero1_llama_hf_pretrain/tp_zero1_llama_hf_pretrain.py:38``).
Here they are optax schedules, passed directly as the ``learning_rate`` of
:func:`.trainer.initialize_parallel_optimizer` (optax treats a callable lr
as a per-step schedule; the count lives in the optimizer state, so resume
restores it).
"""

from __future__ import annotations

import optax


def linear_warmup_linear_decay(peak_lr: float, warmup_steps: int,
                               total_steps: int,
                               end_lr: float = 0.0) -> optax.Schedule:
    """The reference's default pretraining schedule
    (``get_linear_schedule_with_warmup``)."""
    return optax.join_schedules([
        optax.linear_schedule(0.0, peak_lr, max(warmup_steps, 1)),
        optax.linear_schedule(peak_lr, end_lr,
                              max(total_steps - warmup_steps, 1)),
    ], boundaries=[warmup_steps])


def linear_warmup_cosine_decay(peak_lr: float, warmup_steps: int,
                               total_steps: int,
                               end_lr_ratio: float = 0.1) -> optax.Schedule:
    """Warmup + cosine decay (the reference's cosine variant)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr, warmup_steps=warmup_steps,
        decay_steps=total_steps, end_value=peak_lr * end_lr_ratio)
