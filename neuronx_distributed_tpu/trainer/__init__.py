"""Training orchestration (reference: ``trainer/`` + ``optimizer/``)."""

from . import optimizer
from . import trainer
from .trainer import (
    TrainState,
    ParallelModel,
    initialize_parallel_model,
    initialize_parallel_optimizer,
    make_train_step,
)

__all__ = [
    "optimizer",
    "trainer",
    "TrainState",
    "ParallelModel",
    "initialize_parallel_model",
    "initialize_parallel_optimizer",
    "make_train_step",
]
