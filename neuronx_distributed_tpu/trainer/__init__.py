"""Training orchestration (reference: ``trainer/`` + ``optimizer/``)."""

from . import optimizer
from . import schedules
from . import trainer
from .schedules import (
    linear_warmup_cosine_decay,
    linear_warmup_linear_decay,
)
from .trainer import (
    TrainState,
    ParallelModel,
    initialize_parallel_model,
    initialize_parallel_optimizer,
    make_train_step,
)

__all__ = [
    "optimizer",
    "schedules",
    "trainer",
    "TrainState",
    "ParallelModel",
    "initialize_parallel_model",
    "initialize_parallel_optimizer",
    "make_train_step",
    "linear_warmup_cosine_decay",
    "linear_warmup_linear_decay",
]
