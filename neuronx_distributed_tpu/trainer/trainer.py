"""Training orchestration.

Analogue of the reference's ``trainer/trainer.py``:
``initialize_parallel_model:147`` (build model sharded over the mesh),
``initialize_parallel_optimizer:237`` (ZeRO-1-aware optimizer state), and the
per-step path of ``trainer/optimizer.py`` / ``NxDModel.run_train``.

TPU-native shape: one jitted SPMD ``train_step`` (loss → grad → update) with
``NamedSharding``-annotated params and optimizer state. Sharded-grad
reduction, ZeRO-1 reduce-scatter/all-gather and collective overlap all come
from GSPMD + the XLA latency-hiding scheduler rather than hand-written
bucketed all-reduce (reference ``grads.py:259``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.core import meta
from jax.sharding import NamedSharding, PartitionSpec

from ..config import NxDConfig
from ..parallel import comm
from ..parallel import comm_compressed as cc
from ..parallel import grads as grads_mod
from ..parallel import mesh as ps
from . import optimizer as opt_mod


class TrainState(struct.PyTreeNode):
    """Step + params + optimizer state (flax TrainState without the apply_fn
    closure, so it stays a clean pytree for checkpointing).

    ``comm_error``: gradient-compression error-feedback buffers (the
    per-reduce-rank quantization residue re-injected next step; see
    ``parallel/comm_compressed.py``). None unless the config enables a
    quantized ``grad_comm_dtype`` with error feedback — None flattens to
    an empty subtree, so checkpoints and pytree structure are unchanged
    for uncompressed runs. When present it is *checkpointed state*
    (docs/resilience.md): dropping it on restore silently replays one
    step of quantization residue.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    comm_error: Any = None


@struct.dataclass
class ParallelModel:
    """Bundle returned by :func:`initialize_parallel_model` — the analogue of
    the reference's ``NxDModel`` wrapper (``trainer/model.py:8``)."""

    module: nn.Module = struct.field(pytree_node=False)
    config: NxDConfig = struct.field(pytree_node=False)
    param_specs: Any = struct.field(pytree_node=False)
    param_shapes: Any = struct.field(pytree_node=False)

    def param_shardings(self):
        return jax.tree_util.tree_map(
            ps.named_sharding_for_spec, self.param_specs,
            is_leaf=lambda s: isinstance(s, PartitionSpec))


def _spec_tree(boxed_variables, logical_axis_rules=None) -> Any:
    """PartitionSpec tree from flax Partitioned metadata. Logical axis names
    that are not mesh axes are mapped through ``logical_axis_rules`` (e.g.
    ``{"layers": "pp"}`` for pipeline parallelism) and otherwise replicated.

    RULE-mapped axes keep their mesh axis even when the dim is not
    divisible by the axis size: GSPMD shards uneven dims by padding the
    last shard, so an odd layer count over pp still stores ~1/S of the
    stack per stage (reference partitions unevenly, partition.py:280; the
    pipeline grad_fn zero-pads to a divisible length before entering its
    shard_map). Direct mesh-axis annotations (e.g. tp on a hidden dim)
    keep failing loudly on indivisibility — those are genuine
    misconfigurations.
    """
    specs = nn.get_partition_spec(boxed_variables)
    mesh = ps.get_mesh()
    mesh_axes = set(mesh.axis_names)
    sizes = dict(mesh.shape)
    if ps.get_expert_model_parallel_size() > 1:
        # expert-view axes stay in the spec: such params are placed on the
        # expert mesh view (ps.named_sharding_for_spec), making GSPMD EP
        # shard expert weights over ep instead of replicating them
        em = ps.get_expert_mesh()
        mesh_axes |= set(em.axis_names)
        sizes.update(em.shape)
    rules = logical_axis_rules or {}

    def map_axis(a, dim_size):
        if a in mesh_axes:
            return a
        return rules.get(a)

    def clean(spec, shape):
        if not isinstance(spec, PartitionSpec):
            return PartitionSpec()
        dims = list(shape) + [None] * (len(spec) - len(shape))
        out = []
        for p, d in zip(spec, dims):
            if p is None:
                out.append(None)
            elif isinstance(p, tuple):
                kept = tuple(m for m in (map_axis(a, d) for a in p)
                             if m is not None)
                out.append(kept if kept else None)
            else:
                out.append(map_axis(p, d))
        return PartitionSpec(*out)

    shapes = jax.tree_util.tree_map(
        lambda x: tuple(jnp.shape(x)), meta.unbox(boxed_variables))
    return jax.tree_util.tree_map(
        clean, specs, shapes, is_leaf=lambda s: isinstance(s, PartitionSpec))


def initialize_parallel_model(
    cfg: NxDConfig,
    module: nn.Module,
    rng: jax.Array,
    *sample_args,
    method: Optional[Any] = None,
    logical_axis_rules: Optional[dict] = None,
) -> Tuple[ParallelModel, Any]:
    """Shape-evaluate the model, derive param shardings from the layer
    partitioning metadata, and initialise params *already sharded* (XLA
    materialises each shard on its device — the analogue of the reference's
    meta-device init + sequential move, ``utils/model_utils.py:257,335``).

    Returns ``(ParallelModel, params)``.
    """
    init_fn = functools.partial(module.init, method=method)
    boxed_shapes = jax.eval_shape(init_fn, rng, *sample_args)
    specs = _spec_tree(boxed_shapes, logical_axis_rules)
    shapes = jax.tree_util.tree_map(
        lambda x: tuple(x.shape), meta.unbox(boxed_shapes))

    # Uneven RULE-mapped stacks (odd layer count over pp): NamedSharding
    # requires divisible dims, so the STORAGE is zero-padded up to the next
    # multiple — inside the jitted init, so GSPMD materialises only each
    # device's shard, never a replicated [L] stack. Per-stage param and
    # optimizer bytes are ~1/S of dense (reference partitions unevenly,
    # partition.py:280). Pad rows are zero, their grads are masked zero by
    # the pipeline grad_fn, and ``llama_pipeline.unpad_pipeline_params``
    # strips them for export/serving. ONLY logical-rule axes (e.g.
    # "layers"→pp) pad; direct mesh-axis annotations (tp on a vocab or
    # feature dim) keep failing loudly — padding those would silently
    # change model numerics (e.g. pad vocab columns entering the CE
    # logsumexp of a tied head).
    sizes = dict(ps.get_mesh().shape)
    rules = logical_axis_rules or {}
    raw_specs = nn.get_partition_spec(boxed_shapes)

    def _pad_amount(raw, spec, shape):
        rule_mapped = (isinstance(raw, PartitionSpec) and len(raw)
                       and isinstance(raw[0], str) and raw[0] in rules)
        if (rule_mapped and isinstance(spec, PartitionSpec) and len(spec)
                and shape and isinstance(spec[0], str)):
            n = sizes.get(spec[0])
            if n and shape[0] % n != 0:
                return (-(-shape[0] // n)) * n - shape[0]
        return 0

    pads = jax.tree_util.tree_map(
        _pad_amount, raw_specs, specs, shapes,
        is_leaf=lambda s: isinstance(s, PartitionSpec))
    needs_pad = any(jax.tree_util.tree_leaves(pads))
    if needs_pad:
        def unboxed_init(r, *a):
            p = meta.unbox(init_fn(r, *a))
            return jax.tree_util.tree_map(
                lambda x, n: jnp.pad(
                    x, [(0, n)] + [(0, 0)] * (x.ndim - 1)) if n else x,
                p, pads)
        # pads leads: its int leaves are true leaves, while shapes' tuple
        # leaves would be descended into as containers
        shapes = jax.tree_util.tree_map(
            lambda n, s: (s[0] + n,) + tuple(s[1:]) if n else s,
            pads, shapes)
    else:
        def unboxed_init(r, *a):
            return meta.unbox(init_fn(r, *a))

    shardings = jax.tree_util.tree_map(
        ps.named_sharding_for_spec, specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec))
    init_jit = jax.jit(unboxed_init, out_shardings=shardings)
    params = init_jit(rng, *sample_args)
    pm = ParallelModel(module=module, config=cfg, param_specs=specs,
                       param_shapes=shapes)
    return pm, params


def initialize_parallel_optimizer(
    pm: ParallelModel,
    params: Any,
    learning_rate: Any = 1e-4,
    weight_decay: float = 0.01,
    **adam_kw,
) -> Tuple[optax.GradientTransformation, TrainState, Any]:
    """Create the optimizer and a sharded :class:`TrainState`.

    ZeRO-1 (reference ``NeuronZero1Optimizer``): when enabled in the config,
    optimizer-state shardings are extended over the merged dp×cp axes.
    Returns ``(tx, state, state_shardings)``.
    """
    cfg = pm.config
    tx = opt_mod.make_optimizer(cfg, learning_rate=learning_rate,
                                weight_decay=weight_decay, **adam_kw)
    opt_shape = jax.eval_shape(tx.init, params)
    opt_specs = opt_mod.zero1_state_specs(
        opt_shape, pm.param_specs, pm.param_shapes,
        enabled=cfg.optimizer.zero_one_enabled)
    mesh = ps.get_mesh()
    to_shard = lambda tree: jax.tree_util.tree_map(
        ps.named_sharding_for_spec, tree,
        is_leaf=lambda s: isinstance(s, PartitionSpec))
    opt_shardings = to_shard(opt_specs)
    opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)

    # Gradient-compression error feedback: allocate the per-reduce-rank
    # residue buffers alongside the optimizer state so they are carried
    # (and checkpointed) in the TrainState.
    comm_error = None
    err_shardings = None
    comp = cc.from_config(cfg)
    if comp is not None and comp.quantized and comp.error_feedback:
        red_axes = tuple(ax for ax in (ps.DP_AXIS, ps.CP_AXIS)
                         if dict(mesh.shape).get(ax, 1) > 1)
        if red_axes:
            ef_specs = cc.error_feedback_specs(pm.param_specs, red_axes)
            err_shardings = to_shard(ef_specs)
            comm_error = jax.jit(
                lambda p: cc.init_error_feedback(p, pm.param_specs,
                                                 red_axes),
                out_shardings=err_shardings)(params)

    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt_state, comm_error=comm_error)
    state_shardings = TrainState(
        step=NamedSharding(mesh, PartitionSpec()),
        params=to_shard(pm.param_specs),
        opt_state=opt_shardings,
        comm_error=err_shardings)
    return tx, state, state_shardings


def make_train_step(
    pm: ParallelModel,
    tx: optax.GradientTransformation,
    state_shardings: TrainState,
    loss_fn: Optional[Callable] = None,
    grad_fn: Optional[Callable] = None,
    batch_spec: PartitionSpec = PartitionSpec(ps.DP_AXIS),
    donate: bool = True,
    grad_accum_steps: int = 1,
    scan_steps: int = 1,
    dropout_rng: Optional[jax.Array] = None,
    skip_nonfinite: bool = False,
    compression: Optional[cc.CompressionConfig] = None,
    integrity_every: Optional[int] = None,
):
    """Build the jitted SPMD train step.

    Either ``loss_fn(module, params, batch) -> scalar`` (differentiated here
    under GSPMD; default calls ``module.apply(..., method="loss")``) or
    ``grad_fn(params, batch) -> (loss, grads)`` for paths that must compute
    gradients themselves (e.g. the shard_map pipeline engine, whose gradients
    may not cross the shard_map boundary as cotangents — see
    ``parallel/grads.py``).

    ``grad_accum_steps``: split the batch's leading dim into that many
    microbatches, accumulating grads in a ``lax.scan`` before the single
    optimizer update (the reference trainer's gradient_accumulation_steps;
    activations live for one microbatch at a time). Composes with either
    loss_fn or grad_fn. Note: the result is the *mean over microbatch
    means* — identical to the full-batch step when microbatches carry equal
    valid-token counts (the reference accumulates the same way).

    ``dropout_rng``: base PRNG key enabling dropout (attention/hidden/LoRA —
    any module gated on the "dropout" rng). Folded with ``state.step`` each
    step so masks differ per step while the compiled program stays one
    program. Only the default loss_fn threads it; custom loss_fn/grad_fn
    callers manage their own rngs.

    ``skip_nonfinite``: guard the update ON DEVICE — when loss or global
    grad-norm is non-finite, params and optimizer state pass through
    unchanged (the step counter still advances) and the skip is reported in
    ``metrics["nonfinite_skipped"]``. This is the donation-compatible
    counterpart of the resilience ``Watchdog(policy="skip_step")`` host
    rollback: no extra state copy, no host sync, works with ``donate=True``
    and inside ``scan_steps``.

    ``integrity_every``: compute an on-device integrity fingerprint of the
    *updated* params inside the compiled step, every K steps
    (``resilience.integrity.fingerprint_tree`` — an int32 bit-fold, not a
    host hash). Reported as ``metrics["integrity_fp"]`` (fixed-shape
    ``int32[n_leaves]``, zeros off-cadence) so the metrics stay one
    structure and the program count stays one: the cadence gate is a
    ``lax.cond`` on the step counter, like the ``skip_nonfinite`` select.
    ``resilience.IntegrityMonitor`` consumes it at cadence boundaries to
    detect silent data corruption between device write and next read; with
    ``scan_steps > 1`` only the scan's last step's metric surfaces, so
    keep ``integrity_every`` a multiple of ``scan_steps`` (or 1) for a
    usable cadence.

    ``compression``: a ``parallel.CompressionConfig`` (typically
    ``comm_compressed.from_config(pm.config)``) switching gradient
    synchronisation to the quantized / hierarchical collectives. This
    builds the *explicit* path internally — loss and grads computed inside
    ``shard_map`` with the compressed all-reduce on the data axes (GSPMD
    cannot be told to quantize its implicit reductions) — so it composes
    only with the default loss (``loss_fn=None, grad_fn=None``); pipeline
    ``grad_fn``s own their collectives and stay uncompressed. With a
    quantized dtype + error feedback, the state must carry ``comm_error``
    buffers (``initialize_parallel_optimizer`` allocates them when the
    config asks for compression).
    """
    mesh = ps.get_mesh()

    if loss_fn is not None and grad_fn is not None:
        raise ValueError(
            "pass either loss_fn (differentiated here) or grad_fn "
            "(self-differentiating, e.g. the pipeline engine), not both")
    if compression is not None and (loss_fn is not None
                                    or grad_fn is not None):
        raise ValueError(
            "compression= builds its own shard_map gradient path and only "
            "composes with the default loss; custom loss_fn/grad_fn "
            "callers should call parallel.grads.allreduce_gradients("
            "compression=...) themselves")
    if dropout_rng is not None and (loss_fn is not None
                                    or grad_fn is not None):
        raise ValueError(
            "dropout_rng is only threaded through the default loss_fn; "
            "custom loss_fn/grad_fn callers must manage their own rngs "
            "(fold state.step in and pass rngs= to apply)")
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got "
                         f"{grad_accum_steps}")
    if scan_steps < 1:
        raise ValueError(f"scan_steps must be >= 1, got {scan_steps}")
    if integrity_every is not None and integrity_every < 1:
        raise ValueError(f"integrity_every must be >= 1, got "
                         f"{integrity_every}")
    if loss_fn is None and grad_fn is None:
        def loss_fn(module, params, batch, rngs=None):
            input_ids, labels = batch["input_ids"], batch["labels"]
            if rngs is not None:
                return module.apply(params, input_ids, labels,
                                    method="loss", rngs=rngs)
            return module.apply(params, input_ids, labels, method="loss")
        default_loss = True
    else:
        default_loss = False

    compressed_grad = None
    if compression is not None:
        use_ef = compression.quantized and compression.error_feedback
        with_rng = dropout_rng is not None
        red_axes = tuple(ax for ax in (ps.DP_AXIS, ps.CP_AXIS)
                         if dict(mesh.shape).get(ax, 1) > 1)
        ef_specs = (cc.error_feedback_specs(pm.param_specs, red_axes)
                    if use_ef and red_axes else None)
        use_ef = use_ef and ef_specs is not None

        def inner(*args):
            p, input_ids, labels = args[:3]
            idx = 3
            rngs_in = None
            if with_rng:
                # distinct dropout streams per data-parallel rank, shared
                # across tp (the parallel.random contract)
                rngs_in = {"dropout": jax.random.fold_in(
                    args[idx], comm.combined_axis_index(red_axes)
                    if red_axes else 0)}
                idx += 1
            err = None
            if use_ef:
                # EF buffers carry a leading reduce-rank dim outside the
                # shard_map (so each rank's residue is real, addressable,
                # checkpointable state); locally that dim is 1 — peel it
                err = jax.tree_util.tree_map(
                    lambda t: jnp.squeeze(t, 0), args[idx])

            def local_loss(pp):
                if rngs_in is not None:
                    return pm.module.apply(pp, input_ids, labels,
                                           method="loss", rngs=rngs_in)
                return pm.module.apply(pp, input_ids, labels, method="loss")

            loss, g = jax.value_and_grad(local_loss)(p)
            if use_ef:
                g, ne = grads_mod.allreduce_gradients(
                    g, specs=pm.param_specs, axes=red_axes,
                    compression=compression, error=err)
                ne = jax.tree_util.tree_map(lambda t: t[None], ne)
            else:
                g = grads_mod.allreduce_gradients(
                    g, specs=pm.param_specs, axes=red_axes,
                    compression=compression)
            for ax in red_axes:
                loss = jax.lax.pmean(loss, ax)
            return (loss, g, ne) if use_ef else (loss, g)

        in_specs = [pm.param_specs, batch_spec, batch_spec]
        if with_rng:
            in_specs.append(PartitionSpec())
        if use_ef:
            in_specs.append(ef_specs)
        out_specs = (PartitionSpec(), pm.param_specs)
        if use_ef:
            out_specs = out_specs + (ef_specs,)
        sm_grad = ps.shard_map(inner, mesh, in_specs=tuple(in_specs),
                               out_specs=out_specs)

        def compressed_grad(params, batch, rngs, err):
            args = [params, batch["input_ids"], batch["labels"]]
            if with_rng:
                args.append(rngs["dropout"])
            if use_ef:
                args.append(err)
            outs = sm_grad(*args)
            if use_ef:
                return outs
            return outs[0], outs[1], err

    def one_grad(params, batch, rngs=None, err=None):
        """→ ``(loss, grads, new_err)``; ``err`` passes through untouched
        on the uncompressed paths (None stays None)."""
        if compressed_grad is not None:
            return compressed_grad(params, batch, rngs, err)
        if grad_fn is not None:
            loss, g = grad_fn(params, batch)
            return loss, g, err
        if default_loss:
            loss, g = jax.value_and_grad(
                lambda p: loss_fn(pm.module, p, batch, rngs))(params)
            return loss, g, err
        loss, g = jax.value_and_grad(
            lambda p: loss_fn(pm.module, p, batch))(params)
        return loss, g, err

    def accum_grad(params, batch, rngs=None, err=None):
        a = grad_accum_steps

        def slice_mb(x):
            if x.shape[0] % a != 0:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"grad_accum_steps {a}")
            return x.reshape(a, x.shape[0] // a, *x.shape[1:])

        mbs = jax.tree_util.tree_map(slice_mb, batch)
        # keep every microbatch spread over the full dp axis — without the
        # constraint GSPMD may localize the new leading dim and serialize
        # data parallelism inside the scan
        mb_sharding = NamedSharding(mesh, PartitionSpec(None, *batch_spec))
        mbs = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, mb_sharding), mbs)

        def body(carry, xs):
            loss_sum, gacc, e = carry
            mb, i = xs
            mb_rngs = (None if rngs is None else
                       {k: jax.random.fold_in(r, i)
                        for k, r in rngs.items()})
            # with compression each microbatch reduce consumes/produces
            # the error-feedback residue through the scan carry
            loss, g, e = one_grad(params, mb, mb_rngs, e)
            gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
            return (loss_sum + loss, gacc, e), None

        zero = jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)), params)
        (loss_sum, gsum, err), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zero, err),
            (mbs, jnp.arange(a)))
        scale = 1.0 / a
        return loss_sum * scale, jax.tree_util.tree_map(
            lambda g: g * scale, gsum), err

    def step_fn(state: TrainState, batch) -> Tuple[TrainState, dict]:
        rngs = (None if dropout_rng is None else
                {"dropout": jax.random.fold_in(dropout_rng, state.step)})
        if grad_accum_steps > 1:
            loss, grads, new_err = accum_grad(state.params, batch, rngs,
                                              state.comm_error)
        else:
            loss, grads, new_err = one_grad(state.params, batch, rngs,
                                            state.comm_error)
        grad_norm = optax.global_norm(grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
        }
        if compression is not None:
            metrics["grad_comm_ratio"] = jnp.asarray(compression.ratio,
                                                     jnp.float32)
        if skip_nonfinite:
            # select, don't branch: one compiled program either way, and
            # the guard composes with donation and scan_steps
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            keep = lambda new, old: jnp.where(ok, new, old)  # noqa: E731
            new_params = jax.tree_util.tree_map(keep, new_params,
                                                state.params)
            new_opt = jax.tree_util.tree_map(keep, new_opt, state.opt_state)
            # a skipped step must also discard the residue the bad reduce
            # wrote, or one NaN grad poisons every later step through EF
            new_err = jax.tree_util.tree_map(keep, new_err,
                                             state.comm_error)
            metrics["nonfinite_skipped"] = (~ok).astype(jnp.int32)
        if integrity_every is not None:
            # lazy import: resilience pulls in chaos/storage machinery the
            # hot path doesn't need unless integrity is on
            from ..resilience.integrity import fingerprint_tree

            n_leaves = len(jax.tree_util.tree_leaves(new_params))
            # cond, not select: off-cadence steps must not pay the
            # fingerprint fold; both branches live in the ONE compiled
            # program (compile_count unchanged), like skip_nonfinite
            metrics["integrity_fp"] = jax.lax.cond(
                (state.step + 1) % integrity_every == 0,
                lambda p: fingerprint_tree(p),
                lambda p: jnp.zeros((n_leaves,), jnp.int32),
                new_params)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt, comm_error=new_err), metrics

    batch_shardings = NamedSharding(mesh, batch_spec)
    if scan_steps > 1:
        # run `scan_steps` optimizer steps in ONE dispatch: batch leaves gain
        # a leading scan dim. Keeps host round-trips (and, through remote
        # tunnels, dispatch latency) out of the training loop — the XLA
        # program is the same per-step program, iterated on device.
        def multi_step_fn(state: TrainState, batches):
            def body(s, mb):
                s2, metrics = step_fn(s, mb)
                return s2, metrics
            state, ms = jax.lax.scan(body, state, batches)
            last = jax.tree_util.tree_map(lambda x: x[-1], ms)
            return state, last

        multi_batch_shardings = NamedSharding(
            mesh, PartitionSpec(None, *batch_spec))
        return jax.jit(
            multi_step_fn,
            in_shardings=(state_shardings, multi_batch_shardings),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,) if donate else (),
        )
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, batch_shardings),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )


# -- nxdlint jaxpr-audit entry point ---------------------------------------

from ..analysis.audit_registry import BuiltEntry, register_entry_point


@register_entry_point(
    "train-step",
    description="tiny-Llama SPMD train step (donating jit), same "
                "construction path as the e2e training tests",
    tags=("train",),
    expects_donation=True,
    donation_min_bytes=1 << 14,
)
def _audit_train_step() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``: the smallest real train step,
    sized for the virtual CPU mesh. The returned step is only
    abstract-traced by the auditor, never executed."""
    from ..config import neuronx_distributed_config
    from ..models.llama import LlamaForCausalLM, tiny_config

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    cfg = neuronx_distributed_config(tensor_parallel_size=1)
    mcfg = tiny_config(dtype=jnp.float32, param_dtype=jnp.float32)
    model = LlamaForCausalLM(mcfg)
    ids = jnp.zeros((8, 16), jnp.int32)
    pm, params = initialize_parallel_model(
        cfg, model, jax.random.key(0), ids)
    tx, state, state_shardings = initialize_parallel_optimizer(pm, params)
    step = make_train_step(pm, tx, state_shardings)
    batch = {"input_ids": ids, "labels": ids}
    return BuiltEntry(fn=step, args=(state, batch), donate_argnums=(0,))
