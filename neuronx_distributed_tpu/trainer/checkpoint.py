"""Checkpoint engine: async sharded save/load with commit protocol.

Analogue of the reference's ``trainer/checkpoint.py`` (``save_checkpoint:654``,
``load_checkpoint:838``, ``CheckpointIOState:110``, done-file commit protocol
``end:175``, retention ``_determine_remove_tags:66``).

TPU-native mapping: tensor IO is Orbax/TensorStore — arrays are saved by
*sharding*, not by rank (each host writes its shards; restore reshards to any
mesh), which subsumes the reference's per-rank files, xser streaming bins and
DCP adapter in one mechanism (SURVEY §5 "Checkpoint / resume"). On top we
keep the reference's operational protocol exactly:

* ``checkpoint`` done-marker written only after the async save completes;
* ``newest`` tag file for fast auto-resume; ``tag="-1"``/None loads the
  newest *complete* checkpoint;
* retention of the last N complete checkpoints;
* async saves on a background thread so training continues during IO, with
  ``finalize_checkpoint()`` + atexit flush.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..resilience import manifest as _manifest
from .checkpoint_storage import BaseCheckpointStorage, create_checkpoint_storage

logger = logging.getLogger(__name__)

DONE_FILE = "checkpoint"  # reference: done-marker file name
NEWEST_FILE = "newest"
STATE_DIR = "state"
USER_CONTENT_FILE = "user_content.json"
MANIFEST_FILE = _manifest.MANIFEST_FILE


class CheckpointSaveError(RuntimeError):
    """An async checkpoint commit failed (raised at the next
    save/finalize/wait, never swallowed — reference propagates at
    ``wait_save``, ``checkpoint.py:198``)."""


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint with a done-marker failed manifest verification (or
    restore), and no fallback was possible: explicit-tag loads never fall
    back silently, and auto-resume raises this only after every complete
    tag was tried."""


class CheckpointIOState:
    """Tracks in-flight async saves (reference ``CheckpointIOState:110``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: List[Tuple[str, threading.Thread]] = []
        self._errors: List[Tuple[str, BaseException]] = []

    def add(self, tag: str, thread: threading.Thread) -> None:
        with self._lock:
            self._pending.append((tag, thread))

    def record_error(self, tag: str, exc: BaseException) -> None:
        with self._lock:
            self._errors.append((tag, exc))

    def raise_pending_errors(self) -> None:
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            tags = ", ".join(t for t, _ in errors)
            raise CheckpointSaveError(
                f"async checkpoint commit failed for tag(s) {tags}: "
                f"{errors[0][1]!r}") from errors[0][1]

    def wait_all(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for _, t in pending:
            t.join()
        self.raise_pending_errors()

    def wait_tag(self, tag: str) -> None:
        """Join in-flight saves of one tag (overwrite must not race the
        previous commit thread re-writing the done-marker)."""
        with self._lock:
            pending = [p for p in self._pending if p[0] == tag]
            self._pending = [p for p in self._pending if p[0] != tag]
        for _, t in pending:
            t.join()


_IO_STATE = CheckpointIOState()
atexit.register(_IO_STATE.wait_all)


def _normalize_path(path: str) -> str:
    """``file://`` is local filesystem — strip the scheme so every layer
    (orbax, os.path) sees a plain path."""
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


def _tag_dir(base: str, tag: str) -> str:
    if "://" in base:
        return base.rstrip("/") + "/" + str(tag)
    return os.path.join(base, str(tag))


def _orbax_path(tdir: str) -> str:
    """Path handed to Orbax/TensorStore: absolute for local filesystems
    (Orbax requires it), untouched for object-store URIs — ``abspath`` would
    mangle ``s3://...`` into a bogus local path."""
    if "://" in tdir:
        return tdir.rstrip("/") + "/" + STATE_DIR
    return os.path.abspath(os.path.join(tdir, STATE_DIR))


def _is_complete(storage: BaseCheckpointStorage, base: str, tag: str) -> bool:
    return storage.file_exists(os.path.join(_tag_dir(base, tag), DONE_FILE))


def _complete_tags(storage: BaseCheckpointStorage, base: str) -> List[str]:
    tags = [t for t in storage.list_dirs(base)
            if _is_complete(storage, base, t)]

    def sort_key(t: str):
        try:
            return (0, int(t))
        except ValueError:
            return (1, t)

    return sorted(tags, key=sort_key)


def has_checkpoint(path: str, tag: Optional[str] = None) -> bool:
    """Reference: top-level ``has_checkpoint`` export."""
    path = _normalize_path(path)
    storage = create_checkpoint_storage(path)
    if tag is not None and tag != "-1":
        return _is_complete(storage, path, str(tag))
    return len(_complete_tags(storage, path)) > 0


def list_complete_tags(path: str) -> List[str]:
    """All complete (done-marker carrying) tags under ``path``, oldest
    first, numeric tags before lexical ones — the public face of the
    engine's own completeness scan, for tooling (``scripts/
    reshard_checkpoint.py``) that must never re-derive the commit
    protocol from private helpers."""
    path = _normalize_path(path)
    return _complete_tags(create_checkpoint_storage(path), path)


def verify_checkpoint(path: str, tag: Any) -> Tuple[bool, str]:
    """``(ok, detail)`` manifest verification of one complete tag —
    content digests where recorded (manifest v2), inventory+size
    otherwise. Does not restore; tooling uses this to report whether the
    bytes it is about to ship are the bytes that were saved."""
    path = _normalize_path(path)
    return _verify_tag(create_checkpoint_storage(path), path, str(tag))


def save_checkpoint(
    path: str,
    tag: Any,
    state: Any,
    user_content: Optional[dict] = None,
    async_save: bool = True,
    num_kept: int = -1,
) -> None:
    """Save ``state`` (any pytree of jax arrays) under ``path/tag``.

    Reference: ``save_checkpoint:654``. The done-marker is written only after
    tensors are durably on storage; with ``async_save`` the commit happens on
    a background thread and training proceeds.
    """
    tag = str(tag)
    path = _normalize_path(path)
    # surface any earlier async-commit failure instead of training on
    # believing those checkpoints exist
    _IO_STATE.raise_pending_errors()
    storage = create_checkpoint_storage(path)
    tdir = _tag_dir(path, tag)
    storage.create_dir(tdir)

    # Commit-protocol invariant: done-marker implies durable tensors. An
    # overwrite of an existing complete tag must drop the stale marker
    # before the state dir is touched, else a crash mid-rewrite leaves a
    # half-written checkpoint that _is_complete() accepts. An in-flight
    # async save of the same tag would re-write the marker from its commit
    # thread — join it first. The stale manifest goes too: it describes
    # the files being replaced.
    _IO_STATE.wait_tag(tag)
    storage.remove_file(os.path.join(tdir, DONE_FILE))
    storage.remove_file(os.path.join(tdir, MANIFEST_FILE))

    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    state_path = _orbax_path(tdir)
    if storage.dir_exists(state_path):
        storage.remove_dir(state_path)
    try:
        ckptr.save(state_path, args=ocp.args.StandardSave(state))
    except Exception:
        ckptr.close()
        raise

    if user_content is not None:
        storage.save_object(user_content, os.path.join(tdir,
                                                       USER_CONTENT_FILE))

    def commit():
        ckptr.wait_until_finished()
        ckptr.close()
        # manifest after the payload is durable (sizes are final), before
        # the done-marker: a complete tag always carries its inventory
        man = _manifest.build_manifest(storage, tdir, tag)
        if man is not None:
            storage.save_object(man, os.path.join(tdir, MANIFEST_FILE))
        storage.save_text("done", os.path.join(tdir, DONE_FILE))
        storage.save_text(tag, os.path.join(path, NEWEST_FILE))
        if num_kept > 0:
            _apply_retention(storage, path, num_kept)
        logger.info("checkpoint %s committed", tdir)

    def commit_async():
        try:
            commit()
        except BaseException as e:  # re-raised at next save/finalize
            logger.exception("async commit of checkpoint %s failed", tdir)
            _IO_STATE.record_error(tag, e)

    if async_save:
        t = threading.Thread(target=commit_async, daemon=False,
                             name=f"ckpt-commit-{tag}")
        t.start()
        _IO_STATE.add(tag, t)
    else:
        commit()


# Retention runs on async commit threads: two overlapping saves that both
# carry num_kept would otherwise list/remove concurrently — each computes
# a stale survivor set and can delete a tag the other just committed.
_RETENTION_LOCK = threading.Lock()


def _apply_retention(storage: BaseCheckpointStorage, path: str,
                     num_kept: int) -> None:
    """Keep the newest ``num_kept`` complete tags (reference
    ``_determine_remove_tags:66``). Serialized process-wide: the
    list-then-remove sequence is not atomic, so concurrent commit threads
    take turns."""
    with _RETENTION_LOCK:
        tags = _complete_tags(storage, path)
        for t in tags[:-num_kept] if num_kept > 0 else []:
            logger.info("retention: removing checkpoint %s", t)
            storage.remove_dir(_tag_dir(path, t))


def finalize_checkpoint() -> None:
    """Block until all async saves are committed (reference
    ``finalize_checkpoint`` / atexit flush ``checkpoint.py:733-735``)."""
    _IO_STATE.wait_all()


def _verify_tag(storage: BaseCheckpointStorage, path: str,
                tag: str) -> Tuple[bool, str]:
    tdir = _tag_dir(path, tag)
    return _manifest.verify_manifest(storage, tdir,
                                     os.path.join(tdir, MANIFEST_FILE))


def _restore_tag(storage: BaseCheckpointStorage, path: str, tag: str,
                 target: Optional[Any]) -> Tuple[Any, Optional[dict]]:
    tdir = _tag_dir(path, tag)
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    restore_args = (ocp.args.StandardRestore(target)
                    if target is not None else ocp.args.StandardRestore())
    try:
        state = ckptr.restore(_orbax_path(tdir), args=restore_args)
    finally:
        ckptr.close()
    user_content = None
    uc = os.path.join(tdir, USER_CONTENT_FILE)
    if storage.file_exists(uc):
        user_content = storage.load_object(uc)
    return state, user_content


def load_checkpoint(
    path: str,
    tag: Optional[Any] = None,
    target: Optional[Any] = None,
    verify: bool = True,
) -> Tuple[Any, Optional[dict]]:
    """Load ``(state, user_content)``.

    ``tag=None`` / ``"-1"`` auto-resumes from the newest complete checkpoint
    (reference ``load_checkpoint:838`` with ``tag="-1"``). ``target`` is a
    pytree of arrays or ``jax.ShapeDtypeStruct`` (with shardings) directing
    dtype/sharding of the restore — restoring to a different mesh than the
    save reshards transparently.

    Verified resume (``verify=True``): a tag's manifest (file inventory +
    metadata checksum, written by ``save_checkpoint`` before the
    done-marker) is checked first. In auto-resume mode a corrupt or
    unrestorable tag falls back to the newest *prior* complete tag with a
    logged warning; an explicit-tag load raises
    :class:`CheckpointCorruptionError` instead — the caller named that tag,
    silently loading another would be worse than failing.
    """
    path = _normalize_path(path)
    storage = create_checkpoint_storage(path)
    if tag is None or str(tag) == "-1":
        # The 'newest' pointer is only a fast-path hint: out-of-order async
        # commits (or a crash between done-marker and pointer write) can
        # leave it pointing at an older complete tag — never resume behind
        # the newest complete checkpoint.
        tags = _complete_tags(storage, path)
        if not tags:
            raise FileNotFoundError(f"no complete checkpoint under {path}")
        skipped = []
        for t in reversed(tags):
            if verify:
                ok, why = _verify_tag(storage, path, t)
                if not ok:
                    logger.warning(
                        "checkpoint %s/%s failed verification (%s); "
                        "falling back to the prior complete tag", path, t,
                        why)
                    skipped.append((t, why))
                    continue
            try:
                return _restore_tag(storage, path, t, target)
            except Exception as e:
                logger.warning(
                    "checkpoint %s/%s failed to restore (%r); falling back "
                    "to the prior complete tag", path, t, e)
                skipped.append((t, repr(e)))
        raise CheckpointCorruptionError(
            f"no intact checkpoint under {path}; skipped: "
            + "; ".join(f"{t}: {why}" for t, why in skipped))
    tag = str(tag)
    if not _is_complete(storage, path, tag):
        raise FileNotFoundError(
            f"checkpoint {path}/{tag} missing or incomplete (no done-marker)")
    if verify:
        ok, why = _verify_tag(storage, path, tag)
        if not ok:
            raise CheckpointCorruptionError(
                f"checkpoint {path}/{tag} is corrupt: {why}")
    return _restore_tag(storage, path, tag, target)
