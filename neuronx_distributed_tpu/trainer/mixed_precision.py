"""Mixed-precision optimizer wrappers.

Analogue of the reference's ``utils/adamw_fp32_optim_params.py`` (AdamW
keeping an fp32 master copy inside optimizer state for non-ZeRO mixed
precision) and the ``mixed_precision_config`` master-weights options
(``trainer/trainer.py:66-76``).

Default framework convention is already "fp32 params + bf16 compute" (cast
at use inside the layers), which makes masters implicit. This wrapper covers
the other convention — bf16 *stored* params (half the param HBM, as some
serving-adjacent training setups want) with fp32 masters and update math
living in the optimizer state.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class MasterWeightsState(NamedTuple):
    master: Any         # fp32 copy of params
    inner: optax.OptState


def with_fp32_master_weights(
        tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """Wrap ``tx`` so updates are computed against fp32 masters and the
    emitted updates move the (bf16) live params to the new master values
    exactly (reference ``AdamW_FP32OptimParams``)."""

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params)
        return MasterWeightsState(master=master, inner=tx.init(master))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError(
                "with_fp32_master_weights requires the live params: call "
                "tx.update(grads, state, params)")
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        updates, inner = tx.update(grads32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, updates)
        # emitted update = (new_master cast to param dtype) - live param,
        # so apply_updates lands exactly on the rounded master
        emitted = jax.tree_util.tree_map(
            lambda nm, p: nm.astype(p.dtype) - p, new_master, params)
        return emitted, MasterWeightsState(master=new_master, inner=inner)

    return optax.GradientTransformation(init, update)
