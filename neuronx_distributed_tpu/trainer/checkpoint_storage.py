"""Checkpoint storage backends.

Analogue of the reference's ``trainer/checkpoint_storage.py``
(``BaseCheckpointStorage:46``, ``FilesysCheckpointStorage:138``,
``S3CheckpointStorage:287``, factory ``create_checkpoint_storage:611``).

Tensor payloads go through Orbax/TensorStore (which natively supports
``gs://`` / ``s3://`` URIs when the relevant filesystem drivers are
installed); this layer owns the *control-plane* objects the reference keeps
beside them — done-markers, tags, retention listings, small JSON metadata —
behind one abstraction so the engine never touches ``os.path`` directly.
"""

from __future__ import annotations

import errno
import functools
import json
import logging
import os
import random
import re
import shutil
import time
from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)


# Deterministic failure modes: retrying these burns max_attempts x sleeps
# before surfacing the same bug. JSONDecodeError is a ValueError subclass.
_NON_RETRIABLE = (FileNotFoundError, PermissionError, IsADirectoryError,
                  NotADirectoryError, FileExistsError, TypeError, ValueError,
                  KeyError, AttributeError, NotImplementedError)

# Fragments marking a throttling/transient server response even when the
# fsspec driver surfaces it as a generic exception type. HTTP status codes
# match as whole words only — a bare substring ('503' inside 'shard size
# 5035') would turn a deterministic bug into 5 retries with sleeps.
_TRANSIENT_MARKERS = ("slowdown", "slow down", "throttl", "timed out",
                      "timeout", "connection reset", "connection aborted",
                      "temporarily unavailable", "too many requests",
                      "internal error")
_TRANSIENT_STATUS_RE = re.compile(r"\b(?:429|500|502|503|504)\b")

# OSError errnos that describe a deterministic local condition, not an
# environment hiccup: no amount of backoff frees the disk or remounts the
# filesystem writable.
_DETERMINISTIC_ERRNOS = frozenset(
    getattr(errno, name) for name in
    ("ENOSPC", "EDQUOT", "EROFS", "ENAMETOOLONG", "EISDIR", "ENOTDIR")
    if hasattr(errno, name))


def _is_transient(e: Exception) -> bool:
    if isinstance(e, _NON_RETRIABLE):
        return False
    if isinstance(e, OSError) and e.errno in _DETERMINISTIC_ERRNOS:
        # disk full / quota / read-only fs: retrying with backoff burns
        # minutes before surfacing the same condition (advisor r3)
        return False
    if isinstance(e, (ConnectionError, TimeoutError, OSError)):
        # network errors plus remaining OSErrors (EIO, ENETDOWN, stale NFS
        # handles, ...) are environment hiccups worth retrying
        return True
    msg = str(e).lower()
    return (any(m in msg for m in _TRANSIENT_MARKERS)
            or _TRANSIENT_STATUS_RE.search(msg) is not None)


def retry_with_backoff(max_attempts: int = 5, base_delay: float = 0.5,
                       max_delay: float = 8.0):
    """Retry transient storage errors with exponential backoff and
    *decrementing* jitter (reference ``checkpoint_storage.py:236-286``:
    tenacity retry tuned for S3 503 slow-down — early attempts spread out
    randomly, later attempts converge to the full deterministic delay).
Only errors classified transient by :func:`_is_transient` are retried;
    deterministic bugs (TypeError, JSON decode errors, missing files)
    surface immediately (reference retries only classified slow-down
    errors, ``checkpoint_storage.py:250``).
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            last = None
            for attempt in range(max_attempts):
                try:
                    return fn(*args, **kwargs)
                except Exception as e:
                    if not _is_transient(e):
                        raise  # deterministic, not transient
                    last = e
                    if attempt == max_attempts - 1:
                        break
                    delay = min(base_delay * 2 ** attempt, max_delay)
                    # decrementing jitter: subtract up to (remaining
                    # fraction) of the delay, so retries decorrelate early
                    # and back off fully late
                    frac = 1.0 - attempt / max(max_attempts - 1, 1)
                    delay -= random.uniform(0, delay * 0.5 * frac)
                    logger.warning(
                        "storage op %s failed (%r), retry %d/%d in %.2fs",
                        fn.__name__, e, attempt + 1, max_attempts - 1,
                        delay)
                    time.sleep(delay)
            raise last
        return wrapper
    return deco


class BaseCheckpointStorage(ABC):
    """Reference: ``BaseCheckpointStorage`` (``checkpoint_storage.py:46``)."""

    def __init__(self, dirname: str):
        self._dirname = dirname

    def dirname(self) -> str:
        return self._dirname

    @abstractmethod
    def dir_exists(self, dirname: str) -> bool: ...

    @abstractmethod
    def file_exists(self, filename: str) -> bool: ...

    @abstractmethod
    def create_dir(self, dirname: str) -> None: ...

    @abstractmethod
    def list_dirs(self, dirname: str) -> List[str]: ...

    @abstractmethod
    def remove_dir(self, dirname: str) -> None: ...

    @abstractmethod
    def remove_file(self, filename: str) -> None: ...

    @abstractmethod
    def save_text(self, text: str, filename: str) -> None: ...

    @abstractmethod
    def load_text(self, filename: str) -> str: ...

    def save_object(self, obj: Any, filename: str) -> None:
        self.save_text(json.dumps(obj), filename)

    def load_object(self, filename: str) -> Any:
        return json.loads(self.load_text(filename))

    def list_files(self, dirname: str) -> Optional[List[Tuple[str, int]]]:
        """``(relative_path, size_bytes)`` for every file under ``dirname``
        (recursive, '/'-separated relpaths), or ``None`` when the backend
        cannot enumerate — callers (manifest verification) must then skip
        verification rather than fail."""
        return None

    def file_size(self, filename: str) -> Optional[int]:
        """Size in bytes, or ``None`` when missing/unsupported."""
        return None

    def read_bytes(self, filename: str) -> Optional[bytes]:
        """Raw file contents, or ``None`` when missing/unsupported.
        Manifest content digests (verified resume) hash through this;
        backends returning ``None`` degrade to inventory+size checks."""
        return None


class FilesysCheckpointStorage(BaseCheckpointStorage):
    """Local/NFS filesystem backend (reference
    ``FilesysCheckpointStorage:138``)."""

    def dir_exists(self, dirname: str) -> bool:
        return os.path.isdir(dirname)

    def file_exists(self, filename: str) -> bool:
        return os.path.isfile(filename)

    def create_dir(self, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)

    def list_dirs(self, dirname: str) -> List[str]:
        if not os.path.isdir(dirname):
            return []
        return [d for d in os.listdir(dirname)
                if os.path.isdir(os.path.join(dirname, d))]

    def remove_dir(self, dirname: str) -> None:
        shutil.rmtree(dirname, ignore_errors=True)

    def remove_file(self, filename: str) -> None:
        try:
            os.remove(filename)
        except FileNotFoundError:
            pass

    def save_text(self, text: str, filename: str) -> None:
        os.makedirs(os.path.dirname(filename), exist_ok=True)
        tmp = filename + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, filename)  # atomic publish

    def load_text(self, filename: str) -> str:
        with open(filename) as f:
            return f.read()

    def list_files(self, dirname: str) -> Optional[List[Tuple[str, int]]]:
        if not os.path.isdir(dirname):
            return []
        out: List[Tuple[str, int]] = []
        for root, dirs, files in os.walk(dirname):
            dirs.sort()
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, dirname).replace(os.sep, "/")
                try:
                    out.append((rel, os.path.getsize(full)))
                except OSError:
                    # racing deletion (retention): report what remains
                    pass
        return out

    def file_size(self, filename: str) -> Optional[int]:
        try:
            return os.path.getsize(filename)
        except OSError:
            return None

    def read_bytes(self, filename: str) -> Optional[bytes]:
        try:
            with open(filename, "rb") as f:
                return f.read()
        except OSError:
            return None


class ObjectStoreCheckpointStorage(BaseCheckpointStorage):
    """Cloud object-store backend (reference ``S3CheckpointStorage:287``).

    Tensor payloads already stream through TensorStore's gcs/s3 drivers; this
    control-plane implementation requires ``fsspec`` with the matching
    protocol. Instantiating without it raises immediately (no silent
    fallback), mirroring the reference's explicit boto3 dependency.
    """

    def __init__(self, dirname: str):
        super().__init__(dirname)
        try:
            import fsspec  # noqa: F401

            self._fs = fsspec.filesystem(dirname.split("://", 1)[0])
        except Exception as e:  # pragma: no cover - env without fsspec
            raise ImportError(
                f"object-store checkpoint dir {dirname!r} requires fsspec "
                f"with the matching driver: {e}") from e

    @retry_with_backoff()
    def dir_exists(self, dirname: str) -> bool:
        return self._fs.isdir(dirname)

    @retry_with_backoff()
    def file_exists(self, filename: str) -> bool:
        return self._fs.isfile(filename)

    @retry_with_backoff()
    def create_dir(self, dirname: str) -> None:
        self._fs.makedirs(dirname, exist_ok=True)

    @retry_with_backoff()
    def list_dirs(self, dirname: str) -> List[str]:
        if not self._fs.isdir(dirname):
            return []
        return [os.path.basename(p.rstrip("/")) for p in self._fs.ls(dirname)
                if self._fs.isdir(p)]

    @retry_with_backoff()
    def remove_dir(self, dirname: str) -> None:
        # a retry after a partially-completed delete legitimately finds
        # nothing left — that is success, not an error
        try:
            self._fs.rm(dirname, recursive=True)
        except FileNotFoundError:
            pass

    @retry_with_backoff()
    def remove_file(self, filename: str) -> None:
        # try/except rather than isfile-then-rm: fsspec dircaches can
        # report a stale False and silently skip the delete
        try:
            self._fs.rm(filename)
        except FileNotFoundError:
            pass

    @retry_with_backoff()
    def save_text(self, text: str, filename: str) -> None:
        with self._fs.open(filename, "w") as f:
            f.write(text)

    @retry_with_backoff()
    def load_text(self, filename: str) -> str:
        with self._fs.open(filename, "r") as f:
            return f.read()

    @retry_with_backoff()
    def list_files(self, dirname: str) -> Optional[List[Tuple[str, int]]]:
        if not self._fs.isdir(dirname):
            return []
        base = dirname.rstrip("/")
        out: List[Tuple[str, int]] = []
        for path, info in sorted(self._fs.find(base, detail=True).items()):
            if info.get("type") == "directory":
                continue
            rel = path[len(base):].lstrip("/") if path.startswith(base) \
                else os.path.basename(path)
            out.append((rel, int(info.get("size", 0))))
        return out

    @retry_with_backoff()
    def file_size(self, filename: str) -> Optional[int]:
        try:
            return int(self._fs.size(filename))
        except FileNotFoundError:
            return None

    @retry_with_backoff()
    def read_bytes(self, filename: str) -> Optional[bytes]:
        try:
            with self._fs.open(filename, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None


# Process-wide storage wrapper hook: the resilience chaos harness (and any
# future instrumentation layer) interposes on EVERY storage the checkpoint
# engine creates — including the ones async commit threads construct —
# without threading a parameter through save/load call sites.
_STORAGE_WRAPPER: Optional[
    Callable[[BaseCheckpointStorage], BaseCheckpointStorage]] = None


def install_storage_wrapper(
        wrapper: Callable[[BaseCheckpointStorage],
                          BaseCheckpointStorage]) -> None:
    """Wrap every storage subsequently built by
    :func:`create_checkpoint_storage` (e.g.
    ``resilience.chaos.wrapper_for_plan(plan)``)."""
    global _STORAGE_WRAPPER
    _STORAGE_WRAPPER = wrapper


def clear_storage_wrapper() -> None:
    global _STORAGE_WRAPPER
    _STORAGE_WRAPPER = None


def create_checkpoint_storage(dirname: str) -> BaseCheckpointStorage:
    """Factory (reference ``create_checkpoint_storage:611``)."""
    if dirname.startswith("file://"):
        storage: BaseCheckpointStorage = FilesysCheckpointStorage(
            dirname[len("file://"):])
    elif "://" in dirname:
        storage = ObjectStoreCheckpointStorage(dirname)
    else:
        storage = FilesysCheckpointStorage(dirname)
    if _STORAGE_WRAPPER is not None:
        storage = _STORAGE_WRAPPER(storage)
    return storage
