"""High-level training loop with callbacks.

Analogue of the reference's PyTorch-Lightning adapter layer (``lightning/``:
``NeuronLTModule`` module.py:24, ``NeuronXLAStrategy`` strategy.py:36,
TB logger, checkpoint IO, progress bar). In single-controller JAX a strategy/
launcher/accelerator split is unnecessary — the loop is a plain function over
the jitted train step; the Lightning surface maps to :class:`Callback` hooks
(logging, checkpointing, early stop) around it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax

from ..config import NxDConfig
from ..obs.accounting import CompileTracker
from ..obs.metrics import get_registry
from ..obs.tracing import get_tracer
from ..utils.logger import get_logger, log_event
from . import checkpoint as ckpt

logger = get_logger(__name__)


class Callback:
    """Hook points (the Lightning-callback analogue)."""

    def on_train_start(self, trainer: "Trainer") -> None: ...

    def on_step_end(self, trainer: "Trainer", metrics: Dict) -> None: ...

    def on_eval_end(self, trainer: "Trainer", metrics: Dict) -> None: ...

    def on_train_end(self, trainer: "Trainer") -> None: ...


class MetricsLogger(Callback):
    """Rank-0 console/TSV metrics logging (reference ``lightning/logger.py``
    TB logger)."""

    def __init__(self, every: int = 10, file: Optional[str] = None):
        self.every = every
        self.file = file
        self._t0 = None
        self._tokens = 0

    def on_train_start(self, trainer):
        self._t0 = time.perf_counter()

    def on_step_end(self, trainer, metrics):
        step = trainer.host_step
        self._tokens += trainer.tokens_per_batch
        if step % self.every == 0:
            dt = time.perf_counter() - self._t0
            tps = self._tokens / max(dt, 1e-9)
            line = (f"step {step} loss {float(metrics['loss']):.4f} "
                    f"grad_norm {float(metrics.get('grad_norm', 0)):.3f} "
                    f"tokens/s {tps:,.0f}")
            if "grad_comm_ratio" in metrics:
                # wire-compression ratio of the gradient collectives
                # (parallel/comm_compressed.py); constant per run but kept
                # on the step line so logs are self-describing
                line += (" comm_ratio "
                         f"{float(metrics['grad_comm_ratio']):.2f}x")
            logger.info(line)
            if self.file:
                with open(self.file, "a") as f:
                    f.write(line + "\n")


class CheckpointCallback(Callback):
    """Periodic async checkpointing with retention + final flush (reference
    ``lightning/checkpoint_io.py`` over our checkpoint engine).

    Step 0 never saves (an untrained checkpoint both wastes a retention
    slot and can shadow a real resume point), and ``on_train_end`` saves
    the final step synchronously when it is not aligned to ``every`` — the
    tail of a run is never lost to alignment.

    Every tag carries a save manifest with per-shard content digests, so
    a later rewind (watchdog, or :class:`~..resilience.integrity
    .IntegrityMonitor` on a fingerprint mismatch) restores from a
    checkpoint whose *bytes* verify. Order this callback *before* the
    IntegrityMonitor in ``callbacks``: detection then fires after the
    boundary's save, and a mismatch rewinds to state captured before the
    corruption could be persisted.
    """

    def __init__(self, path: str, every: int = 1000, num_kept: int = 3):
        self.path = path
        self.every = every
        self.num_kept = num_kept
        self._last_saved: Optional[int] = None

    def on_step_end(self, trainer, metrics):
        step = trainer.host_step
        if self.every and step > 0 and step % self.every == 0:
            with get_tracer().span("train/checkpoint", step=step,
                                   mode="async"):
                ckpt.save_checkpoint(self.path, step, trainer.state,
                                     async_save=True,
                                     num_kept=self.num_kept)
            self._last_saved = step

    def on_train_end(self, trainer):
        step = trainer.host_step
        if step > 0 and step != self._last_saved:
            with get_tracer().span("train/checkpoint", step=step,
                                   mode="sync"):
                ckpt.save_checkpoint(self.path, step, trainer.state,
                                     async_save=False,
                                     num_kept=self.num_kept)
            self._last_saved = step
        ckpt.finalize_checkpoint()


class Trainer:
    """Minimal loop: resume → iterate batches → step → callbacks.

    The analogue of ``NeuronLTModule`` + Lightning ``Trainer.fit`` for users
    who don't bring their own loop.
    """

    def __init__(self, step_fn: Callable, state: Any,
                 callbacks: Optional[List[Callback]] = None,
                 resume_path: Optional[str] = None,
                 eval_fn: Optional[Callable] = None,
                 preemption_guard: Optional[Any] = None):
        self.step_fn = step_fn
        self.eval_fn = eval_fn
        self.state = state
        self.callbacks = callbacks or []
        self.tokens_per_batch = 0
        # a resilience.PreemptionGuard: fit() honors a SIGTERM/SIGINT
        # request at the next step boundary with an emergency checkpoint
        self.preemption_guard = preemption_guard
        if preemption_guard is not None and not preemption_guard.installed:
            preemption_guard.install()
        # pre-step snapshot for callbacks that roll back a bad update
        # (Watchdog skip_step); only kept when a callback asks for it —
        # valid only with a non-donating step_fn
        self._track_prev = any(
            getattr(cb, "needs_prev_state", False) for cb in self.callbacks)
        self._prev_state: Optional[Any] = None
        # observability: compile tracking of the compiled step (alerts on
        # recompiles through the shared event channel) + phase spans.
        # When obs is disabled every hook below is a single bool check.
        self._compile_tracker = CompileTracker.for_function(
            "trainer/step", step_fn)
        # obs handle cache, keyed by (registry, generation) so a mid-run
        # reset() rebuilds the children instead of writing into dropped
        # metrics
        self._obs_cache = None
        # host-side mirror of state.step: callbacks read this instead of
        # int(state.step), which would force a device sync every iteration
        # and break async dispatch overlap
        self.host_step = int(state.step)
        if resume_path is not None and ckpt.has_checkpoint(resume_path):
            target = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding), state)
            self.state, _ = ckpt.load_checkpoint(resume_path, tag=None,
                                                 target=target)
            self.host_step = int(self.state.step)
            logger.info("resumed from step %d", self.host_step)

    def fit(self, batches: Iterable, max_steps: Optional[int] = None,
            eval_batches: Optional[Iterable] = None,
            eval_every: Optional[int] = None):
        """Train; optionally evaluate every ``eval_every`` steps and once
        at the end — the validation-loop role of the reference's Lightning
        adapter. Eval metrics reach ``on_eval_end`` and the returned
        metrics dict under ``eval_*`` keys (they are NOT visible to
        ``on_step_end``, which fires before each eval)."""
        if eval_batches is not None:
            if self.eval_fn is None:
                # fail in milliseconds, not after the whole training run
                raise ValueError(
                    "fit(eval_batches=...) requires eval_fn at "
                    "construction")
            # materialise once: a one-shot generator would silently yield
            # zero batches on every eval after the first
            eval_batches = list(eval_batches)
        for cb in self.callbacks:
            cb.on_train_start(self)
        metrics: Dict = {}
        evaluated_at = -1
        tracer = get_tracer()
        reg = get_registry()
        batch_iter = iter(batches)
        while True:
            if max_steps is not None and self.host_step >= max_steps:
                break
            # phase: data — host-side input pipeline latency
            with tracer.span("train/data", step=self.host_step):
                try:
                    batch = next(batch_iter)
                except StopIteration:
                    break
            ids = batch.get("input_ids")
            self.tokens_per_batch = int(ids.size) if ids is not None else 0
            if self._track_prev:
                self._prev_state = self.state
            # phase: step — dispatch of the compiled step (async under
            # jit: wall time here is dispatch + any blocking compile)
            t0 = time.perf_counter()
            with tracer.span("train/step", step=self.host_step):
                self.state, metrics = self.step_fn(self.state, batch)
            wall_s = time.perf_counter() - t0
            self._compile_tracker.poll(wall_s=wall_s)
            self.host_step += 1
            if reg.enabled:
                cache = self._obs_cache
                if (cache is None or cache[0] is not reg
                        or cache[1] != reg.generation):
                    cache = (reg, reg.generation,
                             reg.counter("nxd_train_steps_total",
                                         "Train steps completed."),
                             reg.histogram(
                                 "nxd_train_step_seconds",
                                 "Wall time per train step (dispatch + "
                                 "any blocking compile) — the planner's "
                                 "compute-efficiency calibration "
                                 "source."))
                    self._obs_cache = cache
                cache[2].inc()
                cache[3].observe(wall_s)
            # phase: checkpoint et al. — callbacks (CheckpointCallback
            # opens its own train/checkpoint span inside)
            with tracer.span("train/callbacks", step=self.host_step):
                for cb in self.callbacks:
                    cb.on_step_end(self, metrics)
            if (self.preemption_guard is not None
                    and self.preemption_guard.requested):
                # step boundary: the request recorded by the signal handler
                # becomes a synchronous emergency save + resumable exit
                self._handle_preemption()
            if (eval_batches is not None and eval_every
                    and self.host_step % eval_every == 0):
                metrics.update(self.evaluate(eval_batches))
                evaluated_at = self.host_step
        if eval_batches is not None and evaluated_at != self.host_step:
            metrics.update(self.evaluate(eval_batches))
        for cb in self.callbacks:
            cb.on_train_end(self)
        return self.state, metrics

    def _checkpoint_path(self) -> Optional[str]:
        """Where an emergency save goes: the guard's explicit path, else
        the first CheckpointCallback's — the run resumes from the same
        directory it periodically checkpoints to."""
        if self.preemption_guard is not None and \
                self.preemption_guard.checkpoint_path:
            return self.preemption_guard.checkpoint_path
        for cb in self.callbacks:
            if isinstance(cb, CheckpointCallback):
                return cb.path
        return None

    def _handle_preemption(self) -> None:
        from ..resilience.preemption import TrainingPreempted

        guard = self.preemption_guard
        guard.announce(self.host_step)
        path = self._checkpoint_path()
        saved_tag = None
        if path is not None:
            saved_tag = self._emergency_save(path, guard.remaining_grace())
        else:
            logger.warning(
                "preempted with no checkpoint path (no PreemptionGuard "
                "checkpoint_path and no CheckpointCallback); flushing "
                "in-flight commits only")
            ckpt.finalize_checkpoint()
        log_event(logger, "preemption_exit", step=self.host_step,
                  saved_tag=saved_tag)
        raise TrainingPreempted(self.host_step, saved_tag)

    def _emergency_save(self, path: str, grace_s: float) -> Optional[str]:
        """Synchronous save bounded by the grace deadline. A save that
        cannot finish in time degrades to flushing the in-flight async
        commits — the last periodic checkpoint stays the resume point
        rather than a half-written emergency tag (which the commit
        protocol would reject on resume anyway)."""
        tag = str(self.host_step)
        box: Dict[str, Any] = {}

        def run():
            try:
                ckpt.save_checkpoint(path, tag, self.state,
                                     async_save=False)
                box["ok"] = True
            except BaseException as e:  # noqa: BLE001 - reported below
                box["err"] = e

        # daemon: if the deadline fires we abandon the writer thread so the
        # process can still exit inside the platform's kill window
        t = threading.Thread(target=run, daemon=True,
                             name="ckpt-emergency")
        t.start()
        t.join(timeout=max(grace_s, 0.0))
        if t.is_alive():
            logger.warning(
                "emergency checkpoint %s/%s exceeded the %.1fs grace "
                "deadline; falling back to flushing in-flight commits",
                path, tag, grace_s)
            try:
                ckpt.finalize_checkpoint()
            except Exception:
                logger.exception("flushing in-flight commits failed")
            return None
        if "err" in box:
            logger.error("emergency checkpoint %s/%s failed: %r — falling "
                         "back to flushing in-flight commits", path, tag,
                         box["err"])
            try:
                ckpt.finalize_checkpoint()
            except Exception:
                logger.exception("flushing in-flight commits failed")
            return None
        logger.info("emergency checkpoint saved: %s/%s", path, tag)
        return tag

    def evaluate(self, batches: Iterable) -> Dict:
        """Mean loss over ``batches`` with NO gradient/optimizer work.

        Uses ``eval_fn(params, batch) -> scalar loss`` when provided;
        otherwise derives it is an error (the step_fn mutates state). The
        model runs without a dropout rng, so dropout-gated modules are
        deterministic.
        """
        if self.eval_fn is None:
            raise ValueError(
                "Trainer.evaluate requires eval_fn (params, batch) -> "
                "loss; pass it at construction "
                "(e.g. lambda p, b: pm.module.apply(p, b['input_ids'], "
                "b['labels'], method='loss'))")
        total, n = 0.0, 0
        for batch in batches:
            total += float(self.eval_fn(self.state.params, batch))
            n += 1
        metrics = {"eval_loss": total / max(n, 1), "eval_batches": n}
        for cb in self.callbacks:
            cb.on_eval_end(self, metrics)
        return metrics
