"""GPT-NeoX model family.

Parity target: the reference's gpt-neox training example
(``examples/training/gpt_neox``, 20B config in
``test/integration/gpt_neox_20B``). Architecture: parallel residual
(``x + attn(ln1(x)) + mlp(ln2(x))``), LayerNorm with bias, partial rotary
(``rotary_pct`` of each head dim), biased linears — all built from the same
parallel layers as llama.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.norms import LayerNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mesh as ps


@dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    intermediate_size: int = 24576
    num_layers: int = 44
    num_heads: int = 64
    max_seq_len: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layernorm_eps: float = 1e-5
    use_parallel_residual: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    remat: bool = False
    scan_layers: bool = True
    # Pallas flash path (any head_dim — non-128 widths lane-pad in the
    # kernel dispatcher; the reference's NKI flash serves its whole zoo,
    # kernels/flash_attn.py:162)
    use_flash_attention: bool = False
    # attention-probability dropout (HF gpt_neox attention_dropout; active
    # iff a "dropout" rng is supplied — counter-based masks shared with
    # the flash kernels, in-kernel on the Pallas path)
    attention_dropout: float = 0.0
    tp_size: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        """Rotated slice of each head dim (even; 0 disables rotary —
        apply_rotary splits the slice in half)."""
        return (int(self.head_dim * self.rotary_pct) // 2) * 2


GPT_NEOX_20B = GPTNeoXConfig()


def tiny_neox_config(**kw) -> GPTNeoXConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=256,
                num_layers=2, num_heads=4, max_seq_len=128)
    base.update(kw)
    return GPTNeoXConfig(**base)


class NeoXAttention(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions=None):
        cfg = self.cfg
        hd = cfg.head_dim
        q, k, v = pl.GQAQKVColumnParallelLinear(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_heads,
            head_dim=hd, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, tp_size=cfg.tp_size,
            name="qkv")(x)
        b, s = q.shape[0], q.shape[1]
        n_local = q.shape[-1] // hd
        q = q.reshape(b, s, n_local, hd)
        k = k.reshape(b, s, n_local, hd)
        v = v.reshape(b, s, n_local, hd)
        # partial rotary: first rotary_dim of the head dim rotates
        rot = cfg.rotary_dim
        if rot > 0:
            q = jnp.concatenate([
                attn_mod.apply_rotary(q[..., :rot], cos, sin, positions),
                q[..., rot:]], axis=-1)
            k = jnp.concatenate([
                attn_mod.apply_rotary(k[..., :rot], cos, sin, positions),
                k[..., rot:]], axis=-1)
        dropout_p, dropout_seed = attn_mod.attention_dropout_seed(
            self, cfg.attention_dropout)
        if cfg.use_flash_attention:
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True,
                                  dropout_p=dropout_p,
                                  dropout_seed=dropout_seed)
        else:
            out = attn_mod.sdpa_reference(q, k, v, causal=True,
                                          dropout_p=dropout_p,
                                          dropout_seed=dropout_seed)
        out = out.reshape(b, s, n_local * hd)
        return pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, name="o_proj")(out)


class NeoXMLP(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = pl.ColumnParallelLinear(
            features=cfg.intermediate_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, name="up")(x)
        h = nn.gelu(h, approximate=False)  # HF uses erf gelu
        return pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, name="down")(h)


class NeoXLayer(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions=None):
        cfg = self.cfg
        ln_kw = dict(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                     sequence_parallel=cfg.sequence_parallel)
        attn_out = NeoXAttention(cfg, name="attn")(
            LayerNorm(**ln_kw, name="ln1")(x), cos, sin, positions)
        if cfg.use_parallel_residual:
            mlp_out = NeoXMLP(cfg, name="mlp")(
                LayerNorm(**ln_kw, name="ln2")(x))
            return x + attn_out + mlp_out
        x = x + attn_out
        return x + NeoXMLP(cfg, name="mlp")(
            LayerNorm(**ln_kw, name="ln2")(x))


class _NeoXScanBody(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions):
        return NeoXLayer(self.cfg, name="layer")(x, cos, sin, positions), None


class GPTNeoXForCausalLM(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        from ..parallel import mappings

        x = pl.ParallelEmbedding(
            num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed")(
                input_ids)
        if cfg.sequence_parallel:
            x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
        cos, sin = attn_mod.precompute_rope(max(2, cfg.rotary_dim),
                                            cfg.max_seq_len, cfg.rope_theta)
        if cfg.scan_layers:
            body_cls = _NeoXScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            scanned = nn.scan(
                body_cls, variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"})(
                    cfg, name="layers")
            x, _ = scanned(x, cos, sin, positions)
        else:
            for i in range(cfg.num_layers):
                x = NeoXLayer(cfg, name=f"layer_{i}")(x, cos, sin, positions)
        x = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                      sequence_parallel=cfg.sequence_parallel,
                      name="final_norm")(x)
        logits = pl.ColumnParallelLinear(
            features=cfg.vocab_size, use_bias=False, gather_output=False,
            sequence_parallel=cfg.sequence_parallel, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="lm_head")(x)
        return logits

    def loss(self, input_ids, labels, ignore_index: int = -100):
        logits = self(input_ids)
        return lf.causal_lm_loss(logits, labels, ignore_index=ignore_index)
