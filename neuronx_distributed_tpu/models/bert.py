"""BERT model family (encoder + MLM pretraining head).

Parity target: the reference's bert pretraining example
(``examples/training/bert``; the reference's original demo workload).
Bidirectional encoder built from the same parallel layers: learned position
embeddings, post-LN transformer blocks, gelu MLP, tied or untied MLM head
with vocab-parallel cross-entropy over masked positions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.norms import LayerNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layernorm_eps: float = 1e-12
    # HF BertForMaskedLM head: transform dense + gelu + LN, decoder tied to
    # the word embeddings with a free bias (cls.predictions.*)
    mlm_transform: bool = False
    # dropout (HF bert defaults are 0.1/0.1): active iff a "dropout" rng is
    # supplied to apply() — no deterministic-flag threading. Attention-prob
    # dropout uses the counter-based hash shared with the flash kernels;
    # hidden dropout applies after each sublayer projection, pre-residual.
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # Pallas flash path (bidirectional, causal=False; d=64 lane-pads)
    use_flash_attention: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    tp_size: Optional[int] = None


BERT_LARGE = BertConfig()


def tiny_bert_config(**kw) -> BertConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, max_seq_len=64)
    base.update(kw)
    return BertConfig(**base)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        train = self.has_rng("dropout")
        hd = cfg.hidden_size // cfg.num_heads
        q, k, v = pl.GQAQKVColumnParallelLinear(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_heads,
            head_dim=hd, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, tp_size=cfg.tp_size,
            name="qkv")(x)
        b, s = q.shape[0], q.shape[1]
        n_local = q.shape[-1] // hd
        q = q.reshape(b, s, n_local, hd)
        k = k.reshape(b, s, n_local, hd)
        v = v.reshape(b, s, n_local, hd)
        dropout_p, dropout_seed = attn_mod.attention_dropout_seed(
            self, cfg.attention_dropout)
        if cfg.use_flash_attention:
            from ..ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=False,
                                   dropout_p=dropout_p,
                                   dropout_seed=dropout_seed)
        else:
            attn = attn_mod.sdpa_reference(q, k, v, causal=False,
                                           dropout_p=dropout_p,
                                           dropout_seed=dropout_seed)
        attn = attn.reshape(b, s, n_local * hd)
        attn = pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj")(attn)
        hidden_drop = nn.Dropout(rate=cfg.hidden_dropout)
        attn = hidden_drop(attn, deterministic=not train)
        x = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                      name="ln_attn")(x + attn)
        h = pl.ColumnParallelLinear(
            features=cfg.intermediate_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="up")(x)
        h = nn.gelu(h, approximate=False)  # HF uses erf gelu
        h = pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="down")(h)
        h = hidden_drop(h, deterministic=not train)
        return LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                         name="ln_mlp")(x + h)


class _BertScanBody(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x):
        return BertLayer(self.cfg, name="layer")(x), None


class BertForPreTraining(nn.Module):
    """Encoder + MLM head (``loss`` masks to the -100-ignored labels)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.cfg
        embed_mod = pl.ParallelEmbedding(
            num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed")
        x = embed_mod(input_ids)
        pos_table = self.param(
            "position_embedding",
            nn.with_partitioning(pl.default_embed_init, (None, None)),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
        x = x + pos_table[None, :x.shape[1]].astype(cfg.dtype)
        if token_type_ids is not None:
            type_table = self.param(
                "type_embedding",
                nn.with_partitioning(pl.default_embed_init, (None, None)),
                (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
            x = x + jnp.take(type_table.astype(cfg.dtype), token_type_ids,
                             axis=0)
        x = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                      name="embed_norm")(x)
        x = nn.Dropout(rate=cfg.hidden_dropout)(
            x, deterministic=not self.has_rng("dropout"))
        if cfg.scan_layers:
            body_cls = _BertScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            scanned = nn.scan(
                body_cls, variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"})(
                    cfg, name="layers")
            x, _ = scanned(x)
        else:
            for i in range(cfg.num_layers):
                x = BertLayer(cfg, name=f"layer_{i}")(x)
        if cfg.mlm_transform:
            # HF cls.predictions head: transform dense + erf-gelu + LN,
            # decoder tied to the word embeddings plus a free vocab bias
            from flax.core import meta

            from ..parallel import mesh as ps

            h = pl.ColumnParallelLinear(
                features=cfg.hidden_size, use_bias=True, gather_output=True,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="mlm_transform")(x)
            h = nn.gelu(h, approximate=False)
            h = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                          name="mlm_norm")(h)
            table = meta.unbox(embed_mod.variables["params"]["embedding"])
            logits = pl.embedding_attend(table, h, dtype=cfg.dtype)
            bias = self.param(
                "mlm_bias",
                nn.with_partitioning(nn.initializers.zeros_init(),
                                     (ps.TP_AXIS,)),
                (pl._maybe_local(cfg.vocab_size, ps.TP_AXIS),),
                cfg.param_dtype)
            return logits + bias.astype(cfg.dtype)
        logits = pl.ColumnParallelLinear(
            features=cfg.vocab_size, use_bias=False, gather_output=False,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="mlm_head")(x)
        return logits

    def loss(self, input_ids, labels, ignore_index: int = -100):
        logits = self(input_ids)
        return lf.causal_lm_loss(logits, labels, ignore_index=ignore_index)
