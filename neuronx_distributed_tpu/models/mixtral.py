"""Mixtral (MoE llama) model family.

Parity target: the reference's mixtral training example
(``examples/training/mixtral``) built from its ``MoE`` module — here the
dense llama decoder with the MLP swapped for :class:`..modules.moe.MoE`,
plus router auxiliary losses accumulated through the scanned layer stack.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.moe import MoE
from ..modules.norms import RMSNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mappings
from ..parallel import mesh as ps
from .llama import (LlamaAttention, LlamaConfig, _act_kw, _quant_lm_head,
                    context_parallel_positions)


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    # "capacity" or "blockwise" (dropless; reference expert_mlps_v2.py:691)
    moe_dispatch: str = "capacity"
    moe_block_size: int = 512
    # decode: DMA-elide unhit experts' weights (forward-only; the decode
    # serving path enables this via dataclasses.replace — see
    # mixtral_forward_with_cache)
    moe_sentinel_empty: bool = False
    # EP dispatch wire dtype ("fp32" | "int8" | "fp8"): quantizes the token
    # gather/combine payloads over ep (blockwise dispatch only; see
    # parallel/ep_dispatch.py)
    moe_ep_wire_dtype: str = "fp32"
    # decomposed (ppermute-ring) EP dispatch overlapping per-chunk expert
    # compute with later hops; None = auto-engage at ep >= 4
    moe_overlap_dispatch: Optional[bool] = None
    # expert bank implementation: "float" | "mx_fp4" | "mx_fp8" (packed
    # microscaling decode weights; convert with mx_pack_expert_params)
    moe_expert_impl: str = "float"
    router_type: str = "top_k"
    shared_expert_intermediate: int = 0
    router_aux_coef: float = 0.02
    router_z_coef: float = 0.001

    def __post_init__(self):
        super().__post_init__()
        if (self.weight_quant is not None
                and self.moe_dispatch != "capacity"
                and self.moe_expert_impl == "float"):
            raise ValueError(
                f"weight_quant={self.weight_quant!r} serves experts "
                "quantized, which requires moe_dispatch='capacity' (got "
                f"{self.moe_dispatch!r}); set moe_dispatch='capacity' or "
                "pin moe_expert_impl explicitly")

    @property
    def moe_expert_impl_(self) -> str:
        """Effective expert bank impl: an active ``weight_quant`` tier
        quantizes the experts too unless ``moe_expert_impl`` was pinned."""
        if self.weight_quant is not None and self.moe_expert_impl == "float":
            return _WEIGHT_QUANT_EXPERT_IMPL[self.weight_quant]
        return self.moe_expert_impl


# weight_quant tier -> quantized expert bank implementation
_WEIGHT_QUANT_EXPERT_IMPL = {"int8": "int8", "fp8": "fp8",
                             "mxfp4": "mx_fp4", "mxfp8": "mx_fp8"}


MIXTRAL_8X7B = MixtralConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=1e6,
    num_experts=8, top_k=2)

# DBRX (reference: examples/training/dbrx): 16 fine-grained experts, top-4,
# GQA with 8 kv heads — same decoder skeleton, different routing width.
DBRX = MixtralConfig(
    vocab_size=100352, hidden_size=6144, intermediate_size=10752,
    num_layers=40, num_heads=48, num_kv_heads=8, rope_theta=5e5,
    max_seq_len=32768, num_experts=16, top_k=4)


def tiny_moe_config(**kw) -> MixtralConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
                num_experts=4, top_k=2)
    base.update(kw)
    return MixtralConfig(**base)


class MixtralDecoderLayer(nn.Module):
    cfg: MixtralConfig
    # Reduced-sync TP: False elides the attention exit all-reduce. The MoE
    # block keeps its internal tp reduction (its expert-combine psum also
    # moves tokens, so it cannot be elided); its replicated output is
    # scaled to a 1/n share instead, so an unsynced layer's deviation from
    # the last synced hidden state still sums to the true update under the
    # model's periodic resync psum.
    tp_sync: bool = True

    @nn.compact
    def __call__(self, x, cos, sin, positions=None, cache=None,
                 cache_index=None):
        cfg = self.cfg
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="input_norm")(x)
        attn_out = LlamaAttention(cfg, tp_sync=self.tp_sync, name="attn")(
            h, cos, sin, positions, cache=cache, cache_index=cache_index)
        new_cache = None
        if cache is not None:
            attn_out, new_cache = attn_out
        x = x + attn_out
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="post_norm")(x)
        if cfg.sequence_parallel:
            # routing needs full sequences: gather with to_model_parallel=
            # False (bwd = split) because ExpertMLPs' internal copy_to
            # already psums grads over tp — a reduce-scatter pairing here
            # would double-reduce (cf. the lm_head composition note in
            # llama.py)
            h = mappings.gather_from_sequence_parallel_region(
                h, seq_dim=1, to_model_parallel=False)
        moe_out, aux = MoE(
            num_experts=cfg.num_experts, hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor,
            dispatch_mode=cfg.moe_dispatch,
            block_size=cfg.moe_block_size,
            sentinel_empty=cfg.moe_sentinel_empty,
            ep_wire_dtype=cfg.moe_ep_wire_dtype,
            ep_overlap=cfg.moe_overlap_dispatch,
            expert_impl=cfg.moe_expert_impl_,
            router_type=cfg.router_type,
            shared_expert_intermediate=cfg.shared_expert_intermediate,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="moe")(h)
        if cfg.sequence_parallel:
            # output is fully tp-reduced and replicated: re-shard the
            # sequence with a plain split (bwd all-gather)
            moe_out = mappings.scatter_to_sequence_parallel_region(
                moe_out, seq_dim=1)
        if not self.tp_sync:
            n = pl._bound_size(ps.TP_AXIS) or 1
            moe_out = moe_out / n
        x = x + moe_out
        aux_vec = jnp.stack([aux["load_balance_loss"], aux["z_loss"]])
        if cache is not None:
            return x, aux_vec, new_cache
        return x, aux_vec


class _MoEScanBody(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions):
        x, aux = MixtralDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions)
        return x, aux


class _MoEDecodeScanBody(nn.Module):
    """Cached-decode scan body (the MoE analogue of llama's
    ``_DecodeScanBody``; reference mixtral serving uses the same base
    model_builder keys)."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, cache_kv, slot_pos, cos, sin, positions,
                 cache_index):
        k_l, v_l = cache_kv
        x, _, new_cache = MixtralDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions, cache=(k_l, v_l, slot_pos),
            cache_index=cache_index)
        return x, new_cache


class _MoEPagedScanBody(nn.Module):
    """nn.scan body for paged MoE decode — the mixtral analogue of llama's
    ``_PagedScanBody`` (same ``layer`` scope as :class:`_MoEDecodeScanBody`,
    so one checkpoint serves both cache protocols). The attention sublayer
    already understands :class:`..inference.paging.PagedCacheView`; the MoE
    sublayer is cache-free, so only the view plumbing differs."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, cache_kv, pool_pos, tables, write_idx, cos, sin,
                 positions):
        from ..inference.paging import PagedCacheView

        if len(cache_kv) == 4:
            k_l, v_l, ks_l, vs_l = cache_kv
        else:
            (k_l, v_l), ks_l, vs_l = cache_kv, None, None
        view = PagedCacheView(k=k_l, v=v_l, k_scale=ks_l, v_scale=vs_l,
                              pos=pool_pos, tables=tables,
                              write_idx=write_idx)
        x, _, new_view = MixtralDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions, cache=view, cache_index=None)
        if len(cache_kv) == 4:
            return x, (new_view.k, new_view.v, new_view.k_scale,
                       new_view.v_scale)
        return x, (new_view.k, new_view.v)


class MixtralModel(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        x = pl.ParallelEmbedding(
            num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed")(
                input_ids)
        positions = context_parallel_positions(input_ids, positions)
        if cfg.sequence_parallel:
            x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        if cfg.scan_layers:
            body_cls = _MoEScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            scanned = nn.scan(
                body_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            x, aux = scanned(x, cos, sin, positions)
            aux = jnp.sum(aux, axis=0)
        else:
            auxes = []
            layer_cls = MixtralDecoderLayer
            if cfg.remat:
                layer_cls = nn.remat(
                    layer_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            from ..ops import collective_matmul as cm

            sched = cm.tp_sync_schedule(cfg.num_layers,
                                        cfg.activation_sync_fraction)
            # see LlamaModel: only engage over a real bound tp axis
            n_tp = pl._bound_size(ps.TP_AXIS)
            reduced = (cfg.activation_sync_fraction < 1.0
                       and n_tp is not None and n_tp > 1)
            # reduced-sync resync (see LlamaModel): psum the accumulated
            # deviation from the last synced hidden state before every
            # synced layer
            x_ref = x
            pending = False
            for i in range(cfg.num_layers):
                if reduced and pending and sched[i]:
                    x = x_ref + mappings.reduce_from_tensor_parallel_region(
                        x - x_ref)
                    pending = False
                x, a = layer_cls(cfg, tp_sync=sched[i] if reduced else True,
                                 name=f"layer_{i}")(x, cos, sin, positions)
                auxes.append(a)
                if reduced:
                    if sched[i]:
                        x_ref = x
                    else:
                        pending = True
            aux = jnp.sum(jnp.stack(auxes), axis=0)
        x = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel, name="norm")(x)
        return x, aux


class MixtralForCausalLM(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if cfg.tie_embeddings:
            raise ValueError(
                "tie_embeddings is not supported for Mixtral (HF Mixtral "
                "never ties); use an explicit lm_head")
        x, aux = MixtralModel(cfg, name="model")(input_ids, positions)
        if cfg.weight_quant is not None:
            logits = _quant_lm_head(cfg, False, name="lm_head")(x)
        else:
            logits = pl.ColumnParallelLinear(
                features=cfg.vocab_size, use_bias=False,
                gather_output=False,
                sequence_parallel=cfg.sequence_parallel,
                overlap_comm=cfg.overlap_comm, **_act_kw(cfg),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="lm_head")(x)
        return logits, aux

    def loss(self, input_ids, labels, ignore_index: int = -100):
        cfg = self.cfg
        logits, aux = self(input_ids)
        ce = lf.causal_lm_loss(logits, labels, ignore_index=ignore_index)
        return (ce + cfg.router_aux_coef * aux[0]
                + cfg.router_z_coef * aux[1])


def mixtral_forward_with_cache(cfg: MixtralConfig, params,
                               input_ids: jax.Array,
                               positions: jax.Array, kv_cache,
                               slot_ids=None):
    """KV-cached forward for MoE serving ("context_encoding" /
    "token_generation" keys) — the mixtral analogue of
    :func:`.llama.llama_forward_with_cache` (the reference serves mixtral
    through the same base model_builder keys,
    ``examples/inference/modules/model_base.py``).

    At decode the tiny token count makes the dropless blockwise dispatch
    with a small block size the natural expert path
    (``cfg.moe_dispatch='blockwise'``); empty-block sentinels are enabled
    here so each step reads only the experts its tokens hit — the
    bandwidth-side equivalent of the reference's fused token-gen MoE
    kernel (``moe_fused_tkg.py:85``; forward-only, so the training-side dW
    constraint does not apply).

    Paged protocol (llama parity): pass a
    :class:`..inference.paging.PagedKVCache` plus ``slot_ids [T]`` mapping
    each packed token (``input_ids [1, T]``) to its cache slot; K/V land in
    the slot's block-table blocks. Contiguous callers are untouched.
    """
    import dataclasses

    from ..inference.kv_cache import KVCache
    from ..inference.paging import PagedKVCache, QuantizedPagedKVCache

    if not cfg.scan_layers:
        raise ValueError("cached decode requires scan_layers=True")
    paged = isinstance(kv_cache, (PagedKVCache, QuantizedPagedKVCache))
    if paged:
        if slot_ids is None:
            raise ValueError("paged cache forward requires slot_ids [T]")
        if input_ids.shape[0] != 1:
            raise ValueError(
                "paged decode packs requests into one row batch [1, T]; "
                f"got batch {input_ids.shape[0]}")
    # token-generation-sized calls only: at prefill (large batch*seq) most
    # experts are hit anyway and the decode kernel's partial-sum layout
    # would cost O(num_ib * tokens * H) HBM for nothing (measured crossover
    # ~T=4 tokens TOTAL, BASELINE.md r3 decode-MoE table — so the batch dim
    # counts, advisor r3)
    total_tokens = input_ids.shape[0] * input_ids.shape[1]
    if (cfg.moe_dispatch == "blockwise" and not cfg.moe_sentinel_empty
            and total_tokens * cfg.top_k <= cfg.num_experts):
        cfg = dataclasses.replace(cfg, moe_sentinel_empty=True)
    p = params["params"]
    b, s = input_ids.shape
    positions = jnp.asarray(positions, jnp.int32)

    embed = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    x = embed.apply({"params": p["model"]["embed"]}, input_ids)
    cos, sin = attn_mod.precompute_rope(
        cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
        use_scaled=cfg.rope_scaling)

    rope_pos = jnp.minimum(positions, cfg.max_seq_len - 1)

    if paged:
        from ..inference import paging as _paging

        slot_ids = jnp.asarray(slot_ids, jnp.int32)
        # per-token routing (see llama_forward_with_cache paged branch):
        # each packed token carries its slot's block-table row and a flat
        # pool index for this step's K/V write
        tok_tables = kv_cache.block_tables[
            jnp.clip(slot_ids, 0, kv_cache.max_slots - 1)]
        write_idx = _paging.flat_write_indices(
            tok_tables, positions[0], kv_cache.block_size,
            kv_cache.capacity)
        slot_pos = _paging.write_pool_positions(kv_cache.pos, positions[0],
                                                write_idx)
        scanned = nn.scan(
            _MoEPagedScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                     nn.broadcast, nn.broadcast, nn.broadcast),
            out_axes=0,
            length=cfg.num_layers,
        )(cfg)
        pool_quantized = isinstance(kv_cache, QuantizedPagedKVCache)
        cache_kv = ((kv_cache.k, kv_cache.v, kv_cache.k_scale,
                     kv_cache.v_scale) if pool_quantized
                    else (kv_cache.k, kv_cache.v))
        x, new_kv = scanned.apply(
            {"params": p["model"]["layers"]}, x,
            cache_kv, slot_pos, tok_tables, write_idx,
            cos, sin, rope_pos)
    else:
        slot_pos = jax.lax.dynamic_update_slice_in_dim(
            kv_cache.pos, positions, kv_cache.index, axis=1)
        scanned = nn.scan(
            _MoEDecodeScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                     nn.broadcast, nn.broadcast),
            out_axes=0,
            length=cfg.num_layers,
        )(cfg)
        x, new_kv = scanned.apply(
            {"params": p["model"]["layers"]}, x, (kv_cache.k, kv_cache.v),
            slot_pos, cos, sin, rope_pos, kv_cache.index)

    x = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype).apply(
        {"params": p["model"]["norm"]}, x)
    if cfg.weight_quant is not None:
        head = _quant_lm_head(cfg, True)
    else:
        head = pl.ColumnParallelLinear(
            features=cfg.vocab_size, use_bias=False, gather_output=True,
            overlap_comm=cfg.overlap_comm, **_act_kw(cfg),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    logits = head.apply({"params": p["lm_head"]}, x)
    if paged:
        if isinstance(kv_cache, QuantizedPagedKVCache):
            new_k, new_v, nks, nvs = new_kv
            new_cache = kv_cache.replace(k=new_k, v=new_v, k_scale=nks,
                                         v_scale=nvs, pos=slot_pos)
        else:
            new_k, new_v = new_kv
            new_cache = kv_cache.replace(k=new_k, v=new_v, pos=slot_pos)
    else:
        new_k, new_v = new_kv
        new_cache = KVCache(k=new_k, v=new_v, pos=slot_pos,
                            index=kv_cache.index + s)
    return logits, new_cache
