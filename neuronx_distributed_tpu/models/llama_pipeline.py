"""Pipeline-parallel Llama training path.

The analogue of the reference's llama + ``NxDPPModel`` composition
(``examples/training/llama/tp_pp_llama_hf_pretrain/run_llama_nxd.py``,
``pipeline/model.py:74``): the decoder stack is partitioned over the ``pp``
mesh axis (layer-stacked params sharded on their leading scan dim — the
partition is a *sharding*, not an fx graph split), the embedding runs on
stage 0 and the norm+LM-head+loss on the last stage, and the microbatch
schedule executes as one scanned SPMD program (:mod:`..pipeline.spmd_engine`).

Params are byte-compatible with :class:`..models.llama.LlamaForCausalLM`
(``scan_layers=True``) — the same checkpoint trains with or without pp.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modules import attention as attn_mod
from ..modules.norms import RMSNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mappings
from ..parallel import mesh as ps
from ..pipeline import spmd_engine as eng
from .llama import LlamaConfig, _ScanBody

PIPELINE_LOGICAL_RULES = {"layers": ps.PP_AXIS}


def pipelined_loss_fn(cfg: LlamaConfig, num_microbatches: int,
                      ignore_index: int = -100):
    """Build ``pp_loss(params, ids, labels) -> scalar`` to run inside
    shard_map over the full (pp, dp, cp, tp) mesh.

    ``params`` is the LlamaForCausalLM variables dict whose scanned-layer
    leaves arrive pp-sharded (leading dim L/S locally).
    """
    if not cfg.scan_layers:
        raise ValueError("pipeline path requires scan_layers=True")
    if getattr(cfg, "attention_dropout", 0.0) > 0.0:
        # the GPipe engine differentiates one scanned forward and has no
        # per-microbatch rng channel; the explicit-VJP executor does — a
        # silent skip here would fake regularization (cf. the CP dropout
        # guard history in models/llama.py)
        raise ValueError(
            "attention_dropout under PP requires the 1F1B executor "
            "(make_pipeline_grad_fn(..., schedule='1f1b' or "
            "'interleaved')); the GPipe schedule has no per-microbatch "
            "rng channel")

    embed_mod = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    norm_mod = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                       sequence_parallel=cfg.sequence_parallel)
    head_mod = pl.ColumnParallelLinear(
        features=cfg.vocab_size, use_bias=False, gather_output=False,
        sequence_parallel=cfg.sequence_parallel,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)

    def pp_loss(params, ids, labels):
        p = params["params"]
        S = ps.get_pipeline_model_parallel_size()
        M = num_microbatches
        if cfg.num_layers % S != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by pp {S}")
        l_local = cfg.num_layers // S

        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        # ---- stage 0: embedding (pp-replicated params; grads assembled
        # from stage 0 via copy_to's backward psum). The embed runs
        # per-tick INSIDE the pipeline, cond-gated to stage 0 — only the
        # int32 ids ride the scan replicated, not [M, mb, S, H]
        # activations (VERDICT r4 weak #7)
        embed_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                         p["model"]["embed"])
        ids_mb = eng.microbatch(ids, M)

        def input_fn(ids_):
            x = embed_mod.apply({"params": embed_p}, ids_)
            if cfg.sequence_parallel:
                x = mappings.scatter_to_sequence_parallel_region(x,
                                                                 seq_dim=1)
            return x

        # ---- pipelined decoder stack over local layers
        body = nn.scan(
            _ScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=l_local,
        )(cfg)

        def stage_fn(act):
            out, _ = body.apply({"params": p["model"]["layers"]}, act, cos,
                                sin, None)
            return out

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

        outs = eng.pipeline_spmd(stage_fn, ids_mb, S, M, input_fn=input_fn)

        # ---- last stage: final norm + LM head + vocab-parallel CE,
        # accumulated per microbatch
        norm_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                        p["model"]["norm"])
        if cfg.tie_embeddings:
            # tied word embeddings: the head re-uses the (already
            # stage-replicated-wrapped) embedding table — the copy_to
            # backward psum over pp collects the stage-0 embedding grad and
            # the last-stage head grad into one (reference
            # register_shared_weights/_reduce_shared_weights,
            # pipeline/model.py:750,791)
            head_p = embed_p["embedding"]
        else:
            head_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                            p["lm_head"])
        labels_mb = eng.microbatch(labels, M)

        def mb_loss(carry, om):
            o, lb = om
            h = norm_mod.apply({"params": norm_p}, o)
            if cfg.tie_embeddings:
                logits = pl.embedding_attend(
                    head_p, h, sequence_parallel=cfg.sequence_parallel,
                    dtype=cfg.dtype)
            else:
                logits = head_mod.apply({"params": head_p}, h)
            per_tok = lf.parallel_cross_entropy(logits, lb,
                                                ignore_index=ignore_index)
            n_valid = jnp.sum((lb != ignore_index).astype(jnp.float32))
            return (carry[0] + jnp.sum(per_tok), carry[1] + n_valid), None

        (loss_sum, denom), _ = jax.lax.scan(
            mb_loss, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (outs, labels_mb))
        local = loss_sum / jnp.maximum(denom, 1.0)
        loss = eng.last_stage_value(local)
        return eng.data_parallel_mean(loss)

    return pp_loss


def make_pipeline_grad_fn(cfg: LlamaConfig, num_microbatches: int,
                          param_specs: Any,
                          ignore_index: int = -100,
                          schedule: str = "gpipe",
                          num_chunks: int = 1,
                          vocab_pp: bool = False,
                          dropout_seed: int = 0):
    """Build ``grad_fn(params, batch) -> (loss, grads)`` for
    :func:`..trainer.make_train_step`.

    ``schedule``: ``"gpipe"`` (autodiff of the scanned forward,
    :mod:`..pipeline.spmd_engine`), ``"1f1b"`` or ``"interleaved"``
    (explicit-VJP executor with O(S·C) live activations,
    :mod:`..pipeline.engine_1f1b`) — mirroring the reference's schedule
    selection (``pipeline/model.py:690``).

    Gradients are computed *inside* shard_map and synchronised over the data
    axes with raw psum before crossing the boundary as primal outputs
    (see :mod:`..parallel.grads` — cotangents must not cross the shard_map
    boundary). ``param_specs``: the ParallelModel's spec tree (built with
    ``logical_axis_rules=PIPELINE_LOGICAL_RULES``).
    """
    from ..parallel import grads as grads_mod

    if schedule != "interleaved" and num_chunks != 1:
        raise ValueError(
            f"num_chunks={num_chunks} only applies to "
            f"schedule='interleaved', got schedule={schedule!r}")
    if schedule in ("1f1b", "interleaved"):
        return make_1f1b_grad_fn(
            cfg, num_microbatches, param_specs, num_chunks=num_chunks,
            ignore_index=ignore_index, vocab_pp=vocab_pp,
            dropout_seed=dropout_seed)
    if schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if vocab_pp:
        raise ValueError("vocab_pp requires schedule='1f1b'/'interleaved'")

    pp_loss = pipelined_loss_fn(cfg, num_microbatches, ignore_index)

    def inner(params, ids, labels):
        loss, g = jax.value_and_grad(pp_loss)(params, ids, labels)
        g = grads_mod.allreduce_gradients(g, specs=param_specs)
        return loss, g

    def grad_fn(params, batch):
        mesh = ps.get_mesh()
        return ps.shard_map(
            inner, mesh,
            in_specs=(param_specs, P(ps.DP_AXIS, None), P(ps.DP_AXIS, None)),
            out_specs=(P(), param_specs))(
                params, batch["input_ids"], batch["labels"])

    return grad_fn


def _permute_layer_stack(variables: Any, perm) -> Any:
    from jax.sharding import NamedSharding

    def permute(x):
        y = x[perm]
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding):
            # the gather unshards the scan dim; restore the original
            # placement (pp-sharded layer stack)
            y = jax.device_put(y, sh)
        return y

    out = jax.tree_util.tree_map(lambda x: x, variables)  # shallow copy
    out["params"]["model"]["layers"] = jax.tree_util.tree_map(
        permute, variables["params"]["model"]["layers"])
    return out


def unpad_pipeline_params(variables: Any, cfg: LlamaConfig) -> Any:
    """Strip storage pad rows from the layer stack (odd layer counts over
    pp store the stack zero-padded to a multiple of S so it can shard —
    see ``trainer.initialize_parallel_model``). Use before serving, dense
    eval, or checkpoint export to HF."""
    out = jax.tree_util.tree_map(lambda x: x, variables)  # shallow copy
    out["params"]["model"]["layers"] = jax.tree_util.tree_map(
        lambda x: x[:cfg.num_layers],
        variables["params"]["model"]["layers"])
    return out


def interleave_pipeline_params(variables: Any, cfg: LlamaConfig,
                               num_stages: int, num_chunks: int) -> Any:
    """Reorder the scanned layer stack from canonical order into the
    chunk-within-stage storage the interleaved executor expects
    (:func:`..pipeline.engine_1f1b.interleaved_layer_order`)."""
    from ..pipeline.engine_1f1b import interleaved_layer_order

    order = interleaved_layer_order(cfg.num_layers, num_stages, num_chunks)
    return _permute_layer_stack(variables, order)


def deinterleave_pipeline_params(variables: Any, cfg: LlamaConfig,
                                 num_stages: int, num_chunks: int) -> Any:
    """Inverse of :func:`interleave_pipeline_params` (checkpoint export)."""
    import numpy as np

    from ..pipeline.engine_1f1b import interleaved_layer_order

    order = interleaved_layer_order(cfg.num_layers, num_stages, num_chunks)
    return _permute_layer_stack(variables, np.argsort(order))


def make_1f1b_grad_fn(cfg: LlamaConfig, num_microbatches: int,
                      param_specs: Any, num_chunks: int = 1,
                      ignore_index: int = -100, vocab_pp: bool = False,
                      dropout_seed: int = 0):
    """1F1B / interleaved executor (:mod:`..pipeline.engine_1f1b`).

    Unlike the GPipe path, forward and backward interleave explicitly and
    live activation memory is ``O(stages · chunks)`` instead of
    ``O(num_microbatches)`` — the reference's flagship 70B config depends on
    exactly this property (``pipeline/scheduler.py:157``).

    For ``num_chunks > 1`` the layer-stack params must already be stored in
    *interleaved* order — convert a canonical-order tree explicitly with
    :func:`interleave_pipeline_params` (and back with
    :func:`deinterleave_pipeline_params` before checkpoint export); passing
    a canonical-order tree would silently train a layer-permuted model.

    ``vocab_pp=True`` additionally shards the embedding table and LM head
    over the pp axis (vocab dim ``(pp, tp)``): every stage holds a
    ``1/(S·tp)`` vocab shard of the params and of the engine's f32 grad
    accumulators instead of a pp-replicated copy — the SPMD counterpart of
    the reference placing shared vocab weights only on owning stages
    (``pipeline/model.py:750,791``), at the cost of ~3 act-sized pp psums
    per embed/head tick.

    With ``cfg.attention_dropout > 0`` the dropout rng IS threaded through
    this executor: each stage folds the engine's microbatch slot σ(f,c)
    (identical in the forward tick and the vjp recompute — see
    ``engine_1f1b.pipeline_1f1b_grads(stage_takes_slot=...)``) plus its pp
    index into ``jax.random.key(dropout_seed)``, and ``nn.scan`` splits the
    result per layer — masks are distinct per (microbatch, chunk, stage,
    layer) and bit-identical between forward and backward recompute. Masks
    are a pure function of ``(dropout_seed, step, slot, stage)``: they vary
    across optimizer steps only when the caller puts an integer
    ``batch["dropout_step"]`` in the batch (``make_train_step``'s grad_fn
    contract has no rng channel, so the step must ride the batch).

    NOTE: :func:`.mixtral_pipeline.make_moe_1f1b_grad_fn` mirrors this
    scaffolding (adding router-aux seeding); keep the two in sync.
    """
    from ..parallel import comm
    from ..parallel import grads as grads_mod
    from ..pipeline import engine_1f1b as e1

    if not cfg.scan_layers:
        raise ValueError("pipeline path requires scan_layers=True")
    use_dropout = getattr(cfg, "attention_dropout", 0.0) > 0.0
    C = num_chunks
    vocab_axis = (ps.PP_AXIS, ps.TP_AXIS) if vocab_pp else ps.TP_AXIS

    embed_mod = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        axis=vocab_axis,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    norm_mod = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                       sequence_parallel=cfg.sequence_parallel)
    # under vocab_pp the SP gather stays a tp collective (explicit in
    # head_loss_fn) while the kernel/collectives span (pp, tp)
    head_mod = pl.ColumnParallelLinear(
        features=cfg.vocab_size, use_bias=False, gather_output=False,
        sequence_parallel=cfg.sequence_parallel and not vocab_pp,
        axis=vocab_axis,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)

    def inner(params, ids, labels, dstep):
        p = params["params"]
        S = ps.get_pipeline_model_parallel_size()
        M = num_microbatches
        L = cfg.num_layers
        if C == 1:
            # uneven stage partition (reference cuts anywhere,
            # pipeline/partition.py:280): grad_fn zero-pads the scanned
            # stack to a multiple of S BEFORE entering this shard_map — an
            # all-zero decoder layer is an exact identity through the
            # residual (attention out-proj and MLP down-proj are zero), and
            # its grads are dropped by grad_fn's final slice so the pad
            # weights never move. Storage stays pp-sharded (GSPMD uneven
            # sharding, trainer._spec_tree): per-stage param/optimizer
            # bytes are ~1/S of dense even for odd layer counts.
            lv = -(-L // S)
            l_pad = lv * S
        else:
            if L % (S * C) != 0:
                raise ValueError(
                    f"num_layers {L} not divisible by stages*chunks "
                    f"{S * C} (uneven partition is supported for "
                    "num_chunks=1)")
            l_pad = L
            lv = L // (S * C)
        denom = jnp.maximum(
            jnp.sum(labels != ignore_index).astype(jnp.float32), 1.0)
        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        def embed_fn(ep, ids_):
            x = embed_mod.apply({"params": ep}, ids_)
            if cfg.sequence_parallel:
                x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
            return x

        body = nn.scan(
            _ScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=lv,
        )(cfg)

        if use_dropout:
            pp_bound = comm._axis_size(ps.PP_AXIS)

            def stage_fn(chunk_p, act, slot):
                # mask = f(seed, step, slot, stage): slot decorrelates
                # microbatches/chunks and repeats exactly in the engine's
                # bwd recompute; the pp index decorrelates stages (same
                # slot, same layer shapes — without it every stage would
                # reuse stage 0's masks)
                my = (jax.lax.axis_index(ps.PP_AXIS) if pp_bound
                      else jnp.zeros((), jnp.int32))
                key = jax.random.key(dropout_seed)
                key = jax.random.fold_in(key, dstep)
                key = jax.random.fold_in(key, slot)
                key = jax.random.fold_in(key, my)
                out, _ = body.apply({"params": chunk_p}, act, cos, sin,
                                    None, rngs={"dropout": key})
                return out
        else:
            def stage_fn(chunk_p, act):
                out, _ = body.apply({"params": chunk_p}, act, cos, sin,
                                    None)
                return out

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

        tied = cfg.tie_embeddings

        def head_loss_fn(hp, act, lb):
            h = norm_mod.apply({"params": hp["norm"]}, act)
            if vocab_pp and cfg.sequence_parallel:
                h = mappings.gather_from_sequence_parallel_region(
                    h, seq_dim=1, to_model_parallel=True)
            if tied:
                logits = pl.embedding_attend(
                    hp["table"], h, axis=vocab_axis,
                    sequence_parallel=cfg.sequence_parallel and not vocab_pp,
                    dtype=cfg.dtype)
            else:
                logits = head_mod.apply({"params": hp["lm_head"]}, h)
            per_tok = lf.parallel_cross_entropy(logits, lb, axis=vocab_axis,
                                                ignore_index=ignore_index)
            return jnp.sum(per_tok) / denom

        # the stack arrives as this stage's LOCAL [C*lv, ...] shard (grad_fn
        # padded it to l_pad outside; in_spec P('pp') splits the lead dim)
        layers_c = jax.tree_util.tree_map(
            lambda x: x.reshape((C, lv) + x.shape[1:]), p["model"]["layers"])
        head_p = {"norm": p["model"]["norm"]}
        if tied:
            head_p["table"] = p["model"]["embed"]["embedding"]
        else:
            head_p["lm_head"] = p["lm_head"]
        eng_params = {"embed": p["model"]["embed"], "layers": layers_c,
                      "head": head_p}
        ids_mb = eng.microbatch(ids, M)
        labels_mb = eng.microbatch(labels, M)
        m_run = M
        if C > 1 and M % S != 0:
            # lift the interleaved M % S constraint: pad microbatches whose
            # labels are all ignore_index — their CE loss, head grads and
            # stage cotangents are zero (denom counts real labels only)
            m_run = -(-M // S) * S
            ids_mb = jnp.concatenate(
                [ids_mb, jnp.zeros((m_run - M,) + ids_mb.shape[1:],
                                   ids_mb.dtype)])
            labels_mb = jnp.concatenate(
                [labels_mb, jnp.full((m_run - M,) + labels_mb.shape[1:],
                                     ignore_index, labels_mb.dtype)])

        loss, g = e1.pipeline_1f1b_grads(
            embed_fn, stage_fn, head_loss_fn, eng_params, ids_mb, labels_mb,
            num_stages=S, num_microbatches=m_run, num_chunks=C,
            num_real_microbatches=M, vocab_parallel_pp=vocab_pp,
            stage_takes_slot=use_dropout)

        # local [C*lv] grads exit through out_spec P('pp') as the padded
        # [l_pad] stack; grad_fn slices the pad rows off outside
        g_layers = jax.tree_util.tree_map(
            lambda x: x.reshape((C * lv,) + x.shape[2:]), g["layers"])
        g_embed = dict(g["embed"])
        if tied:
            g_embed["embedding"] = (g_embed["embedding"]
                                    + g["head"]["table"])
        g_model = {"embed": g_embed, "layers": g_layers,
                   "norm": g["head"]["norm"]}
        gp = {"model": g_model}
        if not tied:
            gp["lm_head"] = g["head"]["lm_head"]
        grads = {"params": gp}
        grads = grads_mod.allreduce_gradients(grads, specs=run_specs)
        return eng.data_parallel_mean(loss), grads

    run_specs = param_specs
    if vocab_pp:
        # the shard_map boundary reshards vocab params (pp, tp) on entry
        # and reassembles the per-shard grads on exit; outer placement
        # (trainer specs) is untouched
        import copy

        run_specs = copy.deepcopy(param_specs)
        mp = run_specs["params"]["model"]
        mp["embed"]["embedding"] = P((ps.PP_AXIS, ps.TP_AXIS), None)
        if not cfg.tie_embeddings:
            run_specs["params"]["lm_head"]["kernel"] = P(
                None, (ps.PP_AXIS, ps.TP_AXIS))

    def grad_fn(params, batch):
        mesh = ps.get_mesh()
        S = ps.get_pipeline_model_parallel_size()
        L = cfg.num_layers
        l_pad = -(-L // S) * S if C == 1 else L

        def map_layers(tree, f, *rest):
            new = jax.tree_util.tree_map(f, tree["params"]["model"]["layers"],
                                         *rest)
            out = dict(tree)
            out["params"] = dict(tree["params"])
            out["params"]["model"] = dict(tree["params"]["model"])
            out["params"]["model"]["layers"] = new
            return out

        # stacks arrive either padded-to-l_pad (pipeline storage from
        # initialize_parallel_model — pp-sharded, the memory-property
        # layout) or at the true length L (host/dense trees in tests and
        # conversions): pad the latter here, and return grads in whichever
        # layout the params came in
        stored_len = jax.tree_util.tree_leaves(
            params["params"]["model"]["layers"])[0].shape[0]
        padded_here = False
        if l_pad != stored_len:
            def pad(x, spec):
                x = jnp.concatenate(
                    [x, jnp.zeros((l_pad - L,) + x.shape[1:], x.dtype)])
                return jax.lax.with_sharding_constraint(
                    x, jax.NamedSharding(mesh, spec))
            params = map_layers(params, pad,
                                run_specs["params"]["model"]["layers"])
            padded_here = True
        # optional per-step dropout decorrelation: grad_fn's contract has
        # no rng channel, so a step counter may ride the batch
        dstep = jnp.asarray(batch.get("dropout_step", 0), jnp.int32)
        loss, grads = ps.shard_map(
            inner, mesh,
            in_specs=(run_specs, P(ps.DP_AXIS, None), P(ps.DP_AXIS, None),
                      P()),
            out_specs=(P(), run_specs))(
                params, batch["input_ids"], batch["labels"], dstep)
        if l_pad != L:
            if padded_here:
                grads = map_layers(grads, lambda x: x[:L])
            else:
                # padded storage: keep [l_pad] shapes for the optimizer but
                # pin pad-row grads to zero so the pad weights never move
                mask_shape = (l_pad,)
                row_ok = (jnp.arange(l_pad) < L)
                grads = map_layers(
                    grads, lambda x: x * row_ok.reshape(
                        mask_shape + (1,) * (x.ndim - 1)).astype(x.dtype))
        return loss, grads

    return grad_fn


def make_pipeline_eval_fn(cfg: LlamaConfig, num_microbatches: int,
                          param_specs: Any, ignore_index: int = -100):
    """Forward-only pipelined loss (reference ``NxDPPModel.run_eval``)."""
    pp_loss = pipelined_loss_fn(cfg, num_microbatches, ignore_index)

    def eval_fn(params, batch):
        mesh = ps.get_mesh()
        return ps.shard_map(
            pp_loss, mesh,
            in_specs=(param_specs, P(ps.DP_AXIS, None), P(ps.DP_AXIS, None)),
            out_specs=P())(params, batch["input_ids"], batch["labels"])

    return eval_fn
