"""Llama model family (flagship), TP/SP/DP/CP-parallel, TPU-native.

Parity target: the reference's llama training examples
(``examples/training/llama/tp_zero1_llama_hf_pretrain``,
``tp_pp_llama_hf_pretrain``) which wrap HF ``LlamaForCausalLM`` with the
reference's parallel layers (``modeling_llama_nxd.py``). Here the model is
built natively from our parallel layers:

* embedding: :class:`ParallelEmbedding` (vocab-sharded over tp)
* attention: :class:`GQAQKVColumnParallelLinear` + rotary + flash/sdpa +
  :class:`RowParallelLinear`
* MLP: fused gate+up :class:`ColumnParallelLinear` + :class:`RowParallelLinear`
* loss: vocab-parallel cross-entropy over the tp-sharded lm head

Layers are stacked with ``nn.scan`` (single compiled layer body — the XLA
analogue of the reference's per-layer graph reuse) and optionally
rematerialised (activation checkpointing, reference
``utils/activation_checkpoint.py:55``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.norms import RMSNorm
from ..ops import collective_matmul as cm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mappings
from ..parallel import mesh as ps

from ..lora import LoraConfig
from ..utils.remat import resolve_remat_policy, validate_remat_policy


def _lora_kw(cfg: "LlamaConfig", name: str) -> dict:
    """lora_rank/alpha kwargs for a target sublayer (reference LoraModel
    walks the model matching target_modules; here targets select at
    construction)."""
    if cfg.lora is not None and name in cfg.lora.target_modules:
        return {"lora_rank": cfg.lora.r, "lora_alpha": cfg.lora.alpha,
                "lora_dropout": cfg.lora.dropout}
    return {}


def _act_kw(cfg: "LlamaConfig") -> dict:
    """Activation-wire kwargs threaded into every TP linear."""
    return {"activation_comm_dtype": cfg.activation_comm_dtype,
            "activation_comm_block_size": cfg.activation_comm_block_size}


# serving weight-quantization tiers (docs/quantization.md): int8/fp8 are
# per-out-channel symmetric w8a16, mxfp4/mxfp8 packed OCP microscaling
WEIGHT_QUANT_FORMATS = ("int8", "fp8", "mxfp4", "mxfp8")


def _weight_quant_dtype(fmt: str):
    """QuantizedDtype for the int8/fp8 tiers."""
    from ..quantization.quantization_utils import QuantizedDtype

    return (QuantizedDtype.INT8 if fmt == "int8"
            else QuantizedDtype.FP8E4M3)


def _quant_lm_head(cfg: "LlamaConfig", gather_output: bool, name=None):
    """The quantized ColumnParallel lm_head for ``cfg.weight_quant``."""
    kw = {} if name is None else {"name": name}
    if cfg.weight_quant.startswith("mx"):
        from ..quantization.mx_layers import MXQuantizedColumnParallel

        return MXQuantizedColumnParallel(
            features=cfg.vocab_size, mx_format=cfg.weight_quant[2:],
            gather_output=gather_output, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, **kw)
    from ..quantization.quantization_layers import QuantizedColumnParallel

    return QuantizedColumnParallel(
        features=cfg.vocab_size,
        quantized_dtype=_weight_quant_dtype(cfg.weight_quant),
        gather_output=gather_output, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype, **kw)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: bool = False
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    remat: bool = False
    # what the rematerialised layer body saves across fwd→bwd:
    #   "nothing"        — recompute everything (max memory savings);
    #   "save_attention" — save flash outputs + log-sum-exp so the backward
    #     skips re-running the attention forward kernel (the single biggest
    #     recompute item, ~13% of step compute at bench shapes; the flash
    #     backward only ever needed out+lse — see
    #     ops/flash_attention.py::_flash_pallas_vjp_fwd).
    remat_policy: str = "nothing"
    scan_layers: bool = True
    use_flash_attention: bool = False
    # force the Pallas flash kernel (interpret mode on CPU) instead of the
    # backend/shape auto-dispatch — lets CI exercise the kernel path (incl.
    # its named remat residuals) on the virtual CPU mesh. None = auto.
    attn_force_pallas: Optional[bool] = None
    # decode: shard the KV cache's SLOT dim over the cp axis and LSE-combine
    # partial attention (ops.flash_decoding; reference KV-shared groups,
    # parallel_state.py:1473 + trace/spmd.py:74). Long-context serving:
    # cache memory and decode attention FLOPs split over the decode group.
    use_flash_decoding: bool = False
    # context-parallel attention: "ring" (ppermute KV rotation),
    # "ring_pallas" (ring with the flash kernel fused into each step), or
    # "ulysses" (all-to-all seq<->head resharding; needs heads % cp == 0)
    cp_attn_impl: str = "ring"
    # wire dtype for the CP ring's KV ppermute hops (serving CP prefill;
    # ops/ring_attention wire= codec): "fp32" ships full precision and is
    # BITWISE identical to the pre-codec ring (the fallback knob);
    # "int8"/"fp8" blockwise-quantize each hop through wire_codec at
    # ~3.94x/~3.9x wire reduction (EQuARX, PAPERS.md). Serving threads
    # EngineConfig.cp_wire_dtype here; the training ring ignores it (the
    # quantizer has zero gradient).
    cp_wire_dtype: str = "fp32"
    cp_wire_block_size: int = 256
    # attention-probability dropout (training path only; active iff a
    # "dropout" rng is supplied to apply()). In-kernel on the flash path
    # via counter-based masks (reference seed plumbing:
    # kernels/flash_attn.py:30,54). Under CP: ring uses global-coordinate
    # masks (bit-identical to the cp=1 model at the same TP degree),
    # Ulysses per-rank deterministic masks.
    attention_dropout: float = 0.0
    tp_size: Optional[int] = None
    # decomposed collective-matmuls in every TP linear (qkv/o_proj/gate_up/
    # down/lm_head — docs/tp_overlap.md): None = auto (tp axis >= 4 and
    # shapes tile), True = on where shapes allow, False = monolithic.
    # Threaded from ParallelConfig.tp_overlap_comm by configure_model().
    overlap_comm: Optional[bool] = None
    # Activation-collective compression (docs/comm_compression.md): wire
    # dtype for every TP activation collective in the stack — "fp32" off,
    # "int8"/"fp8" blockwise-quantize the payloads (decomposed rings and
    # monolithic fallbacks alike). Threaded from
    # ParallelConfig.tp_activation_comm_dtype by configure_model().
    activation_comm_dtype: str = "fp32"
    activation_comm_block_size: int = 256
    # Reduced-sync TP (PAPERS.md "Tensor-Parallelism with Partially
    # Synchronized Activations"): fraction of decoder layers whose
    # row-parallel exits run the full all-reduce; the rest keep per-rank
    # partial sums, compensated by a residual resync before every synced
    # layer (cm.tp_sync_schedule). < 1.0 requires scan_layers=False (the
    # schedule varies per layer) and sequence_parallel=False (the
    # reduce-scatter also reshapes, so it cannot be elided).
    activation_sync_fraction: float = 1.0
    # Serving weight-quantization tier (docs/quantization.md): storage
    # format for every TP linear in the stack — None (fp weights),
    # "int8"/"fp8" (per-out-channel symmetric, w8a16 dequant-into-matmul)
    # or "mxfp4"/"mxfp8" (packed OCP microscaling, 32-element E8M0
    # blocks). Threaded from EngineConfig.weight_quant /
    # ParallelConfig.weight_quant; convert float checkpoints with
    # quantization.serving.quantize_params_for_serving.
    weight_quant: Optional[str] = None
    # LoRA adapters (see neuronx_distributed_tpu.lora); None = disabled
    lora: Optional["LoraConfig"] = None
    # sequence-chunked LM loss (fused_linear_cross_entropy): the loss path
    # streams `chunk`-token slices through head-matmul + CE so [B, S, V]
    # logits never materialise. None = classic full-logits path.
    loss_chunk: Optional[int] = None

    def __post_init__(self) -> None:
        if self.cp_attn_impl not in ("ring", "ring_pallas", "ulysses"):
            raise ValueError(
                f"cp_attn_impl must be 'ring', 'ring_pallas' or "
                f"'ulysses', got {self.cp_attn_impl!r}")
        if self.cp_wire_dtype not in ("fp32", "int8", "fp8"):
            raise ValueError(
                f"cp_wire_dtype must be 'fp32', 'int8' or 'fp8', got "
                f"{self.cp_wire_dtype!r}")
        validate_remat_policy(self.remat_policy)
        # raises on unknown wire dtypes / bad block sizes
        cm.wire_config(self.activation_comm_dtype,
                       self.activation_comm_block_size)
        if not 0.0 < self.activation_sync_fraction <= 1.0:
            raise ValueError(
                f"activation_sync_fraction must be in (0, 1], got "
                f"{self.activation_sync_fraction}")
        if self.activation_sync_fraction < 1.0:
            if self.scan_layers:
                raise ValueError(
                    "activation_sync_fraction < 1.0 requires "
                    "scan_layers=False: the sync schedule varies per layer "
                    "and scanned layers share one compiled body")
            if self.sequence_parallel:
                raise ValueError(
                    "activation_sync_fraction < 1.0 is incompatible with "
                    "sequence_parallel: the reduce-scatter exit reshapes "
                    "the activation and cannot be elided")
        if self.weight_quant is not None:
            if self.weight_quant not in WEIGHT_QUANT_FORMATS:
                raise ValueError(
                    f"weight_quant must be one of {WEIGHT_QUANT_FORMATS} "
                    f"or None, got {self.weight_quant!r}")
            incompatible = (
                "LoRA (adapters assume float kernels)"
                if self.lora is not None else
                "tie_embeddings=True (the embedding table stays float)"
                if self.tie_embeddings else
                "loss_chunk (the fused loss streams a float lm_head kernel)"
                if self.loss_chunk is not None else
                "sequence_parallel (quantized linears enter via copy_to "
                "and exit via all-reduce only)"
                if self.sequence_parallel else
                "activation_sync_fraction < 1.0"
                if self.activation_sync_fraction < 1.0 else None)
            if incompatible:
                raise ValueError(
                    f"weight_quant={self.weight_quant!r} is incompatible "
                    f"with {incompatible}")
            if self.weight_quant.startswith("mx"):
                from ..quantization.microscaling import MX_BLOCK

                q_features = self.num_heads * self.head_dim_
                bad = ("hidden_size" if self.hidden_size % MX_BLOCK else
                       "intermediate_size"
                       if self.intermediate_size % MX_BLOCK else
                       "num_heads * head_dim"
                       if q_features % MX_BLOCK else None)
                if bad:
                    raise ValueError(
                        f"weight_quant={self.weight_quant!r} needs {bad} "
                        f"divisible by the MX block ({MX_BLOCK}): all "
                        "contraction dims are block-scaled")
        if self.loss_chunk is not None:
            if self.loss_chunk <= 0:
                raise ValueError(
                    f"loss_chunk must be positive, got {self.loss_chunk}")
            unsupported = ("tie_embeddings=True" if self.tie_embeddings
                           else "LoRA targeting 'lm_head'"
                           if (self.lora is not None
                               and "lm_head" in self.lora.target_modules)
                           else None)
            if unsupported:
                # silently falling back to full logits would let users
                # believe they have the memory savings when they don't
                raise ValueError(
                    f"loss_chunk is incompatible with {unsupported}: the "
                    "fused chunked loss streams through a dedicated lm_head "
                    "kernel param; unset loss_chunk for this configuration")

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads


# Canonical configs (reference fixtures:
# examples/training/llama/tp_zero1_llama_hf_pretrain/7B_config_llama2 etc.)
LLAMA2_7B = LlamaConfig(num_layers=32, hidden_size=4096,
                        intermediate_size=11008, num_heads=32, num_kv_heads=32)
LLAMA2_70B = LlamaConfig(num_layers=80, hidden_size=8192,
                         intermediate_size=28672, num_heads=64, num_kv_heads=8)
LLAMA3_8B = LlamaConfig(vocab_size=128256, num_layers=32, hidden_size=4096,
                        intermediate_size=14336, num_heads=32, num_kv_heads=8,
                        rope_theta=500000.0)


def tiny_config(**kw) -> LlamaConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)
    base.update(kw)
    return LlamaConfig(**base)


def _is_paged_cache_view(cache) -> bool:
    from ..inference.paging import PagedCacheView

    return isinstance(cache, PagedCacheView)


def _is_cp_prefill_view(cache) -> bool:
    from ..inference.paging import CPPrefillView

    return isinstance(cache, CPPrefillView)


def _cp_prefill_attend(cfg: LlamaConfig, q, k, v, positions, view):
    """Context-parallel ring prefill against the CP-sharded paged pool:
    scatter this rank's chunk of K/V rows into the LOCAL pool shard at
    the precomputed flat indices (rows another rank owns carry the drop
    sentinel), then attend the whole prompt with ring attention — the KV
    chunks rotate around the cp ring, quantized per
    ``cfg.cp_wire_dtype``. Called inside shard_map with the cp axis
    bound; the packed batch is this rank's ``[1, W_local]`` slice of the
    right-padded prompt, so ring's global arange coordinates equal the
    true token positions and causality is exact across ranks."""
    import math as _math

    from ..inference import paging
    from ..ops.ring_attention import ring_attention

    k_rows, v_rows = k[0], v[0]                      # [W_local, KV, D]
    new_k = paging.write_pool_rows(view.k, k_rows, view.write_idx)
    new_v = paging.write_pool_rows(view.v, v_rows, view.write_idx)
    n_rep = q.shape[2] // k.shape[2]
    kf = attn_mod.repeat_kv(k, n_rep)
    vf = attn_mod.repeat_kv(v, n_rep)
    out = ring_attention(q, kf, vf, causal=True,
                         scale=1.0 / _math.sqrt(q.shape[-1]),
                         wire_dtype=cfg.cp_wire_dtype,
                         wire_block_size=cfg.cp_wire_block_size)
    new_view = view.replace(k=new_k, v=new_v)
    return out.astype(cfg.dtype), new_view


def _paged_cache_attend(cfg: LlamaConfig, q, k, v, positions, view):
    """Attention against the paged block pool: (optionally quantize and)
    scatter this step's K/V rows into the layer's pool slice at the
    precomputed flat indices, then gather-attend through the per-token
    block tables (:mod:`..ops.paged_attention`). The packed batch is
    ``[1, T]``; rows with a dropped write index (pads, preempted slots)
    never land in the pool and their outputs are discarded by the caller.
    """
    import math as _math

    from ..inference import paging
    from ..inference.kv_cache import quantize_kv
    from ..ops.paged_attention import paged_attention
    from ..parallel import comm

    # inside a cp shard_map the pool's block dim is sharded over the cp
    # axis: each rank writes only the rows it owns (the engine's wrapper
    # localises the tables, non-resident rows carry the drop sentinel)
    # and attends its resident blocks; partials merge with the
    # flash-decoding combine (paged/flash-decoding hybrid)
    cp = comm._axis_size(ps.CP_AXIS)
    combine = ps.CP_AXIS if cp not in (None, 1) else None
    k_rows, v_rows = k[0], v[0]                      # [T, KV_local, D]
    if view.k_scale is not None:
        qk, ks = quantize_kv(k_rows)
        qv, vs = quantize_kv(v_rows)
        new_k = paging.write_pool_rows(view.k, qk, view.write_idx)
        new_v = paging.write_pool_rows(view.v, qv, view.write_idx)
        new_ks = paging.write_pool_rows(view.k_scale, ks, view.write_idx)
        new_vs = paging.write_pool_rows(view.v_scale, vs, view.write_idx)
    else:
        new_k = paging.write_pool_rows(view.k, k_rows, view.write_idx)
        new_v = paging.write_pool_rows(view.v, v_rows, view.write_idx)
        new_ks = new_vs = None
    out = paged_attention(
        q[0], new_k, new_v, view.pos, view.tables, positions[0],
        k_scale=new_ks, v_scale=new_vs,
        scale=1.0 / _math.sqrt(q.shape[-1]),
        force_pallas=cfg.attn_force_pallas,
        combine_axis=combine)[None]
    new_view = view.replace(k=new_k, v=new_v, k_scale=new_ks,
                            v_scale=new_vs)
    return out.astype(cfg.dtype), new_view


class LlamaAttention(nn.Module):
    """Attention with optional KV cache for autoregressive decode.

    Training path: ``__call__(x, cos, sin, positions)``.
    Decode path (reference: KV-cache state buffers in
    ``trace/nxd_model`` + ``examples/inference/modules``): pass
    ``cache=(k_cache, v_cache)`` of shape ``[B, S_max, KV, D]`` and
    ``cache_index`` (scalar write offset); returns ``(out, new_cache)``.
    """

    cfg: LlamaConfig
    # False elides o_proj's exit all-reduce (reduced-sync TP; scheduled per
    # layer by LlamaModel via cm.tp_sync_schedule)
    tp_sync: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: Optional[jax.Array] = None,
                 cache=None, cache_index=None):
        cfg = self.cfg
        head_dim = cfg.head_dim_
        if cfg.weight_quant is not None and cfg.weight_quant.startswith(
                "mx"):
            from ..quantization.mx_layers import MXGQAQKVColumnParallelLinear

            q, k, v = MXGQAQKVColumnParallelLinear(
                num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
                head_dim=head_dim, mx_format=cfg.weight_quant[2:],
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                tp_size=cfg.tp_size, name="qkv")(x)
        elif cfg.weight_quant is not None:
            from ..quantization.quantization_layers import \
                QuantizedGQAQKVColumnParallelLinear

            q, k, v = QuantizedGQAQKVColumnParallelLinear(
                num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
                head_dim=head_dim,
                quantized_dtype=_weight_quant_dtype(cfg.weight_quant),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                tp_size=cfg.tp_size, name="qkv")(x)
        else:
            q, k, v = pl.GQAQKVColumnParallelLinear(
                num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
                head_dim=head_dim, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                sequence_parallel=cfg.sequence_parallel,
                tp_size=cfg.tp_size,
                overlap_comm=cfg.overlap_comm, name="qkv",
                **_act_kw(cfg), **_lora_kw(cfg, "qkv"))(x)
        b, s = q.shape[0], q.shape[1]
        n_q_local = q.shape[-1] // head_dim
        n_kv_local = k.shape[-1] // head_dim
        q = q.reshape(b, s, n_q_local, head_dim)
        k = k.reshape(b, s, n_kv_local, head_dim)
        v = v.reshape(b, s, n_kv_local, head_dim)
        q = attn_mod.apply_rotary(q, cos, sin, positions)
        k = attn_mod.apply_rotary(k, cos, sin, positions)
        new_cache = None
        if cache is not None and _is_cp_prefill_view(cache):
            # CP ring prefill (inference/engine.py cp>1): write this
            # rank's rows into the local pool shard, ring-attend the
            # whole prompt across the cp axis
            out, new_cache = _cp_prefill_attend(cfg, q, k, v, positions,
                                                cache)
        elif cache is not None and _is_paged_cache_view(cache):
            # paged pool (inference/paging.py): write this step's rows at
            # the precomputed flat indices, gather-attend via block tables
            out, new_cache = _paged_cache_attend(cfg, q, k, v, positions,
                                                 cache)
        elif cache is not None:
            # cache = (k_cache, v_cache, slot_positions); slot_positions
            # [B, S_max] holds each slot's true token position (PAD_POSITION
            # sentinel for pads), updated once per step by the caller.
            k_cache, v_cache, slot_pos = cache
            if cfg.use_flash_decoding:
                # slot-sharded cache (flash decoding): masked write into
                # this rank's slot shard, partial attention + LSE combine
                # over the decode group (ops.flash_decoding)
                from ..inference.kv_cache import sharded_slot_update
                from ..ops.flash_decoding import flash_decode_attention

                k_cache = sharded_slot_update(
                    k_cache, k.astype(k_cache.dtype), cache_index,
                    ps.CP_AXIS)
                v_cache = sharded_slot_update(
                    v_cache, v.astype(v_cache.dtype), cache_index,
                    ps.CP_AXIS)
                new_cache = (k_cache, v_cache)
                out = flash_decode_attention(
                    q, k_cache.astype(cfg.dtype), v_cache.astype(cfg.dtype),
                    slot_pos, positions, axis=ps.CP_AXIS).astype(cfg.dtype)
            else:
                k_cache = jax.lax.dynamic_update_slice_in_dim(
                    k_cache, k.astype(k_cache.dtype), cache_index, axis=1)
                v_cache = jax.lax.dynamic_update_slice_in_dim(
                    v_cache, v.astype(v_cache.dtype), cache_index, axis=1)
                new_cache = (k_cache, v_cache)
                k_full = attn_mod.repeat_kv(k_cache.astype(cfg.dtype),
                                            n_q_local // n_kv_local)
                v_full = attn_mod.repeat_kv(v_cache.astype(cfg.dtype),
                                            n_q_local // n_kv_local)
                import math as _math

                scale = 1.0 / _math.sqrt(head_dim)
                scores = jnp.einsum(
                    "bqnd,bknd->bnqk", q.astype(jnp.float32),
                    k_full.astype(jnp.float32)) * scale
                # causal mask by stored positions: pads carry PAD_POSITION
                # and are never attended, so ragged batches need no extra
                # mask
                mask = positions[:, :, None] >= slot_pos[:, None, :]
                scores = jnp.where(mask[:, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                out = jnp.einsum("bnqk,bknd->bqnd", probs,
                                 v_full.astype(jnp.float32)
                                 ).astype(cfg.dtype)
        else:
            from ..parallel import comm

            # attention dropout: active iff the config rate > 0 AND the
            # caller supplied a "dropout" rng (training); eval calls without
            # the rng are deterministic with no flag-threading
            dropout_p, dropout_seed = attn_mod.attention_dropout_seed(
                self, cfg.attention_dropout)
            cp = comm._axis_size(ps.CP_AXIS)
            if cp is not None and cp > 1 and cfg.cp_attn_impl == "ulysses":
                # Ulysses moves the raw GQA kv heads through its
                # all-to-alls and expands after the reshard; dropout masks
                # there are per-rank-deterministic (see ulysses_attention)
                from ..ops.ulysses import ulysses_attention

                out = ulysses_attention(q, k, v, causal=True,
                                        dropout_p=dropout_p,
                                        dropout_seed=dropout_seed)
            elif cp is not None and cp > 1:
                # context parallel: KV rotates around the cp ring
                # (reference kernels/ring_attention_kernel.py); dropout
                # masks use GLOBAL seq coordinates, bit-identical to the
                # cp=1 model at the same TP degree ("ring"); "ring_pallas"
                # fuses the flash kernel into each ring step and draws
                # per-(rank, chunk) in-kernel masks instead
                from ..ops.ring_attention import (ring_attention,
                                                  ring_attention_pallas)

                k = attn_mod.repeat_kv(k, n_q_local // n_kv_local)
                v = attn_mod.repeat_kv(v, n_q_local // n_kv_local)
                if cfg.cp_attn_impl == "ring_pallas":
                    out = ring_attention_pallas(q, k, v,
                                                dropout_p=dropout_p,
                                                dropout_seed=dropout_seed)
                else:
                    out = ring_attention(q, k, v, causal=True,
                                         dropout_p=dropout_p,
                                         dropout_seed=dropout_seed)
            elif cfg.use_flash_attention:
                from ..ops.flash_attention import flash_attention

                k = attn_mod.repeat_kv(k, n_q_local // n_kv_local)
                v = attn_mod.repeat_kv(v, n_q_local // n_kv_local)
                out = flash_attention(q, k, v, causal=True,
                                      force_pallas=cfg.attn_force_pallas,
                                      dropout_p=dropout_p,
                                      dropout_seed=dropout_seed)
            else:
                k = attn_mod.repeat_kv(k, n_q_local // n_kv_local)
                v = attn_mod.repeat_kv(v, n_q_local // n_kv_local)
                out = attn_mod.sdpa_reference(q, k, v, causal=True,
                                              dropout_p=dropout_p,
                                              dropout_seed=dropout_seed)
        out = out.reshape(b, s, n_q_local * head_dim)
        if cfg.weight_quant is not None and cfg.weight_quant.startswith(
                "mx"):
            from ..quantization.mx_layers import MXQuantizedRowParallel

            out = MXQuantizedRowParallel(
                features=cfg.num_heads * head_dim,
                mx_format=cfg.weight_quant[2:], dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="o_proj")(out)
        elif cfg.weight_quant is not None:
            from ..quantization.quantization_layers import \
                QuantizedRowParallel

            out = QuantizedRowParallel(
                features=cfg.num_heads * head_dim,
                quantized_dtype=_weight_quant_dtype(cfg.weight_quant),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="o_proj")(out)
        else:
            out = pl.RowParallelLinear(
                features=cfg.num_heads * head_dim, use_bias=False,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                sequence_parallel=cfg.sequence_parallel,
                overlap_comm=cfg.overlap_comm, name="o_proj",
                tp_sync=self.tp_sync,
                **_act_kw(cfg), **_lora_kw(cfg, "o_proj"))(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaMLP(nn.Module):
    cfg: LlamaConfig
    # False elides down's exit all-reduce (reduced-sync TP)
    tp_sync: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        if cfg.weight_quant is not None:
            return self._quantized_call(x)
        # Fused gate+up in ONE column-parallel matmul (one MXU pass; the
        # reference keeps separate gate/up projections). The kernel is
        # [H, 2, I] with the tp shard on the *last* dim, so the gate/up split
        # (dim 1) is layout-identical under shard_map, GSPMD and dense.
        i_local = pl._maybe_local(cfg.intermediate_size, ps.TP_AXIS)
        kernel = self.param(
            "gate_up_kernel",
            nn.with_partitioning(pl.default_kernel_init,
                                 (None, None, ps.TP_AXIS)),
            (cfg.hidden_size, 2, i_local), cfg.param_dtype)
        lora_on = (cfg.lora is not None
                   and "gate_up" in cfg.lora.target_modules)
        lora_act = (lora_on and cfg.lora.dropout > 0.0
                    and self.has_rng("dropout"))
        if lora_on:
            lora_a = self.param(
                "lora_a", nn.with_partitioning(pl.default_kernel_init,
                                               (None, None)),
                (cfg.hidden_size, cfg.lora.r), cfg.param_dtype)
            lora_b = self.param(
                "lora_b", nn.with_partitioning(
                    nn.initializers.zeros_init(), (None, None, ps.TP_AXIS)),
                (cfg.lora.r, 2, i_local), cfg.param_dtype)
            if not lora_act:
                kernel = kernel + cfg.lora.scale * jnp.einsum(
                    "hr,rki->hki", lora_a, lora_b)
        # the fused [H, 2, I] kernel rides the decomposed collective-matmul
        # directly (last-dim contraction, gate/up split preserved);
        # activation-space LoRA needs the gathered input, so it falls back
        wire = cm.wire_config(cfg.activation_comm_dtype,
                              cfg.activation_comm_block_size)
        engaged = not lora_act and cm.overlap_engaged(
            cfg.overlap_comm, ps.TP_AXIS, x.shape, 1,
            needs_divisible=not cfg.sequence_parallel)
        if engaged or (wire is not None and not lora_act
                       and pl._bound_size(ps.TP_AXIS) is not None):
            impl = "decomposed" if engaged else "monolithic"
            x = x.astype(cfg.dtype)
            if cfg.sequence_parallel:
                h = cm.all_gather_matmul(x, kernel.astype(cfg.dtype),
                                         ps.TP_AXIS, 1, impl=impl,
                                         wire=wire)
            else:
                h = cm.copy_matmul(x, kernel.astype(cfg.dtype),
                                   ps.TP_AXIS, 1, impl=impl, wire=wire)
            h = nn.silu(h[..., 0, :]) * h[..., 1, :]
            return pl.RowParallelLinear(
                features=cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                sequence_parallel=cfg.sequence_parallel,
                overlap_comm=cfg.overlap_comm, name="down",
                tp_sync=self.tp_sync,
                **_act_kw(cfg), **_lora_kw(cfg, "down"))(h)
        if cfg.sequence_parallel:
            x = mappings.gather_from_sequence_parallel_region(
                x, seq_dim=1, to_model_parallel=True)
        else:
            x = mappings.copy_to_tensor_parallel_region(x)
        x = x.astype(cfg.dtype)
        h = jnp.einsum("bsh,hki->bski", x, kernel.astype(cfg.dtype))
        if lora_act:
            # dropout on the adapter input cannot fold into the kernel
            x_l = nn.Dropout(rate=cfg.lora.dropout)(x, deterministic=False)
            h = h + cfg.lora.scale * jnp.einsum(
                "bsr,rki->bski", jnp.dot(x_l, lora_a.astype(cfg.dtype)),
                lora_b.astype(cfg.dtype))
        if pl._bound_size(ps.TP_AXIS) is None:
            h = ps.with_sharding_constraint(h, None, None, None, ps.TP_AXIS)
        h = nn.silu(h[..., 0, :]) * h[..., 1, :]
        return pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel,
            overlap_comm=cfg.overlap_comm, name="down",
            tp_sync=self.tp_sync,
            **_act_kw(cfg), **_lora_kw(cfg, "down"))(h)

    def _quantized_call(self, x: jax.Array) -> jax.Array:
        """Weight-quantized (w8a16) gate_up + down: the fused [H, 2, I]
        kernel is stored quantized and dequantized into the einsum; no
        collective-matmul overlap (the packed kernel cannot ride the
        decomposed ring)."""
        cfg = self.cfg
        i_local = pl._maybe_local(cfg.intermediate_size, ps.TP_AXIS)
        x = mappings.copy_to_tensor_parallel_region(x)
        x = x.astype(cfg.dtype)
        if cfg.weight_quant.startswith("mx"):
            from ..quantization.microscaling import MX_BLOCK
            from ..quantization.mx_layers import (MXQuantizedRowParallel,
                                                  _mx_dequant, _mx_storage)

            fmt = cfg.weight_quant[2:]
            pack, store_dt = _mx_storage(fmt)
            packed = self.param(
                "gate_up_packed",
                nn.with_partitioning(lambda key, s, d: jnp.zeros(s, d),
                                     (None, ps.TP_AXIS, None)),
                (2, i_local, cfg.hidden_size // pack), store_dt)
            scale = self.param(
                "gate_up_scale",
                nn.with_partitioning(nn.initializers.ones_init(),
                                     (None, ps.TP_AXIS, None)),
                (2, i_local, cfg.hidden_size // MX_BLOCK), jnp.float32)
            w = _mx_dequant(packed, scale, fmt, cfg.dtype)   # [2, I, H]
            h = jnp.einsum("bsh,kih->bski", x, w)
            down = MXQuantizedRowParallel(
                features=cfg.hidden_size, mx_format=fmt, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="down")
        else:
            from ..quantization.quantization_layers import \
                QuantizedRowParallel
            from ..quantization.quantization_utils import dequantize

            qdt = _weight_quant_dtype(cfg.weight_quant)
            gate_up_q = self.param(
                "gate_up_q",
                nn.with_partitioning(lambda key, s, d: jnp.zeros(s, d),
                                     (None, None, ps.TP_AXIS)),
                (cfg.hidden_size, 2, i_local), qdt.jnp_dtype)
            gate_up_scale = self.param(
                "gate_up_scale",
                nn.with_partitioning(nn.initializers.ones_init(),
                                     (None, ps.TP_AXIS)),
                (2, i_local), jnp.float32)
            w = dequantize(gate_up_q, gate_up_scale[None], cfg.dtype)
            h = jnp.einsum("bsh,hki->bski", x, w)
            down = QuantizedRowParallel(
                features=cfg.hidden_size, quantized_dtype=qdt,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="down")
        if pl._bound_size(ps.TP_AXIS) is None:
            h = ps.with_sharding_constraint(h, None, None, None, ps.TP_AXIS)
        h = nn.silu(h[..., 0, :]) * h[..., 1, :]
        return down(h)


class LlamaDecoderLayer(nn.Module):
    cfg: LlamaConfig
    # False elides this layer's row-parallel exit all-reduces (o_proj and
    # down); LlamaModel's non-scan loop schedules it per layer
    tp_sync: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: Optional[jax.Array] = None,
                 cache=None, cache_index=None):
        cfg = self.cfg
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="input_norm")(x)
        attn_out = LlamaAttention(cfg, tp_sync=self.tp_sync, name="attn")(
            h, cos, sin, positions, cache=cache, cache_index=cache_index)
        new_cache = None
        if cache is not None:
            attn_out, new_cache = attn_out
        x = x + attn_out
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="post_norm")(x)
        x = x + LlamaMLP(cfg, tp_sync=self.tp_sync, name="mlp")(h)
        if cache is not None:
            return x, new_cache
        return x


def context_parallel_positions(input_ids: jax.Array,
                               positions: Optional[jax.Array]):
    """Global rope positions when the sequence is sliced over cp: this
    shard's tokens start at ``cp_rank * s_local`` (reference:
    ``utils/batch_utils.py:19`` slices the batch; the ring kernel gets global
    offsets). No-op when positions are given or cp is absent/1."""
    if positions is not None:
        return positions
    from ..parallel import comm

    cp = comm._axis_size(ps.CP_AXIS)
    if cp is None or cp <= 1:
        return None
    b, s_local = input_ids.shape
    start = jax.lax.axis_index(ps.CP_AXIS) * s_local
    return jnp.broadcast_to(start + jnp.arange(s_local), (b, s_local))




class _ScanBody(nn.Module):
    """nn.scan body: carries the hidden states, emits nothing."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions):
        x = LlamaDecoderLayer(self.cfg, name="layer")(x, cos, sin, positions)
        return x, None


class _DecodeScanBody(nn.Module):
    """nn.scan body for cached decode: carries hidden states, maps each
    layer's cache slice (leading layer dim) through, emits the new cache."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cache_kv, slot_pos, cos, sin, positions,
                 cache_index):
        if len(cache_kv) == 4:
            # quantized cache: dequant fuses into the attention read; only
            # this step's freshly written slots are (re)quantized, so
            # resident slots never accumulate requantization drift
            from ..inference.kv_cache import dequantize_kv, quantize_kv

            qk, qv, ks, vs = cache_kv
            k_l = dequantize_kv(qk, ks, self.cfg.dtype)
            v_l = dequantize_kv(qv, vs, self.cfg.dtype)
        else:
            k_l, v_l = cache_kv
        x, (nk, nv) = LlamaDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions, cache=(k_l, v_l, slot_pos),
            cache_index=cache_index)
        if len(cache_kv) == 4:
            s_step = x.shape[1]
            nk_step = jax.lax.dynamic_slice_in_dim(nk, cache_index, s_step,
                                                   axis=1)
            nv_step = jax.lax.dynamic_slice_in_dim(nv, cache_index, s_step,
                                                   axis=1)
            qk_s, ks_s = quantize_kv(nk_step)
            qv_s, vs_s = quantize_kv(nv_step)
            return x, (
                jax.lax.dynamic_update_slice_in_dim(qk, qk_s, cache_index,
                                                    axis=1),
                jax.lax.dynamic_update_slice_in_dim(qv, qv_s, cache_index,
                                                    axis=1),
                jax.lax.dynamic_update_slice_in_dim(ks, ks_s, cache_index,
                                                    axis=1),
                jax.lax.dynamic_update_slice_in_dim(vs, vs_s, cache_index,
                                                    axis=1))
        return x, (nk, nv)


class _PagedScanBody(nn.Module):
    """nn.scan body for paged decode: carries hidden states, maps each
    layer's pool slice (leading layer dim) through, broadcasts the step's
    routing arrays (pool positions, per-token block tables, flat write
    indices). Parameter layout is identical to :class:`_DecodeScanBody`
    (same ``layer`` scope), so the same checkpoint serves both cache
    protocols."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cache_kv, pool_pos, tables, write_idx, cos, sin,
                 positions):
        from ..inference.paging import PagedCacheView

        if len(cache_kv) == 4:
            k_l, v_l, ks_l, vs_l = cache_kv
        else:
            (k_l, v_l), ks_l, vs_l = cache_kv, None, None
        view = PagedCacheView(k=k_l, v=v_l, k_scale=ks_l, v_scale=vs_l,
                              pos=pool_pos, tables=tables,
                              write_idx=write_idx)
        x, new_view = LlamaDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions, cache=view, cache_index=None)
        if len(cache_kv) == 4:
            return x, (new_view.k, new_view.v, new_view.k_scale,
                       new_view.v_scale)
        return x, (new_view.k, new_view.v)


class _CPPrefillScanBody(nn.Module):
    """nn.scan body for context-parallel ring prefill: carries hidden
    states, maps each layer's LOCAL pool shard (leading layer dim)
    through, broadcasts the rank's write routing. Parameter layout is
    identical to :class:`_PagedScanBody` (same ``layer`` scope), so the
    same checkpoint serves the ring-prefill and paged-decode workers."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cache_kv, pool_pos, write_idx, cos, sin,
                 positions):
        from ..inference.paging import CPPrefillView

        k_l, v_l = cache_kv
        view = CPPrefillView(k=k_l, v=v_l, pos=pool_pos,
                             write_idx=write_idx)
        x, new_view = LlamaDecoderLayer(self.cfg, name="layer")(
            x, cos, sin, positions, cache=view, cache_index=None)
        return x, (new_view.k, new_view.v)


class LlamaModel(nn.Module):
    """Transformer body: embedding + decoder stack + final norm."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        x = pl.ParallelEmbedding(
            num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed",
            **_lora_kw(cfg, "embed"))(input_ids)
        positions = context_parallel_positions(input_ids, positions)
        if cfg.sequence_parallel:
            x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        if cfg.scan_layers:
            body_cls = _ScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=resolve_remat_policy(cfg.remat_policy))
            scanned = nn.scan(
                body_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            x, _ = scanned(x, cos, sin, positions)
        else:
            layer_cls = LlamaDecoderLayer
            if cfg.remat:
                layer_cls = nn.remat(
                    layer_cls, prevent_cse=False,
                    policy=resolve_remat_policy(cfg.remat_policy))
            sched = cm.tp_sync_schedule(cfg.num_layers,
                                        cfg.activation_sync_fraction)
            # only engage when there is a real bound tp axis: at size 1 (or
            # under GSPMD) the elided all-reduce is already a no-op, and the
            # resync arithmetic x_ref + psum(x - x_ref) is not a bitwise
            # identity, so stay on the plain path
            n_tp = pl._bound_size(ps.TP_AXIS)
            reduced = (cfg.activation_sync_fraction < 1.0
                       and n_tp is not None and n_tp > 1)
            # Reduced-sync resync: x_ref tracks the last fully-synchronized
            # hidden state. Unsynced layers leave each rank holding
            # x_ref + its own share of the elided all-reduce outputs, so a
            # single psum of the accumulated deviation (x - x_ref) before
            # the next synced layer recovers the full activation — one
            # collective amortized over 1/sync_fraction layers.
            x_ref = x
            pending = False
            for i in range(cfg.num_layers):
                if reduced and pending and sched[i]:
                    x = x_ref + mappings.reduce_from_tensor_parallel_region(
                        x - x_ref)
                    pending = False
                x = layer_cls(cfg, tp_sync=sched[i] if reduced else True,
                              name=f"layer_{i}")(x, cos, sin, positions)
                if reduced:
                    if sched[i]:
                        x_ref = x
                    else:
                        pending = True
        x = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel, name="norm")(x)
        # NOTE: when sequence_parallel, the returned hidden states are still
        # sequence-sharded; the LM head (a column-parallel linear with
        # sequence_parallel=True) performs the final gather itself, so the
        # gather's backward reduce-scatter correctly pairs with the head's
        # partial input-grads. Gathering here AND entering the head through
        # copy_to would double-reduce gradients (inflate by tp).
        return x


class _LMHeadKernel(nn.Module):
    """LM-head kernel param only — name/shape/partitioning identical to the
    ``ColumnParallelLinear(name='lm_head')`` the full-logits path creates,
    so checkpoints interchange between the fused and unfused loss paths."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self) -> jax.Array:
        cfg = self.cfg
        out_local = pl._maybe_local(cfg.vocab_size, ps.TP_AXIS)
        return self.param(
            "kernel",
            pl._partitioned(pl.default_kernel_init, (None, ps.TP_AXIS)),
            (cfg.hidden_size, out_local), cfg.param_dtype)


class LlamaForCausalLM(nn.Module):
    """Body + tp-sharded LM head; ``loss()`` uses vocab-parallel CE so the
    full-vocab logits never materialise unsharded — and, with
    ``cfg.loss_chunk`` set, streams sequence chunks through the head matmul
    so even the vocab-*local* logits never materialise at full length
    (:func:`..parallel.loss_functions.fused_linear_cross_entropy`)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 positions: Optional[jax.Array] = None,
                 labels: Optional[jax.Array] = None,
                 ignore_index: int = -100) -> jax.Array:
        cfg = self.cfg
        model = LlamaModel(cfg, name="model")
        x = model(input_ids, positions)
        if cfg.tie_embeddings:
            if _lora_kw(cfg, "lm_head"):
                raise ValueError(
                    "LoRA on 'lm_head' is incompatible with "
                    "tie_embeddings=True (there is no lm_head param); "
                    "target 'embed' instead")
            # tied word embeddings (reference register_shared_weights,
            # pipeline/model.py:750): no lm_head param; logits re-use the
            # vocab-sharded embedding table. Gradients flow through both
            # uses of the one param.
            from flax.core import meta

            table = meta.unbox(
                model.variables["params"]["embed"]["embedding"])
            logits = pl.embedding_attend(
                table, x, sequence_parallel=cfg.sequence_parallel,
                dtype=cfg.dtype)
            if labels is not None:
                return lf.causal_lm_loss(logits, labels,
                                         ignore_index=ignore_index)
            return logits
        if (labels is not None and cfg.loss_chunk
                and not _lora_kw(cfg, "lm_head")):
            # fused chunked head+CE: enter the TP region exactly where
            # ColumnParallelLinear would, then stream chunks
            if cfg.sequence_parallel:
                x = mappings.gather_from_sequence_parallel_region(
                    x, seq_dim=1, to_model_parallel=True)
            else:
                x = mappings.copy_to_tensor_parallel_region(x)
            kernel = _LMHeadKernel(cfg, name="lm_head")()
            return lf.fused_linear_cross_entropy(
                x.astype(cfg.dtype), kernel, labels,
                ignore_index=ignore_index, chunk=cfg.loss_chunk,
                dtype=cfg.dtype)
        if cfg.weight_quant is not None:
            logits = _quant_lm_head(cfg, False, name="lm_head")(x)
        else:
            logits = pl.ColumnParallelLinear(
                features=cfg.vocab_size, use_bias=False,
                gather_output=False,
                sequence_parallel=cfg.sequence_parallel,
                overlap_comm=cfg.overlap_comm,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="lm_head",
                **_act_kw(cfg), **_lora_kw(cfg, "lm_head"))(x)
        if labels is not None:
            return lf.causal_lm_loss(logits, labels,
                                     ignore_index=ignore_index)
        return logits

    def loss(self, input_ids: jax.Array, labels: jax.Array,
             ignore_index: int = -100) -> jax.Array:
        return self(input_ids, labels=labels, ignore_index=ignore_index)


def llama_forward_with_cache(cfg: LlamaConfig, params, input_ids: jax.Array,
                             positions: jax.Array, kv_cache,
                             return_hidden: bool = False, slot_ids=None,
                             cp_prefill: bool = False):
    """KV-cached forward for prefill ("context_encoding") and decode
    ("token_generation") — the two compiled graphs of the reference's
    serving path (``trace/model_builder.py:495`` keys).

    ``params``: LlamaForCausalLM variables (scan_layers=True layout).
    ``kv_cache``: :class:`..inference.kv_cache.KVCache` or
    :class:`..inference.kv_cache.QuantizedKVCache` (int8 cache; reference
    kv_cache_quant, ``quantization_config.py:72``). Writes this step's K/V
    at ``kv_cache.index`` and returns ``(logits, new_cache)``.

    Paged protocol: pass a :class:`..inference.paging.PagedKVCache` /
    ``QuantizedPagedKVCache`` plus ``slot_ids [T]`` mapping each packed
    token (``input_ids [1, T]``) to its cache slot; K/V land in the slot's
    block-table blocks instead of at a contiguous write index. Contiguous
    callers are untouched.

    ``cp_prefill=True`` (paged caches only, inside shard_map with the cp
    axis bound): attention per layer is ring attention over the cp axis
    instead of the block-table gather — ``input_ids``/``positions``/
    ``slot_ids`` are this rank's ``[1, W_local]`` slice of the
    right-padded prompt, ``kv_cache`` the LOCAL pool shard with
    rank-local block tables, and each rank scatters only the K/V rows it
    computes. One pass prefills the whole prompt with compute split
    ``1/cp`` per rank (the CP prefill tier's TTFT lever).
    """
    from ..inference.kv_cache import KVCache, QuantizedKVCache
    from ..inference.paging import PagedKVCache, QuantizedPagedKVCache

    if not cfg.scan_layers:
        raise ValueError("cached decode requires scan_layers=True")
    paged = isinstance(kv_cache, (PagedKVCache, QuantizedPagedKVCache))
    if paged:
        if slot_ids is None:
            raise ValueError("paged cache forward requires slot_ids [T]")
        if input_ids.shape[0] != 1:
            raise ValueError(
                "paged decode packs requests into one row batch [1, T]; "
                f"got batch {input_ids.shape[0]}")
    p = params["params"]
    b, s = input_ids.shape
    positions = jnp.asarray(positions, jnp.int32)

    embed = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        **_lora_kw(cfg, "embed"))
    x = embed.apply({"params": p["model"]["embed"]}, input_ids)
    cos, sin = attn_mod.precompute_rope(
        cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
        use_scaled=cfg.rope_scaling)
    # rope lookup needs in-table indices; sentinel pads clamp to the last
    # entry (their K values are garbage but masked out)
    rope_pos = jnp.minimum(positions, cfg.max_seq_len - 1)

    if paged:
        from ..inference import paging as _paging

        slot_ids = jnp.asarray(slot_ids, jnp.int32)
        # per-token routing: each packed token carries its slot's block
        # table row and a flat pool index for this step's K/V write (==
        # capacity for pad rows -> dropped by the mode="drop" scatters)
        tok_tables = kv_cache.block_tables[
            jnp.clip(slot_ids, 0, kv_cache.max_slots - 1)]
        write_idx = _paging.flat_write_indices(
            tok_tables, positions[0], kv_cache.block_size,
            kv_cache.capacity)
        slot_pos = _paging.write_pool_positions(kv_cache.pos, positions[0],
                                                write_idx)
        quantized = isinstance(kv_cache, QuantizedPagedKVCache)
        cache_kv = ((kv_cache.k, kv_cache.v, kv_cache.k_scale,
                     kv_cache.v_scale) if quantized
                    else (kv_cache.k, kv_cache.v))
        if cp_prefill:
            if quantized:
                raise ValueError(
                    "cp_prefill does not support quantized paged caches")
            scanned = nn.scan(
                _CPPrefillScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast, nn.broadcast),
                out_axes=0,
                length=cfg.num_layers,
            )(cfg)
            x, new_kv = scanned.apply(
                {"params": p["model"]["layers"]}, x, cache_kv, slot_pos,
                write_idx, cos, sin, rope_pos)
        else:
            scanned = nn.scan(
                _PagedScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast, nn.broadcast, nn.broadcast),
                out_axes=0,
                length=cfg.num_layers,
            )(cfg)
            x, new_kv = scanned.apply(
                {"params": p["model"]["layers"]}, x, cache_kv, slot_pos,
                tok_tables, write_idx, cos, sin, rope_pos)
    else:
        # record this step's true positions in the slot-position table
        # (pads carry the PAD_POSITION sentinel and are thereby never
        # attended); shared by all layers, updated once here
        if cfg.use_flash_decoding:
            from ..inference.kv_cache import sharded_slot_update

            slot_pos = sharded_slot_update(kv_cache.pos, positions,
                                           kv_cache.index, ps.CP_AXIS,
                                           slot_dim=1)
        else:
            slot_pos = jax.lax.dynamic_update_slice_in_dim(
                kv_cache.pos, positions, kv_cache.index, axis=1)

        scanned = nn.scan(
            _DecodeScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                     nn.broadcast, nn.broadcast),
            out_axes=0,
            length=cfg.num_layers,
        )(cfg)
        quantized = isinstance(kv_cache, QuantizedKVCache)
        cache_kv = ((kv_cache.k, kv_cache.v, kv_cache.k_scale,
                     kv_cache.v_scale) if quantized
                    else (kv_cache.k, kv_cache.v))
        x, new_kv = scanned.apply(
            {"params": p["model"]["layers"]}, x, cache_kv,
            slot_pos, cos, sin, rope_pos, kv_cache.index)

    norm = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype)
    x = norm.apply({"params": p["model"]["norm"]}, x)
    if cfg.tie_embeddings:
        logits = pl.embedding_attend(
            p["model"]["embed"]["embedding"], x, dtype=cfg.dtype,
            gather_output=True)
    elif cfg.weight_quant is not None:
        head = _quant_lm_head(cfg, True)
        logits = head.apply({"params": p["lm_head"]}, x)
    else:
        head = pl.ColumnParallelLinear(
            features=cfg.vocab_size, use_bias=False, gather_output=True,
            overlap_comm=cfg.overlap_comm,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            **_act_kw(cfg), **_lora_kw(cfg, "lm_head"))
        logits = head.apply({"params": p["lm_head"]}, x)
    if paged:
        if quantized:
            new_k, new_v, nks, nvs = new_kv
            new_cache = kv_cache.replace(k=new_k, v=new_v, k_scale=nks,
                                         v_scale=nvs, pos=slot_pos)
        else:
            new_k, new_v = new_kv
            new_cache = kv_cache.replace(k=new_k, v=new_v, pos=slot_pos)
    elif quantized:
        new_k, new_v, nks, nvs = new_kv
        new_cache = QuantizedKVCache(
            k=new_k, v=new_v, k_scale=nks, v_scale=nvs, pos=slot_pos,
            index=kv_cache.index + s)
    else:
        new_k, new_v = new_kv
        new_cache = KVCache(k=new_k, v=new_v, pos=slot_pos,
                            index=kv_cache.index + s)
    if return_hidden:
        # post-norm hidden states — the medusa heads' input
        return logits, new_cache, x
    return logits, new_cache
