"""Llama model family (flagship), TP/SP/DP/CP-parallel, TPU-native.

Parity target: the reference's llama training examples
(``examples/training/llama/tp_zero1_llama_hf_pretrain``,
``tp_pp_llama_hf_pretrain``) which wrap HF ``LlamaForCausalLM`` with the
reference's parallel layers (``modeling_llama_nxd.py``). Here the model is
built natively from our parallel layers:

* embedding: :class:`ParallelEmbedding` (vocab-sharded over tp)
* attention: :class:`GQAQKVColumnParallelLinear` + rotary + flash/sdpa +
  :class:`RowParallelLinear`
* MLP: fused gate+up :class:`ColumnParallelLinear` + :class:`RowParallelLinear`
* loss: vocab-parallel cross-entropy over the tp-sharded lm head

Layers are stacked with ``nn.scan`` (single compiled layer body — the XLA
analogue of the reference's per-layer graph reuse) and optionally
rematerialised (activation checkpointing, reference
``utils/activation_checkpoint.py:55``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.norms import RMSNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mappings
from ..parallel import mesh as ps


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: bool = False
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    sequence_parallel: bool = False
    remat: bool = False
    scan_layers: bool = True
    use_flash_attention: bool = False
    tp_size: Optional[int] = None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads


# Canonical configs (reference fixtures:
# examples/training/llama/tp_zero1_llama_hf_pretrain/7B_config_llama2 etc.)
LLAMA2_7B = LlamaConfig(num_layers=32, hidden_size=4096,
                        intermediate_size=11008, num_heads=32, num_kv_heads=32)
LLAMA2_70B = LlamaConfig(num_layers=80, hidden_size=8192,
                         intermediate_size=28672, num_heads=64, num_kv_heads=8)
LLAMA3_8B = LlamaConfig(vocab_size=128256, num_layers=32, hidden_size=4096,
                        intermediate_size=14336, num_heads=32, num_kv_heads=8,
                        rope_theta=500000.0)


def tiny_config(**kw) -> LlamaConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)
    base.update(kw)
    return LlamaConfig(**base)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        head_dim = cfg.head_dim_
        q, k, v = pl.GQAQKVColumnParallelLinear(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
            head_dim=head_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, tp_size=cfg.tp_size,
            name="qkv")(x)
        b, s = q.shape[0], q.shape[1]
        n_q_local = q.shape[-1] // head_dim
        n_kv_local = k.shape[-1] // head_dim
        q = q.reshape(b, s, n_q_local, head_dim)
        k = k.reshape(b, s, n_kv_local, head_dim)
        v = v.reshape(b, s, n_kv_local, head_dim)
        q = attn_mod.apply_rotary(q, cos, sin, positions)
        k = attn_mod.apply_rotary(k, cos, sin, positions)
        k = attn_mod.repeat_kv(k, n_q_local // n_kv_local)
        v = attn_mod.repeat_kv(v, n_q_local // n_kv_local)
        from ..parallel import comm

        cp = comm._axis_size(ps.CP_AXIS)
        if cp is not None and cp > 1:
            # context parallel: sequence sliced over cp; ring attention
            # rotates KV around the cp ring (reference:
            # kernels/ring_attention_kernel.py)
            from ..ops.ring_attention import ring_attention

            out = ring_attention(q, k, v, causal=True)
        elif cfg.use_flash_attention:
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        else:
            out = attn_mod.sdpa_reference(q, k, v, causal=True)
        out = out.reshape(b, s, n_q_local * head_dim)
        out = pl.RowParallelLinear(
            features=cfg.num_heads * head_dim, use_bias=False,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, name="o_proj")(out)
        return out


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        # Fused gate+up in ONE column-parallel matmul (one MXU pass; the
        # reference keeps separate gate/up projections). The kernel is
        # [H, 2, I] with the tp shard on the *last* dim, so the gate/up split
        # (dim 1) is layout-identical under shard_map, GSPMD and dense.
        i_local = pl._maybe_local(cfg.intermediate_size, ps.TP_AXIS)
        kernel = self.param(
            "gate_up_kernel",
            nn.with_partitioning(pl.default_kernel_init,
                                 (None, None, ps.TP_AXIS)),
            (cfg.hidden_size, 2, i_local), cfg.param_dtype)
        if cfg.sequence_parallel:
            x = mappings.gather_from_sequence_parallel_region(
                x, seq_dim=1, to_model_parallel=True)
        else:
            x = mappings.copy_to_tensor_parallel_region(x)
        x = x.astype(cfg.dtype)
        h = jnp.einsum("bsh,hki->bski", x, kernel.astype(cfg.dtype))
        if pl._bound_size(ps.TP_AXIS) is None:
            h = ps.with_sharding_constraint(h, None, None, None, ps.TP_AXIS)
        h = nn.silu(h[..., 0, :]) * h[..., 1, :]
        return pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            sequence_parallel=cfg.sequence_parallel, name="down")(h)


class LlamaDecoderLayer(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="input_norm")(x)
        x = x + LlamaAttention(cfg, name="attn")(h, cos, sin, positions)
        h = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel,
                    name="post_norm")(x)
        x = x + LlamaMLP(cfg, name="mlp")(h)
        return x


def context_parallel_positions(input_ids: jax.Array,
                               positions: Optional[jax.Array]):
    """Global rope positions when the sequence is sliced over cp: this
    shard's tokens start at ``cp_rank * s_local`` (reference:
    ``utils/batch_utils.py:19`` slices the batch; the ring kernel gets global
    offsets). No-op when positions are given or cp is absent/1."""
    if positions is not None:
        return positions
    from ..parallel import comm

    cp = comm._axis_size(ps.CP_AXIS)
    if cp is None or cp <= 1:
        return None
    b, s_local = input_ids.shape
    start = jax.lax.axis_index(ps.CP_AXIS) * s_local
    return jnp.broadcast_to(start + jnp.arange(s_local), (b, s_local))


class _ScanBody(nn.Module):
    """nn.scan body: carries the hidden states, emits nothing."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions):
        x = LlamaDecoderLayer(self.cfg, name="layer")(x, cos, sin, positions)
        return x, None


class LlamaModel(nn.Module):
    """Transformer body: embedding + decoder stack + final norm."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        x = pl.ParallelEmbedding(
            num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed")(
                input_ids)
        positions = context_parallel_positions(input_ids, positions)
        if cfg.sequence_parallel:
            x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        if cfg.scan_layers:
            body_cls = _ScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            scanned = nn.scan(
                body_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            x, _ = scanned(x, cos, sin, positions)
        else:
            layer_cls = LlamaDecoderLayer
            if cfg.remat:
                layer_cls = nn.remat(
                    layer_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            for i in range(cfg.num_layers):
                x = layer_cls(cfg, name=f"layer_{i}")(x, cos, sin, positions)
        x = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    sequence_parallel=cfg.sequence_parallel, name="norm")(x)
        # NOTE: when sequence_parallel, the returned hidden states are still
        # sequence-sharded; the LM head (a column-parallel linear with
        # sequence_parallel=True) performs the final gather itself, so the
        # gather's backward reduce-scatter correctly pairs with the head's
        # partial input-grads. Gathering here AND entering the head through
        # copy_to would double-reduce gradients (inflate by tp).
        return x


class LlamaForCausalLM(nn.Module):
    """Body + tp-sharded LM head; ``loss()`` uses vocab-parallel CE so the
    full-vocab logits never materialise unsharded."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        x = LlamaModel(cfg, name="model")(input_ids, positions)
        logits = pl.ColumnParallelLinear(
            features=cfg.vocab_size, use_bias=False, gather_output=False,
            sequence_parallel=cfg.sequence_parallel,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="lm_head")(x)
        return logits

    def loss(self, input_ids: jax.Array, labels: jax.Array,
             ignore_index: int = -100) -> jax.Array:
        logits = self(input_ids)
        per_tok = lf.parallel_cross_entropy(logits, labels,
                                            ignore_index=ignore_index)
        denom = jnp.maximum(jnp.sum(labels != ignore_index), 1)
        return jnp.sum(per_tok) / denom
