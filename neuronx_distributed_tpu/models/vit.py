"""ViT model family (image encoder + classification head).

Parity target: the reference's ViT inference example
(``examples/inference/vit/neuron_modeling_vit.py`` — NeuronViTEmbeddings /
NeuronViTLayer / NeuronViTEncoder wrapping HF ``ViTForImageClassification``).
TPU-first design notes:

* patch embedding is patch-extraction (a reshape/transpose, free under XLA)
  followed by a single dense projection — the exact math of the reference's
  stride-``p`` Conv2d (``neuron_modeling_vit.py:148``) but expressed as one
  MXU matmul over ``[B*N, C*p*p] @ [C*p*p, H]`` instead of a convolution;
* pre-LN transformer blocks on the shared parallel layers (TP column/row
  pairs, bidirectional sdpa/flash attention — same kernels as BERT);
* static shapes only: ``interpolate_pos_encoding`` is resolved at trace
  time from the configured image size (dynamic interpolation would break
  XLA's one-trace compilation model; resize offline instead).

HF weight layout maps via ``scripts.checkpoint_converter.convert_hf_vit_to_nxd``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..modules import attention as attn_mod
from ..modules.norms import LayerNorm
from ..parallel import layers as pl


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_labels: int = 1000
    layernorm_eps: float = 1e-12
    # dropout (active iff a "dropout" rng is supplied to apply(), matching
    # the BERT/llama convention); attention dropout shares the
    # counter-based mask hash with the flash kernels
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    use_flash_attention: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    tp_size: Optional[int] = None

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def __post_init__(self) -> None:
        if self.image_size % self.patch_size != 0:
            raise ValueError(
                f"image_size {self.image_size} must be divisible by "
                f"patch_size {self.patch_size}")


# ViT-Base/Large/Huge are the reference example's three documented targets
# (run_vit.py:7-17)
VIT_BASE = ViTConfig()
VIT_LARGE = ViTConfig(hidden_size=1024, intermediate_size=4096,
                      num_layers=24, num_heads=16)
VIT_HUGE = ViTConfig(hidden_size=1280, intermediate_size=5120,
                     num_layers=32, num_heads=16, patch_size=14)


def tiny_vit_config(**kw) -> ViTConfig:
    base = dict(image_size=16, patch_size=8, hidden_size=64,
                intermediate_size=128, num_layers=2, num_heads=4,
                num_labels=8)
    base.update(kw)
    return ViTConfig(**base)


def patchify(pixel_values: jax.Array, patch: int) -> jax.Array:
    """``[B, C, H, W]`` (HF channel-first convention) → ``[B, N, C*p*p]``
    patch vectors, element order (c, i, j) matching a flattened HF Conv2d
    kernel ``[hidden, C, p, p]``."""
    b, c, h, w = pixel_values.shape
    x = pixel_values.reshape(b, c, h // patch, patch, w // patch, patch)
    x = x.transpose(0, 2, 4, 1, 3, 5)  # [B, Hp, Wp, C, p, p]
    return x.reshape(b, (h // patch) * (w // patch), c * patch * patch)


class ViTLayer(nn.Module):
    """Pre-LN block: ``x + attn(LN(x))`` then ``x + mlp(LN(x))``
    (reference ``NeuronViTLayer.forward``, ``neuron_modeling_vit.py:274``)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        train = self.has_rng("dropout")
        hd = cfg.hidden_size // cfg.num_heads
        h = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                      name="ln_before")(x)
        q, k, v = pl.GQAQKVColumnParallelLinear(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_heads,
            head_dim=hd, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, tp_size=cfg.tp_size,
            name="qkv")(h)
        b, s = q.shape[0], q.shape[1]
        n_local = q.shape[-1] // hd
        q = q.reshape(b, s, n_local, hd)
        k = k.reshape(b, s, n_local, hd)
        v = v.reshape(b, s, n_local, hd)
        dropout_p, dropout_seed = attn_mod.attention_dropout_seed(
            self, cfg.attention_dropout)
        if cfg.use_flash_attention:
            from ..ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=False,
                                   dropout_p=dropout_p,
                                   dropout_seed=dropout_seed)
        else:
            attn = attn_mod.sdpa_reference(q, k, v, causal=False,
                                           dropout_p=dropout_p,
                                           dropout_seed=dropout_seed)
        attn = attn.reshape(b, s, n_local * hd)
        attn = pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj")(attn)
        hidden_drop = nn.Dropout(rate=cfg.hidden_dropout)
        x = x + hidden_drop(attn, deterministic=not train)
        h = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype,
                      name="ln_after")(x)
        h = pl.ColumnParallelLinear(
            features=cfg.intermediate_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="up")(h)
        h = nn.gelu(h, approximate=False)  # HF uses erf gelu
        h = pl.RowParallelLinear(
            features=cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="down")(h)
        return x + hidden_drop(h, deterministic=not train)


class _ViTScanBody(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        return ViTLayer(self.cfg, name="layer")(x), None


class ViTForImageClassification(nn.Module):
    """Patch embed + CLS token + pre-LN encoder + classifier on the CLS
    position (HF ``ViTForImageClassification``; the reference serves this
    via its IMAGE_ENC runner, ``run_vit.py:35``). ``method="encode"``
    exposes the raw image-encoder states for feature-extraction serving."""

    cfg: ViTConfig

    def setup(self) -> None:
        cfg = self.cfg
        self.patch_proj = pl.ColumnParallelLinear(
            features=cfg.hidden_size, use_bias=True, gather_output=True,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.cls_token = self.param(
            "cls_token",
            nn.with_partitioning(nn.initializers.zeros_init(),
                                 (None, None, None)),
            (1, 1, cfg.hidden_size), cfg.param_dtype)
        self.position_embedding = self.param(
            "position_embedding",
            nn.with_partitioning(pl.default_embed_init, (None, None)),
            (cfg.num_patches + 1, cfg.hidden_size), cfg.param_dtype)
        self.embed_drop = nn.Dropout(rate=cfg.hidden_dropout)
        if cfg.scan_layers:
            body_cls = _ViTScanBody
            if cfg.remat:
                body_cls = nn.remat(
                    body_cls, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            self.layers = nn.scan(
                body_cls, variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"})(cfg)
        else:
            self.layer_stack = [ViTLayer(cfg) for _ in range(cfg.num_layers)]
        self.final_norm = LayerNorm(eps=cfg.layernorm_eps, dtype=cfg.dtype)
        self.classifier = pl.ColumnParallelLinear(
            features=cfg.num_labels, use_bias=True, gather_output=True,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)

    def encode(self, pixel_values):
        """``[B, C, H, W]`` → final hidden states ``[B, N+1, hidden]``."""
        cfg = self.cfg
        train = self.has_rng("dropout")
        patches = patchify(pixel_values.astype(cfg.dtype), cfg.patch_size)
        x = self.patch_proj(patches)
        x = jnp.concatenate(
            [jnp.broadcast_to(self.cls_token.astype(cfg.dtype),
                              (x.shape[0], 1, cfg.hidden_size)), x], axis=1)
        x = x + self.position_embedding[None].astype(cfg.dtype)
        x = self.embed_drop(x, deterministic=not train)
        if cfg.scan_layers:
            x, _ = self.layers(x)
        else:
            for layer in self.layer_stack:
                x = layer(x)
        return self.final_norm(x)

    def __call__(self, pixel_values):
        x = self.encode(pixel_values)
        return self.classifier(x[:, 0]).astype(jnp.float32)

    def loss(self, pixel_values, labels):
        """Mean softmax cross-entropy over ``[B]`` integer labels."""
        logits = self(pixel_values)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, labels[:, None], axis=-1))
