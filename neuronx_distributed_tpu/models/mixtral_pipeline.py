"""Pipeline-parallel Mixtral (MoE) training path.

MoE × PP composition (the reference's mixtral example runs under
``NxDPPModel`` the same way its llama one does): the MoE decoder stack is
partitioned over the ``pp`` mesh axis exactly like
:mod:`.llama_pipeline`, with the router auxiliary losses accumulated
per-stage inside the scanned GPipe engine (``pipeline_spmd(with_aux=True)``)
and psum'd over pp into the loss — the analogue of the reference
broadcasting/averaging user outputs across the pipeline
(``pipeline/model.py`` loss reduction).

Params are byte-compatible with :class:`.mixtral.MixtralForCausalLM`
(``scan_layers=True``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..modules import attention as attn_mod
from ..modules.norms import RMSNorm
from ..parallel import layers as pl
from ..parallel import loss_functions as lf
from ..parallel import mappings
from ..parallel import mesh as ps
from ..pipeline import spmd_engine as eng
from .llama_pipeline import PIPELINE_LOGICAL_RULES  # noqa: F401 (re-export)
from .mixtral import MixtralConfig, _MoEScanBody


def pipelined_moe_loss_fn(cfg: MixtralConfig, num_microbatches: int,
                          ignore_index: int = -100):
    """Build ``pp_loss(params, ids, labels) -> scalar`` (GPipe engine) for
    the MoE decoder; includes the router aux losses."""
    if not cfg.scan_layers:
        raise ValueError("pipeline path requires scan_layers=True")
    if getattr(cfg, "attention_dropout", 0.0) > 0.0:
        # the MoE pipeline paths carry no per-microbatch rng channel yet
        # (the llama 1F1B executor does — llama_pipeline.make_1f1b_grad_fn
        # slot-keys the masks); a silent skip would fake regularization
        raise ValueError(
            "attention_dropout is not threaded through the MoE pipeline "
            "engines; set attention_dropout=0 for MoE PP configs")

    embed_mod = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    norm_mod = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                       sequence_parallel=cfg.sequence_parallel)
    head_mod = pl.ColumnParallelLinear(
        features=cfg.vocab_size, use_bias=False, gather_output=False,
        sequence_parallel=cfg.sequence_parallel,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)

    def pp_loss(params, ids, labels):
        p = params["params"]
        S = ps.get_pipeline_model_parallel_size()
        M = num_microbatches
        if cfg.num_layers % S != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by pp {S}")
        l_local = cfg.num_layers // S

        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        embed_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                         p["model"]["embed"])
        ids_mb = eng.microbatch(ids, M)

        def input_fn(ids_):
            x = embed_mod.apply({"params": embed_p}, ids_)
            if cfg.sequence_parallel:
                # stage activations ride the ring SP-sharded; the MoE
                # block's own gather/scatter (MixtralDecoderLayer) handles
                # the regather inside each stage (reference
                # moe/model.py:154 delayed reduce-scatter inside NxDPPModel)
                x = mappings.scatter_to_sequence_parallel_region(x,
                                                                 seq_dim=1)
            return x

        body = nn.scan(
            _MoEScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=l_local,
        )(cfg)

        def stage_fn(act):
            out, aux = body.apply({"params": p["model"]["layers"]}, act,
                                  cos, sin, None)
            # aux: [l_local, 2] per-layer (load_balance, z) — sum layers
            return out, jnp.sum(aux, axis=0)

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

        outs, aux_local = eng.pipeline_spmd(stage_fn, ids_mb, S, M,
                                            with_aux=True,
                                            input_fn=input_fn)
        # global router aux: sum over stages with the fwd-psum/bwd-identity
        # mapping (raw psum would transpose to psum and hand every stage
        # S copies of the cotangent), then mean over microbatches
        aux_total = mappings.reduce_from_tensor_parallel_region(
            aux_local, ps.PP_AXIS) / M

        norm_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                        p["model"]["norm"])
        head_p = jax.tree_util.tree_map(eng.stage_replicated_param,
                                        p["lm_head"])
        labels_mb = eng.microbatch(labels, M)

        def mb_loss(carry, om):
            o, lb = om
            h = norm_mod.apply({"params": norm_p}, o)
            logits = head_mod.apply({"params": head_p}, h)
            per_tok = lf.parallel_cross_entropy(logits, lb,
                                                ignore_index=ignore_index)
            n_valid = jnp.sum((lb != ignore_index).astype(jnp.float32))
            return (carry[0] + jnp.sum(per_tok), carry[1] + n_valid), None

        (loss_sum, denom), _ = jax.lax.scan(
            mb_loss,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (outs, labels_mb))
        ce = eng.last_stage_value(loss_sum / jnp.maximum(denom, 1.0))
        loss = (ce + cfg.router_aux_coef * aux_total[0]
                + cfg.router_z_coef * aux_total[1])
        return eng.data_parallel_mean(loss)

    return pp_loss


def make_moe_pipeline_grad_fn(cfg: MixtralConfig, num_microbatches: int,
                              param_specs: Any, ignore_index: int = -100):
    """``grad_fn(params, batch) -> (loss, grads)`` for
    :func:`..trainer.make_train_step` (GPipe schedule; cf.
    :func:`.llama_pipeline.make_pipeline_grad_fn`)."""
    from ..parallel import grads as grads_mod

    pp_loss = pipelined_moe_loss_fn(cfg, num_microbatches, ignore_index)

    def inner(params, ids, labels):
        loss, g = jax.value_and_grad(pp_loss)(params, ids, labels)
        g = grads_mod.allreduce_gradients(g, specs=param_specs)
        return loss, g

    def grad_fn(params, batch):
        mesh = ps.get_mesh()
        return ps.shard_map(
            inner, mesh,
            in_specs=(param_specs, P(ps.DP_AXIS, None), P(ps.DP_AXIS, None)),
            out_specs=(P(), param_specs))(
                params, batch["input_ids"], batch["labels"])

    return grad_fn


def make_moe_1f1b_grad_fn(cfg: MixtralConfig, num_microbatches: int,
                          param_specs: Any, num_chunks: int = 1,
                          ignore_index: int = -100):
    """Explicit 1F1B / interleaved executor for the MoE decoder
    (:mod:`..pipeline.engine_1f1b` with ``aux_weight`` seeding the router
    aux cotangents) — the memory profile DBRX-scale MoE needs under pp.

    For ``num_chunks > 1`` the layer-stack params must already be in
    *interleaved* order — convert with
    :func:`.llama_pipeline.interleave_pipeline_params` (generic over the
    scanned ``model/layers`` subtree); a canonical-order tree would
    silently train a layer-permuted model.

    NOTE: mirrors :func:`.llama_pipeline.make_1f1b_grad_fn` (which adds
    sequence-parallel + tied embeddings but no aux); keep the scaffolding
    of the two in sync."""
    from ..parallel import grads as grads_mod
    from ..pipeline import engine_1f1b as e1

    if not cfg.scan_layers:
        raise ValueError("pipeline path requires scan_layers=True")
    if getattr(cfg, "attention_dropout", 0.0) > 0.0:
        # the MoE 1F1B path does not pass the engine's slot through its
        # stage_fn yet; adopt llama_pipeline.make_1f1b_grad_fn's slot-keyed
        # rng (stage_takes_slot=True) before lifting this guard — a silent
        # skip would fake regularization
        raise ValueError(
            "attention_dropout is not threaded through the MoE pipeline "
            "engines; set attention_dropout=0 for MoE PP configs")
    C = num_chunks

    embed_mod = pl.ParallelEmbedding(
        num_embeddings=cfg.vocab_size, features=cfg.hidden_size,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)
    norm_mod = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                       sequence_parallel=cfg.sequence_parallel)
    head_mod = pl.ColumnParallelLinear(
        features=cfg.vocab_size, use_bias=False, gather_output=False,
        sequence_parallel=cfg.sequence_parallel,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype)

    def inner(params, ids, labels):
        p = params["params"]
        S = ps.get_pipeline_model_parallel_size()
        M = num_microbatches
        if cfg.num_layers % (S * C) != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"stages*chunks {S * C}")
        lv = cfg.num_layers // (S * C)
        denom = jnp.maximum(
            jnp.sum(labels != ignore_index).astype(jnp.float32), 1.0)
        cos, sin = attn_mod.precompute_rope(
            cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta,
            use_scaled=cfg.rope_scaling)

        def embed_fn(ep, ids_):
            x = embed_mod.apply({"params": ep}, ids_)
            if cfg.sequence_parallel:
                x = mappings.scatter_to_sequence_parallel_region(x, seq_dim=1)
            return x

        body = nn.scan(
            _MoEScanBody,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=lv,
        )(cfg)

        def stage_fn(chunk_p, act):
            out, aux = body.apply({"params": chunk_p}, act, cos, sin, None)
            return out, jnp.sum(aux, axis=0).astype(jnp.float32)

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

        def head_loss_fn(hp, act, lb):
            h = norm_mod.apply({"params": hp["norm"]}, act)
            logits = head_mod.apply({"params": hp["lm_head"]}, h)
            per_tok = lf.parallel_cross_entropy(logits, lb,
                                                ignore_index=ignore_index)
            return jnp.sum(per_tok) / denom

        layers_c = jax.tree_util.tree_map(
            lambda x: x.reshape((C, lv) + x.shape[1:]), p["model"]["layers"])
        eng_params = {"embed": p["model"]["embed"], "layers": layers_c,
                      "head": {"norm": p["model"]["norm"],
                               "lm_head": p["lm_head"]}}
        ids_mb = eng.microbatch(ids, M)
        labels_mb = eng.microbatch(labels, M)
        m_run = M
        if C > 1 and M % S != 0:
            # pad microbatches with all-ignore labels (cf. llama_pipeline);
            # their router aux is masked via num_real_microbatches
            m_run = -(-M // S) * S
            ids_mb = jnp.concatenate(
                [ids_mb, jnp.zeros((m_run - M,) + ids_mb.shape[1:],
                                   ids_mb.dtype)])
            labels_mb = jnp.concatenate(
                [labels_mb, jnp.full((m_run - M,) + labels_mb.shape[1:],
                                     ignore_index, labels_mb.dtype)])
        aux_weight = jnp.asarray(
            [cfg.router_aux_coef, cfg.router_z_coef], jnp.float32) / M

        loss, g = e1.pipeline_1f1b_grads(
            embed_fn, stage_fn, head_loss_fn, eng_params, ids_mb, labels_mb,
            num_stages=S, num_microbatches=m_run, num_chunks=C,
            aux_weight=aux_weight, num_real_microbatches=M)

        g_layers = jax.tree_util.tree_map(
            lambda x: x.reshape((C * lv,) + x.shape[2:]), g["layers"])
        grads = {"params": {
            "model": {"embed": g["embed"], "layers": g_layers,
                      "norm": g["head"]["norm"]},
            "lm_head": g["head"]["lm_head"]}}
        grads = grads_mod.allreduce_gradients(grads, specs=param_specs)
        return eng.data_parallel_mean(loss), grads

    def grad_fn(params, batch):
        mesh = ps.get_mesh()
        return ps.shard_map(
            inner, mesh,
            in_specs=(param_specs, P(ps.DP_AXIS, None), P(ps.DP_AXIS, None)),
            out_specs=(P(), param_specs))(
                params, batch["input_ids"], batch["labels"])

    return grad_fn
