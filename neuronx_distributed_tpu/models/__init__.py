"""Reference model families (reference: ``examples/training``/``inference``)."""

from . import llama
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel

__all__ = ["llama", "LlamaConfig", "LlamaForCausalLM", "LlamaModel"]
