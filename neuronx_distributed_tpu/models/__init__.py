"""Reference model families (reference: ``examples/training``/``inference``)."""

from . import bert
from . import gpt_neox
from . import llama
from . import llama_pipeline
from . import mixtral
from . import vit
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel
from .mixtral import MixtralConfig, MixtralForCausalLM

__all__ = ["bert", "gpt_neox", "llama", "llama_pipeline", "mixtral", "vit", "LlamaConfig",
           "LlamaForCausalLM", "LlamaModel", "MixtralConfig",
           "MixtralForCausalLM"]
