"""LoRA adapters.

Analogue of the reference's ``modules/lora/`` (``LoraConfig`` config.py:6,
``LoraModel`` model.py:74, TP-aware ``LoraParallelLinear`` /
``LoraGQAQKVParallelLinear`` tp_layer.py:15,62, adapter-only checkpointing).

TPU-native mapping: the adapters live *inside* the parallel layers
(``lora_rank`` field — A/B sharded consistently with the base kernel, the
LoRA partial sums riding the layer's existing collectives), and the
"model wrapping" of the reference becomes pytree utilities:

* :func:`lora_mask` — boolean pytree marking adapter params (for
  ``optax.masked`` base-freezing, the analogue of requires_grad=False);
* :func:`make_lora_optimizer` — optimizer that updates only adapters;
* :func:`extract_lora_state` / :func:`merge_lora_state` — adapter-only
  checkpoints (reference ``save_lora_base=False`` path);
* :func:`merge_lora_params` — fold ``scale * A @ B`` into the base kernels
  for adapter-free serving (reference merge option).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

LORA_KEYS = ("lora_a", "lora_b", "q_lora_a", "q_lora_b", "k_lora_a",
             "k_lora_b", "v_lora_a", "v_lora_b")

# (kernel key, A key, B key) triples that merge_lora_params folds together
_MERGE_TRIPLES = (
    ("kernel", "lora_a", "lora_b"),
    ("embedding", "lora_a", "lora_b"),
    ("q_kernel", "q_lora_a", "q_lora_b"),
    ("k_kernel", "k_lora_a", "k_lora_b"),
    ("v_kernel", "v_lora_a", "v_lora_b"),
)


@dataclass(frozen=True)
class LoraConfig:
    """Reference: ``modules/lora/config.py:6``."""

    r: int = 8
    alpha: float = 16.0
    dropout: float = 0.0
    # which sublayers get adapters (matched against llama module names)
    target_modules: Tuple[str, ...] = ("qkv", "o_proj")
    save_lora_base: bool = False

    @property
    def scale(self) -> float:
        return self.alpha / self.r


def is_lora_path(path) -> bool:
    keys = {getattr(p, "key", getattr(p, "name", None)) for p in path}
    return bool(keys & set(LORA_KEYS))


def lora_mask(params: Any) -> Any:
    """Boolean pytree: True for adapter leaves."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: is_lora_path(path), params)


def make_lora_optimizer(tx: optax.GradientTransformation,
                        params: Any) -> optax.GradientTransformation:
    """Update only adapter params; base weights are frozen (reference: the
    LoraModel marks base params non-trainable)."""
    mask = lora_mask(params)
    label = jax.tree_util.tree_map(
        lambda m: "lora" if m else "frozen", mask)
    return optax.multi_transform(
        {"lora": tx, "frozen": optax.set_to_zero()}, label)


def extract_lora_state(params: Any) -> Any:
    """Adapter-only sub-pytree (for adapter checkpoints)."""
    def prune(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in LORA_KEYS:
                    out[k] = v
                elif isinstance(v, dict):
                    sub = prune(v)
                    if sub:
                        out[k] = sub
            return out
        return {}

    return prune(params)


def merge_lora_state(params: Any, lora_state: Any) -> Any:
    """Insert adapter leaves back into a base param tree."""
    def merge(base, lo):
        if not isinstance(lo, dict):
            return base
        out = dict(base)
        for k, v in lo.items():
            if isinstance(v, dict):
                out[k] = merge(base.get(k, {}), v)
            else:
                out[k] = v
        return out

    return merge(params, lora_state)


def merge_lora_params(params: Any, cfg: LoraConfig) -> Any:
    """Fold adapters into base kernels and drop them (reference merge-and-
    unload). Handles 2-D kernels, the embedding table, fused GQA kernels and
    the llama fused ``gate_up_kernel`` ([H, 2, I]: B is [r, 2, I])."""
    scale = cfg.scale

    def ab(a, b):
        # a: [h, r] or [L, h, r] (stacked scan layers); b matches with a
        # possibly >2-D output tail (fused gate_up [r, 2, I]). Conv pairs:
        # a [kh, kw, cin, r] with a 1x1 b [1, 1, r, cout] compose into one
        # conv kernel (B is pointwise, so the composition is exact).
        if a.ndim == 4 and b.ndim == 4:
            return jnp.einsum("hwir,ro->hwio", a, b[0, 0])
        if a.ndim == 2:
            return jnp.einsum("hr,r...->h...", a, b)
        return jnp.einsum("lhr,lr...->lh...", a, b)

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {k: walk(v) for k, v in node.items()
               if k not in LORA_KEYS}
        for kern, a_key, b_key in _MERGE_TRIPLES + (
                ("gate_up_kernel", "lora_a", "lora_b"),):
            if kern in node and a_key in node and b_key in node:
                out[kern] = node[kern] + scale * ab(node[a_key], node[b_key])
        return out

    return walk(params)
