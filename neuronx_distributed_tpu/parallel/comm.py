"""Low-level collective wrappers over named mesh axes.

Analogue of the reference's ``parallel_layers/comm.py`` (xm.all_reduce /
all_gather / reduce_scatter with replica-group lists, ``comm.py:124-220``).
On TPU the replica-group plumbing disappears: collectives are expressed over
*named mesh axes* inside ``shard_map`` and XLA lowers them to ICI/DCN
collectives. Every wrapper is a no-op when the axis has size 1, and raises a
clear error when called outside a context binding the axis (the reference's
CPU/gloo fallback is unnecessary — the same code runs on a virtual CPU mesh).

.. warning:: These wrappers are for *non-differentiated* code (or code whose
   VJP you define yourself). On a differentiated path under
   ``shard_map(check_vma=False)``, a raw ``psum`` transposes to another psum
   and inflates gradients by the axis size — use the ``custom_vjp`` pairs in
   :mod:`.mappings` instead (that is exactly the role of the reference's
   autograd Functions in ``mappings.py``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import mesh as ps


def _axis_size(axis) -> Optional[int]:
    """Size of a bound axis (or product over a TUPLE of axes, counting only
    the bound ones), or None if nothing is bound (GSPMD path).

    Uses the module-validated private accessor from :mod:`.mesh` — API drift
    raises at import, never a silent 'unbound' (see mesh.py)."""
    if isinstance(axis, (tuple, list)):
        sizes = [s for s in (_axis_size(a) for a in axis) if s is not None]
        if not sizes:
            return None
        out = 1
        for s in sizes:
            out *= s
        return out
    env = ps._get_axis_env()
    if env.axis_exists(axis):
        return int(env.axis_size(axis))
    return None


def _bound_names(axis) -> Tuple[str, ...]:
    """The subset of ``axis`` (a name or tuple of names) currently bound,
    preserving order (major-to-minor for combined-rank math)."""
    names = axis if isinstance(axis, (tuple, list)) else (axis,)
    env = ps._get_axis_env()
    return tuple(a for a in names if env.axis_exists(a))


def combined_axis_index(axis):
    """Flat rank over a (possibly multi-) axis, major-to-minor — the rank a
    dim sharded with ``PartitionSpec((a1, a2))`` sees for its shard offset.
    Zero when nothing is bound."""
    names = _bound_names(axis)
    if not names:
        return jnp.zeros((), jnp.int32)
    idx = lax.axis_index(names[0])
    for a in names[1:]:
        env = ps._get_axis_env()
        idx = idx * int(env.axis_size(a)) + lax.axis_index(a)
    return idx


def all_reduce(x: jax.Array, axis=ps.TP_AXIS) -> jax.Array:
    names = _bound_names(axis)
    n = _axis_size(axis)
    if not names or n is None or n == 1:
        return x
    return lax.psum(x, names if len(names) > 1 else names[0])


def all_gather(x: jax.Array, axis: str = ps.TP_AXIS, dim: int = -1) -> jax.Array:
    n = _axis_size(axis)
    if n is None or n == 1:
        return x
    dim = dim % x.ndim
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def reduce_scatter(x: jax.Array, axis: str = ps.TP_AXIS, dim: int = -1) -> jax.Array:
    n = _axis_size(axis)
    if n is None or n == 1:
        return x
    dim = dim % x.ndim
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


def all_to_all(x: jax.Array, axis: str, split_dim: int, concat_dim: int) -> jax.Array:
    n = _axis_size(axis)
    if n is None or n == 1:
        return x
    return lax.all_to_all(x, axis, split_axis=split_dim % x.ndim,
                          concat_axis=concat_dim % x.ndim, tiled=True)


def ppermute(x: jax.Array, axis: str, perm: Sequence[Tuple[int, int]]) -> jax.Array:
    n = _axis_size(axis)
    if n is None or n == 1:
        return x
    return lax.ppermute(x, axis, perm)


def split_along_dim(x: jax.Array, axis: str = ps.TP_AXIS, dim: int = -1) -> jax.Array:
    """Keep this shard's chunk of ``x`` along ``dim`` (the reference's
    ``split_tensor_along_last_dim`` + own-rank select, ``mappings.py:214``).
    Under shard_map a "replicated" value is the full array on every shard, so
    scatter == slice at ``axis_index``."""
    n = _axis_size(axis)
    if n is None or n == 1:
        return x
    dim = dim % x.ndim
    if x.shape[dim] % n != 0:
        raise ValueError(
            f"dim {dim} size {x.shape[dim]} not divisible by axis "
            f"{axis!r} size {n}")
    chunk = x.shape[dim] // n
    idx = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)
