"""Vocab-parallel loss functions.

Analogue of the reference's ``parallel_layers/loss_functions.py``
(``_ParallelCrossEntropy:10``, ``parallel_cross_entropy:217``,
``DistributedLogprob:152``): cross-entropy over logits whose vocab dim is
sharded across the tp axis, computed without ever materialising the full
logits — local max → pmax, masked local label logit → psum, local exp-sum →
psum. The backward (softmax − one-hot) falls out of JAX autodiff over the
same collectives, so no hand-written VJP is needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import comm, mappings
from . import mesh as ps

# NOTE: every reduction on a differentiated path below goes through
# ``mappings.reduce_from_tensor_parallel_region`` (custom_vjp: fwd psum, bwd
# identity). A raw ``lax.psum`` under ``shard_map(check_vma=False)`` would
# transpose to another psum and inflate gradients by the axis size.


def _rank_or_zero(axis):
    """Flat shard rank over ``axis`` (a name or tuple, e.g. the vocab-over-
    pp x tp layout of the pipeline engine's vocab-parallel head)."""
    if comm._axis_size(axis) is None:
        return 0
    return comm.combined_axis_index(axis)


def parallel_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    axis: str = ps.TP_AXIS,
    label_smoothing: float = 0.0,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """Per-token cross-entropy loss over vocab-sharded logits.

    Args:
      logits: ``[..., V_local]`` (local shard under shard_map, full vocab
        otherwise).
      labels: integer ``[...]`` global vocab ids.
      ignore_index: label value whose loss contribution is zeroed.

    Returns per-token losses ``[...]`` (reference returns unreduced loss too,
    ``loss_functions.py:217``).
    """
    n = comm._axis_size(axis)
    vocab_local = logits.shape[-1]
    rank = _rank_or_zero(axis)
    start = rank * vocab_local

    logits = logits.astype(jnp.float32)
    # numerically stable global max; the shift carries no gradient
    local_max = jnp.max(logits, axis=-1)
    if n is not None and n > 1:
        names = comm._bound_names(axis)
        global_max = lax.pmax(lax.stop_gradient(local_max),
                              names if len(names) > 1 else names[0])
    else:
        global_max = lax.stop_gradient(local_max)
    shifted = logits - global_max[..., None]

    # global sum of exp
    sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)
    sum_exp = mappings.reduce_from_tensor_parallel_region(sum_exp, axis)

    # label logit: mask ids outside this shard's vocab range
    local_labels = labels - start
    valid = (local_labels >= 0) & (local_labels < vocab_local)
    safe = jnp.where(valid, local_labels, 0)
    label_logit = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
    label_logit = jnp.where(valid, label_logit, 0.0)
    label_logit = mappings.reduce_from_tensor_parallel_region(label_logit, axis)

    loss = jnp.log(sum_exp) - label_logit

    if label_smoothing > 0.0:
        vocab = vocab_local * (n or 1)
        # smoothed loss adds eps * (logsumexp - mean(logits))
        mean_logit = mappings.reduce_from_tensor_parallel_region(
            jnp.sum(shifted, axis=-1), axis) / vocab
        loss = (1.0 - label_smoothing) * loss + label_smoothing * (
            jnp.log(sum_exp) - mean_logit)

    if ignore_index is not None:
        loss = jnp.where(labels == ignore_index, 0.0, loss)
    return loss


def distributed_log_softmax(logits: jax.Array,
                            axis: str = ps.TP_AXIS) -> jax.Array:
    """Log-softmax over the sharded vocab dim (reference
    ``DistributedLogprob:152``); returns the local shard of log-probs."""
    logits = logits.astype(jnp.float32)
    local_max = lax.stop_gradient(jnp.max(logits, axis=-1))
    n = comm._axis_size(axis)
    if n and n > 1:
        names = comm._bound_names(axis)
        local_max = lax.pmax(local_max,
                             names if len(names) > 1 else names[0])
    global_max = local_max
    shifted = logits - global_max[..., None]
    sum_exp = mappings.reduce_from_tensor_parallel_region(
        jnp.sum(jnp.exp(shifted), axis=-1), axis)
    return shifted - jnp.log(sum_exp)[..., None]


def causal_lm_loss(logits: jax.Array, labels: jax.Array,
                   axis: str = ps.TP_AXIS,
                   ignore_index: int = -100) -> jax.Array:
    """Mean vocab-parallel CE over non-ignored tokens — the shared loss head
    of every causal/MLM model family."""
    per_tok = parallel_cross_entropy(logits, labels, axis=axis,
                                     ignore_index=ignore_index)
    denom = jnp.maximum(jnp.sum(labels != ignore_index), 1)
    return jnp.sum(per_tok) / denom


def fused_linear_cross_entropy(
    x: jax.Array,
    kernel: jax.Array,
    labels: jax.Array,
    axis: str = ps.TP_AXIS,
    ignore_index: int = -100,
    chunk: int = 512,
    dtype=jnp.bfloat16,
) -> jax.Array:
    """LM-head matmul + vocab-parallel CE, chunked over the sequence so the
    full ``[B, S, V]`` logits (and their fp32 softmax intermediates) never
    materialise at once.

    The reference materialises full logits and feeds them to
    ``parallel_cross_entropy`` (``parallel_layers/loss_functions.py:217``);
    at tp=1 that is a ``[B, S, 32000]`` bf16 tensor plus an fp32 CE over it —
    pure HBM traffic. Here a ``lax.scan`` over sequence chunks computes
    ``x_chunk @ W → CE`` with the chunk body under
    ``jax.checkpoint(nothing_saveable)``: the backward recomputes each
    chunk's logits (one extra chunk matmul) and accumulates ``dW`` across
    chunks through the scan, so peak memory is O(B·chunk·V) instead of
    O(B·S·V) and the loss fuses into a streaming pipeline.

    Args:
      x: ``[B, S, H]`` hidden states, already inside the TP region (caller
        performs the copy_to / sequence-parallel gather, exactly where
        ``ColumnParallelLinear`` would).
      kernel: ``[H, V_local]`` LM-head kernel (vocab-sharded over ``axis``).
      labels: ``[B, S]`` global vocab ids.
    """
    b, s, h = x.shape
    nc = -(-s // chunk)
    pad = nc * chunk - s
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
    denom = jnp.maximum(jnp.sum(labels != ignore_index), 1)
    xs = jnp.swapaxes(x.reshape(b, nc, chunk, h), 0, 1)       # [nc,B,C,H]
    ls = jnp.swapaxes(labels.reshape(b, nc, chunk), 0, 1)     # [nc,B,C]
    kern = kernel.astype(dtype)

    gspmd = comm._axis_size(axis) is None

    def body(acc, xl):
        xc, lc = xl
        logits = jnp.dot(xc.astype(dtype), kern)
        if gspmd:
            # GSPMD path: pin the chunk logits vocab-sharded (mirrors
            # ColumnParallelLinear's output constraint, layers.py:124-128)
            # so XLA doesn't replicate [B,chunk,V] across tp inside the
            # scan, defeating the memory goal
            logits = ps.with_sharding_constraint(logits, None, None, axis)
        per_tok = parallel_cross_entropy(logits, lc, axis=axis,
                                         ignore_index=ignore_index)
        return acc + jnp.sum(per_tok), None

    body = jax.checkpoint(
        body, policy=jax.checkpoint_policies.nothing_saveable,
        prevent_cse=False)
    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ls))
    return total / denom
