"""Gradient synchronisation and norms for the explicit (shard_map) path.

Analogue of the reference's ``parallel_layers/grads.py``
(``bucket_allreduce_gradients:259`` over DP, SP-grad all-reduce ``:330``,
CP-grad all-reduce ``:348``, ``get_grad_norm:41`` / ``clip_grad_norm:192``
with TP dedup).

Design rule (pinned by tests/test_pipeline.py): gradients are computed
*inside* ``shard_map`` with ``jax.value_and_grad`` and synchronised there
with **raw collectives** before crossing the boundary as primal outputs.
Cotangents must never cross the shard_map boundary: with ``check_vma=False``
the boundary transpose rescales them (claimed-replicated outputs seed
``ct/N``), which silently mis-scales parameter gradients. No bucketing is
needed — XLA fuses and schedules the gradient all-reduces during the
backward (the role of the reference's reverse-order buckets +
``ALLREDUCE_BUCKET_CAP_MB``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from . import comm
from . import comm_compressed as cc
from . import mesh as ps


def _spec_axes(spec) -> set:
    axes = set()
    if isinstance(spec, PartitionSpec):
        for p in spec:
            if p is None:
                continue
            if isinstance(p, tuple):
                axes.update(p)
            else:
                axes.add(p)
    return axes


def allreduce_gradients(
    grads: Any,
    specs: Optional[Any] = None,
    axes: Sequence[str] = (ps.DP_AXIS, ps.CP_AXIS),
    compression: Optional["cc.CompressionConfig"] = None,
    error: Optional[Any] = None,
) -> Any:
    """Average gradients over the bound data axes (reference
    ``bucket_allreduce_gradients:259`` + CP reduce ``:348``).

    Convention (pinned by tests/test_pipeline.py): the loss is the *global
    mean* over tokens, expressed per-shard as the local mean then
    ``lax.pmean`` over data axes. Inside shard_map the pmean's psum-transpose
    hands each shard the *full* cotangent of its local-mean loss, so the
    per-shard grads are ``d(local_mean_loss)/dw`` and the correct global
    combination is their **mean** over the data axes (the reference
    equivalently pre-scales by 1/world before its all-reduce).

    ``specs``: optional PartitionSpec tree; a leaf already sharded over one
    of ``axes`` (e.g. FSDP-style params) is not reduced over that axis.

    ``compression``: optional ``comm_compressed.CompressionConfig`` — the
    reduce runs as a blockwise-quantized (and/or hierarchical) collective
    instead of ``lax.pmean``. ``error``: per-rank error-feedback tree
    (same structure/shapes as ``grads``, this rank's residue slice); when
    given, returns ``(grads, new_error)`` instead of ``grads``.
    """
    bound = [ax for ax in axes if comm._axis_size(ax) not in (None, 1)]
    if not bound:
        return (grads, error) if error is not None else grads

    use_cc = compression is not None and (
        compression.quantized or compression.hierarchical)

    def reduce_leaf(g, spec=None, e=None):
        mentioned = _spec_axes(spec) if spec is not None else set()
        red = tuple(ax for ax in bound if ax not in mentioned)
        if not red:
            # leaf fully sharded over the data axes (FSDP-style): nothing
            # to reduce; residue stays (and stays zero if it started zero)
            return g, e
        if use_cc:
            if e is not None:
                return cc.all_reduce(g, red, config=compression, op="mean",
                                     error=e)
            return cc.all_reduce(g, red, config=compression, op="mean"), None
        for ax in red:
            g = lax.pmean(g, ax)
        return g, (None if e is None else jnp.zeros_like(e))

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    if specs is None:
        flat_s = [None] * len(flat_g)
    else:
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    if error is None:
        flat_e = [None] * len(flat_g)
    else:
        flat_e = jax.tree_util.tree_leaves(error)
    outs = [reduce_leaf(g, s, e)
            for g, s, e in zip(flat_g, flat_s, flat_e)]
    reduced = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
    if error is None:
        return reduced
    new_error = jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs])
    return reduced, new_error


def global_grad_norm(grads: Any, specs: Optional[Any] = None) -> jax.Array:
    """Global L2 norm across every shard (reference ``get_grad_norm:41``):
    each leaf contributes its local sum-of-squares, psum'd over the axes the
    leaf is sharded on (mentioned axes), then summed. Replicated leaves
    contribute once — the analogue of the reference's duplicate-param dedup.
    """
    def leaf_sq(g, spec=None):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        for ax in _spec_axes(spec) if spec is not None else set():
            if comm._axis_size(ax) not in (None, 1):
                sq = lax.psum(sq, ax)
        return sq

    if specs is None:
        leaves = [leaf_sq(g) for g in jax.tree_util.tree_leaves(grads)]
    else:
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        leaves = [leaf_sq(g, s) for g, s in zip(flat_g, flat_s)]
    return jnp.sqrt(sum(leaves))


def clip_grad_norm(grads: Any, max_norm: float,
                   specs: Optional[Any] = None) -> Tuple[Any, jax.Array]:
    """Clip by global norm (reference ``clip_grad_norm:192``); returns
    ``(clipped_grads, norm)``.

    A non-finite norm (overflow/NaN in the backward) yields scale 1.0 —
    the grads pass through unscaled so ``make_train_step(skip_nonfinite=
    True)`` can drop the whole step, instead of a NaN scale poisoning
    every leaf including the ones that were still finite.
    """
    if max_norm <= 0:
        raise ValueError(f"max_norm must be positive, got {max_norm}")
    norm = global_grad_norm(grads, specs)
    scale = jnp.where(jnp.isfinite(norm),
                      jnp.minimum(1.0, max_norm / (norm + 1e-6)), 1.0)
    return jax.tree_util.tree_map(lambda g: g * scale, grads), norm
