"""Tensor-parallel layers.

Analogue of the reference's ``parallel_layers/layers.py`` (``ParallelEmbedding
:186``, ``ColumnParallelLinear:561``, ``RowParallelLinear:815``) and
``modules/qkv_linear.py`` (``GQAQKVColumnParallelLinear:371``).

TPU-first design — each layer supports two execution paths with the same code:

* **GSPMD path** (primary): params carry :class:`flax.linen.Partitioned`
  metadata naming mesh axes; under ``jit`` the collective mappings are
  identities and XLA GSPMD inserts the collectives from the sharding
  annotations. The reference's hand-written async-grad-all-reduce overlap
  (``LinearWithAsyncCommunication``, ``layers.py:434-504``) is subsumed by
  XLA's latency-hiding scheduler.
* **shard_map path** (explicit): under ``shard_map`` the params arrive as
  local shards, the named axis is bound, and the mappings emit explicit
  ``psum``/``all_gather``/``psum_scatter`` exactly like the reference's
  autograd Functions.

Param shapes are declared *global* at init time and *local* when the mesh axis
is bound, so one module definition serves both paths.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from . import comm, mappings
from . import mesh as ps
from ..ops import collective_matmul as cm

Dtype = Any
Initializer = Callable[..., jax.Array]

default_kernel_init = nn.initializers.lecun_normal()
default_embed_init = nn.initializers.normal(stddev=0.02)


def _bound_size(axis: str) -> Optional[int]:
    return comm._axis_size(axis)


def _maybe_local(n: int, axis: str) -> int:
    """Global size ``n`` outside shard_map, local shard size inside."""
    s = _bound_size(axis)
    if s is None or s == 1:
        return n
    if n % s != 0:
        raise ValueError(f"size {n} not divisible by axis {axis!r} size {s}")
    return n // s


def _partitioned(init: Initializer, names: Tuple[Optional[str], ...]):
    """Attach mesh-axis names (GSPMD metadata) unless running under shard_map,
    where params are local and metadata boxing would confuse apply."""
    return nn.with_partitioning(init, names)


def _lora_input(mod: nn.Module, x: jax.Array, rate: float) -> jax.Array:
    """The LoRA branch's input: dropped-out iff ``rate > 0`` and the caller
    supplied a "dropout" rng (reference ``modules/lora/layer.py:237``
    computes ``lora_B(lora_A(lora_dropout(x)))``; the frozen base matmul
    always sees the undropped activations)."""
    if rate > 0.0 and mod.has_rng("dropout"):
        return nn.Dropout(rate=rate)(x, deterministic=False)
    return x


class ColumnParallelLinear(nn.Module):
    """Linear with output features sharded over the tp axis.

    Reference: ``parallel_layers/layers.py:561``. ``Y = X W + b`` with
    ``W = [W_1 .. W_p]`` along the output dim; forward enters the TP region by
    identity (backward all-reduce), or by all-gather along the sequence dim
    when ``sequence_parallel`` (reference ``layers.py:438-504``).

    ``overlap_comm`` routes the entry collective + matmul through the
    decomposed ring primitives in :mod:`..ops.collective_matmul` so the
    transfer overlaps the per-shard partial matmuls (the reference hides the
    same latency with ``LinearWithAsyncCommunication``). ``None`` = auto
    (on when the tp axis is bound with size ≥ 4 and shapes tile), ``True`` =
    on where shapes allow (silent monolithic fallback otherwise), ``False``
    = always monolithic. LoRA's activation-space branch needs the gathered
    input, so adapters fall back.
    """

    features: int  # global output features
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = default_kernel_init
    bias_init: Initializer = nn.initializers.zeros_init()
    axis: str = ps.TP_AXIS
    seq_dim: int = 1
    overlap_comm: Optional[bool] = None
    # Activation-wire compression (docs/comm_compression.md): None/"fp32"
    # keeps the entry collective full precision; "int8"/"fp8" codec-encode
    # its payload — on the decomposed ring when ``overlap_comm`` engages,
    # on the monolithic collective otherwise (LoRA keeps the fp path).
    activation_comm_dtype: Optional[str] = None
    activation_comm_block_size: int = 256
    # LoRA adapter (reference modules/lora/tp_layer.py LoraParallelLinear):
    # 0 disables; A is replicated, B is output-sharded like the kernel.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        out_local = _maybe_local(self.features, self.axis)
        kernel = self.param(
            "kernel",
            _partitioned(self.kernel_init, (None, self.axis)),
            (x.shape[-1], out_local), self.param_dtype)
        bias = None
        if self.use_bias:
            bias = self.param("bias", _partitioned(self.bias_init, (self.axis,)),
                              (out_local,), self.param_dtype)
        lora_a = lora_b = None
        if self.lora_rank > 0:
            lora_a = self.param(
                "lora_a", _partitioned(default_kernel_init, (None, None)),
                (x.shape[-1], self.lora_rank), self.param_dtype)
            lora_b = self.param(
                "lora_b",
                _partitioned(nn.initializers.zeros_init(), (None, self.axis)),
                (self.lora_rank, out_local), self.param_dtype)

        wire = cm.wire_config(self.activation_comm_dtype,
                              self.activation_comm_block_size)
        engaged = self.lora_rank == 0 and cm.overlap_engaged(
            self.overlap_comm, self.axis, x.shape, self.seq_dim,
            needs_divisible=not self.sequence_parallel)
        # Quantized wire without an engaged ring still routes through the
        # primitives monolithically — the collective is compressed either
        # way, and the impl choice stays static on shapes (no recompiles).
        if engaged or (wire is not None and self.lora_rank == 0
                       and _bound_size(self.axis) is not None):
            impl = "decomposed" if engaged else "monolithic"
            x = x.astype(self.dtype)
            if self.sequence_parallel:
                y = cm.all_gather_matmul(x, kernel.astype(self.dtype),
                                         self.axis, self.seq_dim,
                                         impl=impl, wire=wire)
            else:
                y = cm.copy_matmul(x, kernel.astype(self.dtype),
                                   self.axis, self.seq_dim,
                                   impl=impl, wire=wire)
            if bias is not None:
                y = y + bias.astype(self.dtype)
            if self.gather_output:
                y = mappings.gather_from_tensor_parallel_region(
                    y, self.axis, -1)
            return y

        if self.sequence_parallel:
            x = mappings.gather_from_sequence_parallel_region(
                x, self.axis, self.seq_dim, to_model_parallel=True)
        else:
            x = mappings.copy_to_tensor_parallel_region(x, self.axis)

        x = x.astype(self.dtype)
        y = jnp.dot(x, kernel.astype(self.dtype))
        if lora_a is not None:
            scale = self.lora_alpha / self.lora_rank
            x_l = _lora_input(self, x, self.lora_dropout)
            y = y + scale * jnp.dot(
                jnp.dot(x_l, lora_a.astype(self.dtype)),
                lora_b.astype(self.dtype))
        if bias is not None:
            y = y + bias.astype(self.dtype)
        if self.gather_output:
            y = mappings.gather_from_tensor_parallel_region(y, self.axis, -1)
        elif _bound_size(self.axis) is None:
            # GSPMD path: pin the output sharding so XLA keeps the activation
            # tp-sharded between column and row linears.
            y = ps.with_sharding_constraint(
                y, *([None] * (y.ndim - 1) + [self.axis]))
        return y


class OutputChannelParallelConv2d(nn.Module):
    """Conv2d with output channels sharded over tp.

    Reference: ``parallel_layers/layers.py:1309`` (``Conv2dColumnParallel``
    pair for vision backbones). NHWC/HWIO layout — the TPU-native conv
    layout XLA tiles onto the MXU."""

    features: int  # global output channels
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    gather_output: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = default_kernel_init
    axis: str = ps.TP_AXIS
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        out_local = _maybe_local(self.features, self.axis)
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            _partitioned(self.kernel_init, (None, None, None, self.axis)),
            (kh, kw, x.shape[-1], out_local), self.param_dtype)
        lora_a = lora_b = None
        if self.lora_rank > 0:
            # LoRA for convs (reference modules/lora/layer.py:331): A is a
            # same-geometry conv into the rank, B a 1x1 conv out of it; B's
            # out channels shard like the base kernel so the adapter rides
            # the layer's collectives
            lora_a = self.param(
                "lora_a",
                _partitioned(default_kernel_init, (None, None, None, None)),
                (kh, kw, x.shape[-1], self.lora_rank), self.param_dtype)
            lora_b = self.param(
                "lora_b",
                _partitioned(nn.initializers.zeros_init(),
                             (None, None, None, self.axis)),
                (1, 1, self.lora_rank, out_local), self.param_dtype)
        x = mappings.copy_to_tensor_parallel_region(x, self.axis)
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel.astype(self.dtype),
            window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if lora_a is not None:
            scale = self.lora_alpha / self.lora_rank
            x_l = _lora_input(self, x, self.lora_dropout)
            ya = jax.lax.conv_general_dilated(
                x_l.astype(self.dtype), lora_a.astype(self.dtype),
                window_strides=self.strides, padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            y = y + scale * jax.lax.conv_general_dilated(
                ya, lora_b.astype(self.dtype), window_strides=(1, 1),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            bias = self.param("bias",
                              _partitioned(nn.initializers.zeros_init(),
                                           (self.axis,)),
                              (out_local,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        if self.gather_output:
            y = mappings.gather_from_tensor_parallel_region(y, self.axis,
                                                            -1)
        return y


class InputChannelParallelConv2d(nn.Module):
    """Conv2d with input channels sharded over tp (the row-parallel dual,
    reference ``parallel_layers/layers.py:1432``): partial sums over the
    input-channel shard exit with an all-reduce."""

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    input_is_parallel: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = default_kernel_init
    axis: str = ps.TP_AXIS
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if not self.input_is_parallel:
            x = mappings.scatter_to_tensor_parallel_region(x, self.axis, -1)
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            _partitioned(self.kernel_init, (None, None, self.axis, None)),
            (kh, kw, x.shape[-1], self.features), self.param_dtype)
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel.astype(self.dtype),
            window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.lora_rank > 0:
            # A's input channels shard with the base kernel; the adapter's
            # partial sums join the base partials in the SAME exit
            # all-reduce below
            lora_a = self.param(
                "lora_a",
                _partitioned(default_kernel_init,
                             (None, None, self.axis, None)),
                (kh, kw, x.shape[-1], self.lora_rank), self.param_dtype)
            lora_b = self.param(
                "lora_b",
                _partitioned(nn.initializers.zeros_init(),
                             (None, None, None, None)),
                (1, 1, self.lora_rank, self.features), self.param_dtype)
            scale = self.lora_alpha / self.lora_rank
            x_l = _lora_input(self, x, self.lora_dropout)
            ya = jax.lax.conv_general_dilated(
                x_l.astype(self.dtype), lora_a.astype(self.dtype),
                window_strides=self.strides, padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            y = y + scale * jax.lax.conv_general_dilated(
                ya, lora_b.astype(self.dtype), window_strides=(1, 1),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = mappings.reduce_from_tensor_parallel_region(y, self.axis)
        if self.use_bias:
            bias = self.param("bias",
                              _partitioned(nn.initializers.zeros_init(),
                                           (None,)),
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


def embedding_attend(table: jax.Array, x: jax.Array, *,
                     sequence_parallel: bool = False,
                     dtype: Dtype = jnp.bfloat16,
                     axis: str = ps.TP_AXIS, seq_dim: int = 1,
                     gather_output: bool = False) -> jax.Array:
    """Tied-embedding LM head: ``x @ table.T`` with the vocab dim tp-sharded.

    The column-parallel dual of :class:`ParallelEmbedding` — same entry
    collectives as :class:`ColumnParallelLinear` (``gather_output=False``) so
    the result feeds vocab-parallel CE directly. Used for tied word
    embeddings (reference ``pipeline/model.py:750``
    ``register_shared_weights`` and the HF ``tie_word_embeddings`` configs).
    """
    if sequence_parallel:
        x = mappings.gather_from_sequence_parallel_region(
            x, axis, seq_dim, to_model_parallel=True)
    else:
        x = mappings.copy_to_tensor_parallel_region(x, axis)
    y = jnp.dot(x.astype(dtype), jnp.swapaxes(table.astype(dtype), 0, 1))
    if gather_output:
        return mappings.gather_from_tensor_parallel_region(y, axis, -1)
    if _bound_size(axis) is None:
        y = ps.with_sharding_constraint(
            y, *([None] * (y.ndim - 1) + [axis]))
    return y


class RowParallelLinear(nn.Module):
    """Linear with input features sharded over the tp axis.

    Reference: ``parallel_layers/layers.py:815``. ``Y = X W`` with ``W``
    sharded along the input dim; forward exits the TP region by all-reduce, or
    reduce-scatter along the sequence dim when ``sequence_parallel``.

    ``overlap_comm`` (same semantics as :class:`ColumnParallelLinear`)
    decomposes the exit reduce-scatter / all-reduce so each destination
    block's partial product ships while the next block multiplies. Needs
    ``x.shape[seq_dim]`` to tile over the axis (decode's single-token steps
    fall back monolithically — the decision is static on shapes, so it adds
    no recompiles).
    """

    features: int  # global output features
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = default_kernel_init
    bias_init: Initializer = nn.initializers.zeros_init()
    axis: str = ps.TP_AXIS
    seq_dim: int = 1
    overlap_comm: Optional[bool] = None
    # Activation-wire compression for the exit collective (see
    # ColumnParallelLinear) — quantizes the reduce-scatter / all-reduce.
    activation_comm_dtype: Optional[str] = None
    activation_comm_block_size: int = 256
    # Reduced-sync TP (PAPERS.md "Tensor-Parallelism with Partially
    # Synchronized Activations"): False elides the exit all-reduce — each
    # rank keeps its local partial product (bias split 1/n so the shares
    # still sum to the true output) and the model resyncs periodically via
    # ``cm.tp_sync_schedule``. Ignored under ``sequence_parallel`` (the
    # reduce-scatter also reshapes, so it cannot be elided).
    tp_sync: bool = True
    # LoRA adapter: A is input-sharded like the kernel, B replicated; the
    # lora partial sums ride the layer's existing all-reduce/reduce-scatter.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if not self.input_is_parallel:
            x = mappings.scatter_to_tensor_parallel_region(x, self.axis, -1)
        in_local = x.shape[-1]
        kernel = self.param(
            "kernel",
            _partitioned(self.kernel_init, (self.axis, None)),
            (in_local, self.features), self.param_dtype)
        x = x.astype(self.dtype)
        if not self.tp_sync and not self.sequence_parallel:
            # Reduced-sync exit: local partial product, no collective. Each
            # rank holds a 1/n share of the true output (bias included), so
            # a later psum of the accumulated deviation recovers the full
            # activation at the model's periodic resync points.
            y = jnp.dot(x, kernel.astype(self.dtype))
            if self.lora_rank > 0:
                lora_a = self.param(
                    "lora_a",
                    _partitioned(default_kernel_init, (self.axis, None)),
                    (in_local, self.lora_rank), self.param_dtype)
                lora_b = self.param(
                    "lora_b",
                    _partitioned(nn.initializers.zeros_init(), (None, None)),
                    (self.lora_rank, self.features), self.param_dtype)
                scale = self.lora_alpha / self.lora_rank
                x_l = _lora_input(self, x, self.lora_dropout)
                y = y + scale * jnp.dot(
                    jnp.dot(x_l, lora_a.astype(self.dtype)),
                    lora_b.astype(self.dtype))
            if self.use_bias:
                bias = self.param("bias",
                                  _partitioned(self.bias_init, (None,)),
                                  (self.features,), self.param_dtype)
                n = _bound_size(self.axis) or 1
                y = y + bias.astype(self.dtype) / n
            return y
        wire = cm.wire_config(self.activation_comm_dtype,
                              self.activation_comm_block_size)
        engaged = self.lora_rank == 0 and cm.overlap_engaged(
            self.overlap_comm, self.axis, x.shape, self.seq_dim,
            needs_divisible=True)
        if engaged or (wire is not None and self.lora_rank == 0
                       and _bound_size(self.axis) is not None):
            impl = "decomposed" if engaged else "monolithic"
            if self.sequence_parallel:
                y = cm.matmul_reduce_scatter(x, kernel.astype(self.dtype),
                                             self.axis, self.seq_dim,
                                             impl=impl, wire=wire)
            else:
                y = cm.matmul_all_reduce(x, kernel.astype(self.dtype),
                                         self.axis, self.seq_dim,
                                         impl=impl, wire=wire)
            if self.use_bias:
                bias = self.param("bias",
                                  _partitioned(self.bias_init, (None,)),
                                  (self.features,), self.param_dtype)
                y = y + bias.astype(self.dtype)
            return y
        y = jnp.dot(x, kernel.astype(self.dtype))
        if self.lora_rank > 0:
            lora_a = self.param(
                "lora_a", _partitioned(default_kernel_init, (self.axis, None)),
                (in_local, self.lora_rank), self.param_dtype)
            lora_b = self.param(
                "lora_b",
                _partitioned(nn.initializers.zeros_init(), (None, None)),
                (self.lora_rank, self.features), self.param_dtype)
            scale = self.lora_alpha / self.lora_rank
            x_l = _lora_input(self, x, self.lora_dropout)
            y = y + scale * jnp.dot(
                jnp.dot(x_l, lora_a.astype(self.dtype)),
                lora_b.astype(self.dtype))
        if self.sequence_parallel:
            y = mappings.reduce_scatter_to_sequence_parallel_region(
                y, self.axis, self.seq_dim)
        else:
            y = mappings.reduce_from_tensor_parallel_region(y, self.axis)
        if self.use_bias:
            # bias is replicated and added after the reduce (reference
            # layers.py:971: bias on the full output)
            bias = self.param("bias", _partitioned(self.bias_init, (None,)),
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


class ParallelEmbedding(nn.Module):
    """Embedding with the vocab dim sharded over tp.

    Reference: ``parallel_layers/layers.py:186`` (vocab-sharded path
    ``:334``). Under shard_map: mask out-of-shard ids, lookup the local table,
    all-reduce the partial embeddings. Under GSPMD: plain take with a sharded
    table — XLA generates the same masked-gather + all-reduce.
    """

    num_embeddings: int
    features: int
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    embedding_init: Initializer = default_embed_init
    axis: str = ps.TP_AXIS
    # LoRA adapter (reference modules/lora/layer.py LoraEmbedding): A is
    # vocab-sharded like the table, B replicated. lora_dropout is accepted
    # for config uniformity but inapplicable — the input is integer ids
    # (the reference's LoraEmbedding likewise applies no dropout).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    @nn.compact
    def __call__(self, ids: jax.Array) -> jax.Array:
        vocab_local = _maybe_local(self.num_embeddings, self.axis)
        table = self.param(
            "embedding",
            _partitioned(self.embedding_init, (self.axis, None)),
            (vocab_local, self.features), self.param_dtype)
        lora_a = lora_b = None
        if self.lora_rank > 0:
            lora_a = self.param(
                "lora_a",
                _partitioned(nn.initializers.zeros_init(), (self.axis, None)),
                (vocab_local, self.lora_rank), self.param_dtype)
            lora_b = self.param(
                "lora_b", _partitioned(default_kernel_init, (None, None)),
                (self.lora_rank, self.features), self.param_dtype)

        def lookup(tbl, idx):
            return jnp.take(tbl.astype(self.dtype), idx, axis=0)

        scale = (self.lora_alpha / self.lora_rank if self.lora_rank else 0.0)
        s = _bound_size(self.axis)
        if s is None or s == 1:
            out = lookup(table, ids)
            if lora_a is not None:
                out = out + scale * jnp.dot(lookup(lora_a, ids),
                                            lora_b.astype(self.dtype))
            return out
        rank = comm.combined_axis_index(self.axis)
        start = rank * vocab_local
        local_ids = ids - start
        valid = (local_ids >= 0) & (local_ids < vocab_local)
        local_ids = jnp.where(valid, local_ids, 0)
        out = lookup(table, local_ids)
        if lora_a is not None:
            out = out + scale * jnp.dot(lookup(lora_a, local_ids),
                                        lora_b.astype(self.dtype))
        out = jnp.where(valid[..., None], out, jnp.zeros_like(out))
        return mappings.reduce_from_tensor_parallel_region(out, self.axis)


class GQAQKVColumnParallelLinear(nn.Module):
    """Fused Q/K/V projection with grouped-query attention support.

    Reference: ``modules/qkv_linear.py:371``. When ``num_kv_heads < tp`` the
    reference *materialises* each KV head ``kv_size_multiplier = tp /
    num_kv_heads`` times in the checkpoint so every tp shard owns a copy.
    Here the parameterisation stays true GQA — one stored copy per KV head
    (directly mappable to HF checkpoints): the KV kernel is *replicated*, each
    shard slices its group's head (``head = tp_rank // mult``), and the slice
    sits behind ``copy_to_tensor_parallel_region`` so the backward psum
    assembles the full KV gradient from all shards (replicas can never
    diverge, unlike materialised copies).
    """

    num_heads: int
    num_kv_heads: int
    head_dim: int
    use_bias: bool = False
    sequence_parallel: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Initializer = default_kernel_init
    bias_init: Initializer = nn.initializers.zeros_init()
    axis: str = ps.TP_AXIS
    seq_dim: int = 1
    tp_size: Optional[int] = None  # required to size KV replication
    # Overlapped entry (see ColumnParallelLinear): the three projections
    # share one gathered stream — all_gather_matmul((wq, wk, wv)). The
    # replicated-KV path (kv_size_multiplier > 1) and activation-space LoRA
    # fall back; weight-space LoRA folds into the kernels and rides along.
    overlap_comm: Optional[bool] = None
    # Activation-wire compression for the shared entry collective (see
    # ColumnParallelLinear); same replicated-KV / LoRA fallbacks apply.
    activation_comm_dtype: Optional[str] = None
    activation_comm_block_size: int = 256
    # LoRA adapters (weight-space; reference LoraGQAQKVParallelLinear).
    # With lora_dropout active (rate > 0 and a "dropout" rng supplied) the
    # adapters switch to activation space — dropout on the adapter input
    # cannot be expressed as a weight delta.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0

    def _tp(self) -> int:
        s = _bound_size(self.axis)
        if s is not None:
            return s
        if self.tp_size is not None:
            return self.tp_size
        if ps.model_parallel_is_initialized():
            return ps.get_tensor_model_parallel_size()
        return 1

    @property
    def kv_size_multiplier(self) -> int:
        tp = self._tp()
        return max(1, tp // self.num_kv_heads)

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        tp = self._tp()
        mult = max(1, tp // self.num_kv_heads)
        if mult > 1 and tp % self.num_kv_heads != 0:
            raise ValueError(
                f"tp size {tp} must be a multiple of num_kv_heads "
                f"{self.num_kv_heads} when tp > num_kv_heads")
        if mult == 1 and self.num_kv_heads % tp != 0:
            raise ValueError(
                f"num_kv_heads {self.num_kv_heads} not divisible by tp {tp}")
        q_features = self.num_heads * self.head_dim
        kv_features = self.num_kv_heads * self.head_dim
        q_local = _maybe_local(q_features, self.axis)

        wq = self.param("q_kernel",
                        _partitioned(self.kernel_init, (None, self.axis)),
                        (x.shape[-1], q_local), self.param_dtype)
        if mult == 1:
            kv_names: Tuple[Optional[str], ...] = (None, self.axis)
            kv_shape = (x.shape[-1], _maybe_local(kv_features, self.axis))
        else:
            # true-GQA replicated KV kernel; sliced per shard below
            kv_names = (None, None)
            kv_shape = (x.shape[-1], kv_features)
        wk = self.param("k_kernel", _partitioned(self.kernel_init, kv_names),
                        kv_shape, self.param_dtype)
        wv = self.param("v_kernel", _partitioned(self.kernel_init, kv_names),
                        kv_shape, self.param_dtype)
        lora_act = (self.lora_rank > 0 and self.lora_dropout > 0.0
                    and self.has_rng("dropout"))
        if self.lora_rank > 0:
            # weight-space adapters (reference LoraGQAQKVParallelLinear,
            # tp_layer.py:62): delta = scale * A @ B added to each kernel,
            # so the GQA slice/copy paths below need no changes
            scale = self.lora_alpha / self.lora_rank
            qa = self.param("q_lora_a",
                            _partitioned(default_kernel_init, (None, None)),
                            (x.shape[-1], self.lora_rank), self.param_dtype)
            qb = self.param("q_lora_b", _partitioned(
                nn.initializers.zeros_init(), (None, self.axis)),
                (self.lora_rank, q_local), self.param_dtype)
            ka = self.param("k_lora_a",
                            _partitioned(default_kernel_init, (None, None)),
                            (x.shape[-1], self.lora_rank), self.param_dtype)
            kb = self.param("k_lora_b", _partitioned(
                nn.initializers.zeros_init(), kv_names),
                (self.lora_rank, kv_shape[1]), self.param_dtype)
            va = self.param("v_lora_a",
                            _partitioned(default_kernel_init, (None, None)),
                            (x.shape[-1], self.lora_rank), self.param_dtype)
            vb = self.param("v_lora_b", _partitioned(
                nn.initializers.zeros_init(), kv_names),
                (self.lora_rank, kv_shape[1]), self.param_dtype)
            if not lora_act:
                wq = wq + scale * (qa @ qb)
                wk = wk + scale * (ka @ kb)
                wv = wv + scale * (va @ vb)

        bq = bk = bv = None
        if self.use_bias:
            bq = self.param("q_bias",
                            _partitioned(self.bias_init, (self.axis,)),
                            (q_local,), self.param_dtype)
            kv_bias_names = (self.axis,) if mult == 1 else (None,)
            bk = self.param("k_bias", _partitioned(self.bias_init,
                                                   kv_bias_names),
                            (kv_shape[1],), self.param_dtype)
            bv = self.param("v_bias", _partitioned(self.bias_init,
                                                   kv_bias_names),
                            (kv_shape[1],), self.param_dtype)

        if mult > 1 and _bound_size(self.axis) is not None:
            # replicated weight enters the TP region (bwd: psum assembles the
            # full KV grad from every shard's head-slice contribution)
            wk = mappings.copy_to_tensor_parallel_region(wk, self.axis)
            wv = mappings.copy_to_tensor_parallel_region(wv, self.axis)
            head = jax.lax.axis_index(self.axis) // mult
            wk = jax.lax.dynamic_slice_in_dim(
                wk, head * self.head_dim, self.head_dim, axis=1)
            wv = jax.lax.dynamic_slice_in_dim(
                wv, head * self.head_dim, self.head_dim, axis=1)
            if lora_act:
                # activation-space adapters need the same per-head slice of
                # the replicated B factors
                kb = mappings.copy_to_tensor_parallel_region(kb, self.axis)
                vb = mappings.copy_to_tensor_parallel_region(vb, self.axis)
                kb = jax.lax.dynamic_slice_in_dim(
                    kb, head * self.head_dim, self.head_dim, axis=1)
                vb = jax.lax.dynamic_slice_in_dim(
                    vb, head * self.head_dim, self.head_dim, axis=1)
            if self.use_bias:
                bk = mappings.copy_to_tensor_parallel_region(bk, self.axis)
                bv = mappings.copy_to_tensor_parallel_region(bv, self.axis)
                bk = jax.lax.dynamic_slice_in_dim(
                    bk, head * self.head_dim, self.head_dim, axis=0)
                bv = jax.lax.dynamic_slice_in_dim(
                    bv, head * self.head_dim, self.head_dim, axis=0)

        wire = cm.wire_config(self.activation_comm_dtype,
                              self.activation_comm_block_size)
        engaged = (mult == 1 and not lora_act and cm.overlap_engaged(
            self.overlap_comm, self.axis, x.shape, self.seq_dim,
            needs_divisible=not self.sequence_parallel))
        if engaged or (wire is not None and mult == 1 and not lora_act
                       and _bound_size(self.axis) is not None):
            impl = "decomposed" if engaged else "monolithic"
            x = x.astype(self.dtype)
            kernels = (wq.astype(self.dtype), wk.astype(self.dtype),
                       wv.astype(self.dtype))
            if self.sequence_parallel:
                q, k, v = cm.all_gather_matmul(x, kernels, self.axis,
                                               self.seq_dim,
                                               impl=impl, wire=wire)
            else:
                q, k, v = cm.copy_matmul(x, kernels, self.axis,
                                         self.seq_dim, impl=impl, wire=wire)
            if self.use_bias:
                q = q + bq.astype(self.dtype)
                k = k + bk.astype(self.dtype)
                v = v + bv.astype(self.dtype)
            return q, k, v

        if self.sequence_parallel:
            x = mappings.gather_from_sequence_parallel_region(
                x, self.axis, self.seq_dim, to_model_parallel=True)
        else:
            x = mappings.copy_to_tensor_parallel_region(x, self.axis)
        x = x.astype(self.dtype)

        q = jnp.dot(x, wq.astype(self.dtype))
        k = jnp.dot(x, wk.astype(self.dtype))
        v = jnp.dot(x, wv.astype(self.dtype))
        if lora_act:
            x_l = _lora_input(self, x, self.lora_dropout)
            q = q + scale * jnp.dot(jnp.dot(x_l, qa.astype(self.dtype)),
                                    qb.astype(self.dtype))
            k = k + scale * jnp.dot(jnp.dot(x_l, ka.astype(self.dtype)),
                                    kb.astype(self.dtype))
            v = v + scale * jnp.dot(jnp.dot(x_l, va.astype(self.dtype)),
                                    vb.astype(self.dtype))
        if self.use_bias:
            q = q + bq.astype(self.dtype)
            k = k + bk.astype(self.dtype)
            v = v + bv.astype(self.dtype)
        if _bound_size(self.axis) is None:
            spec = [None] * (q.ndim - 1) + [self.axis]
            q = ps.with_sharding_constraint(q, *spec)
            if mult == 1:
                k = ps.with_sharding_constraint(k, *spec)
                v = ps.with_sharding_constraint(v, *spec)
        return q, k, v


class SPMDRank(nn.Module):
    """Rank-as-weight for AOT-traced SPMD graphs (reference:
    ``parallel_layers/layers.py:1543``): an int32 param whose *local shard*
    holds that shard's tp rank (arange-over-tp init, tp-sharded), so a
    compiled graph can branch on rank without a host value.

    Under shard_map the bound ``axis_index`` is returned directly; under
    GSPMD the caller receives the tp-sharded rank vector — each shard's
    element is its own rank — for use in partitioned ops.
    """

    axis: str = ps.TP_AXIS

    @nn.compact
    def __call__(self) -> jax.Array:
        tp = (ps.get_tensor_model_parallel_size()
              if ps.model_parallel_is_initialized() else 1)
        rank = self.param(
            "rank",
            _partitioned(
                lambda key, shape, dtype: jnp.arange(tp, dtype=dtype)[
                    :shape[0]] if _bound_size(self.axis) is None
                else jnp.zeros(shape, dtype),
                (self.axis,)),
            (_maybe_local(tp, self.axis),), jnp.int32)
        s = _bound_size(self.axis)
        if s is None:
            return rank  # GSPMD: tp-sharded [tp], shard i holds i
        if s == 1:
            return rank[0]
        return jax.lax.axis_index(self.axis).astype(jnp.int32)
