"""Device mesh construction and parallel state.

TPU-native analogue of the reference's ``parallel_layers/parallel_state.py``.
Where the reference builds ``torch.distributed`` process groups plus raw SPMD
replica-group lists from a rank tensor reshaped ``[PP, DP, CP, TP]``
(``parallel_state.py:620-636``), we build a single ``jax.sharding.Mesh`` with
axes ``("pp", "dp", "cp", "tp")`` — XLA's GSPMD partitioner and ``shard_map``
collectives replace explicit process groups entirely (one SPMD program, not
one process per rank).

The expert-parallel view (``[PP, DP_exp, EP, TP]``, ``parallel_state.py:629``)
is a *reshape of the same device array*: the ``dp`` and ``cp`` axes merge and
re-split into ``(dp_exp, ep)``, keeping TP groups identical across both views.

Topology-aware device ordering (the reference's ``ascending_ring_PG_group`` /
``ascending_descending_ring_PG_group`` layouts, ``parallel_state.py:107,177``)
maps to ``mesh_utils.create_device_mesh``-style placement: the innermost mesh
axis (``tp``) is laid out along the fastest ICI rings of the TPU torus.

Rank getters come in two flavours:

* mesh-level (host side): sizes, replica-group lists (for tests / parity with
  the reference's ``get_*_replica_groups``);
* in-graph (inside ``shard_map``): ``get_*_rank()`` returns a traced
  ``lax.axis_index`` — the SPMD analogue of the per-process rank.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

# Canonical axis names. Order is [pp, dp, cp, tp] — tp innermost so tensor
# parallel collectives ride nearest-neighbour ICI links (reference orders the
# rank tensor the same way for NeuronLink rings, parallel_state.py:620-636).
PP_AXIS = "pp"
DP_AXIS = "dp"
CP_AXIS = "cp"
TP_AXIS = "tp"
# Expert view axes (reference: [PP, DP_exp, EP, TP], parallel_state.py:629).
EP_AXIS = "ep"
EXP_DP_AXIS = "dp_exp"

MESH_AXES = (PP_AXIS, DP_AXIS, CP_AXIS, TP_AXIS)
EXPERT_MESH_AXES = (PP_AXIS, EXP_DP_AXIS, EP_AXIS, TP_AXIS)


class _ParallelState:
    """Singleton holding the constructed meshes (cf. the module-level group
    globals in the reference's parallel_state)."""

    def __init__(self) -> None:
        self.mesh: Optional[Mesh] = None
        self.expert_mesh: Optional[Mesh] = None
        self.device_array: Optional[np.ndarray] = None  # [pp, dp, cp, tp]
        self.sizes: dict = {}
        self.aot_mode: bool = False
        self.phase_meshes: dict = {}  # (tp, ep) -> Mesh view
        # (fast_axes, slow_axes) link-speed split for hierarchical
        # collectives; None = undeclared (MESH_AXES-order convention).
        self.axis_hierarchy: Optional[Tuple[Tuple[str, ...],
                                            Tuple[str, ...]]] = None


_STATE = _ParallelState()


def _topology_device_order(devices: Sequence[Any], shape: Tuple[int, ...]) -> np.ndarray:
    """Arrange devices into ``shape`` with ICI-topology awareness.

    On real TPU slices delegates to ``mesh_utils.create_device_mesh`` (which
    plays the role of the reference's LOGIC1/LOGIC2 ring layouts,
    ``parallel_state.py:107,177,341``). On CPU/virtual devices (tests) or when
    the topology solver rejects the shape, falls back to id-sorted reshape.
    """
    devs = sorted(devices, key=lambda d: d.id)
    if int(np.prod(shape)) != len(devs):
        raise ValueError(
            f"mesh shape {shape} does not match device count {len(devs)}")
    plat = getattr(devs[0], "platform", "cpu")
    if plat == "tpu" and len(devs) > 1:
        try:
            from jax.experimental import mesh_utils

            return np.asarray(
                mesh_utils.create_device_mesh(shape, devices=devs))
        except Exception as e:  # pragma: no cover - topology-solver fallback
            logger.warning("create_device_mesh failed (%s); id-order fallback", e)
    return np.asarray(devs, dtype=object).reshape(shape)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap: one JAX process per host.

    The analogue of the reference's torchrun/MPI world initialisation
    (SURVEY §5 "comm backend"): after this, ``jax.devices()`` spans every
    host and XLA collectives ride ICI within a slice and DCN across
    slices. Arguments default to the TPU metadata / environment discovery
    built into ``jax.distributed.initialize`` (``JAX_COORDINATOR_ADDRESS``
    etc.); pass them explicitly on non-TPU clusters.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def _hybrid_device_order(devices: Sequence[Any], shape: Tuple[int, ...],
                         dcn_dp: int) -> np.ndarray:
    """Multi-slice layout: the dp axis factors as (dcn outer, ici inner) so
    only data-parallel collectives cross DCN."""
    pp, dp, cp, tp = shape
    if dp % dcn_dp != 0:
        raise ValueError(
            f"dp {dp} not divisible by dcn_data_parallel_size {dcn_dp}")
    devs = sorted(devices, key=lambda d: (getattr(d, "process_index", 0),
                                          d.id))
    plat = getattr(devs[0], "platform", "cpu")
    if plat == "tpu":
        try:
            from jax.experimental import mesh_utils

            return np.asarray(mesh_utils.create_hybrid_device_mesh(
                (pp, dp // dcn_dp, cp, tp), (1, dcn_dp, 1, 1),
                devices=devs))
        except Exception as e:  # pragma: no cover - solver fallback
            logger.warning("create_hybrid_device_mesh failed (%s); "
                           "process-blocked fallback", e)
    # virtual/CPU fallback: contiguous per-slice blocks stacked on dp
    per = len(devs) // dcn_dp
    blocks = [np.asarray(devs[i * per:(i + 1) * per], dtype=object)
              .reshape(pp, dp // dcn_dp, cp, tp) for i in range(dcn_dp)]
    return np.concatenate(blocks, axis=1)


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_model_parallel_size: int = 1,
    devices: Optional[Sequence[Any]] = None,
    data_parallel_size: Optional[int] = None,
    dcn_data_parallel_size: Optional[int] = None,
) -> Mesh:
    """Build the global meshes.

    Analogue of the reference's ``initialize_model_parallel``
    (``parallel_state.py:391``). Degree validation and the ``[PP, DP, CP, TP]``
    factorisation follow ``parallel_state.py:560-636``. There is no collective
    warm-up (``:647-657``) — XLA initialises collectives at first compile.

    ``dcn_data_parallel_size``: multi-slice/multi-host layouts — that many
    data-parallel groups are placed *across* slices (DCN), everything else
    stays within a slice (ICI). The standard TPU recipe: only DP gradients
    cross the slow links (the reference's multi-node analogue is its
    EFA/NCCL DP process groups over torchrun nodes).
    """
    if devices is None:
        devices = jax.devices()
    world = len(devices)
    # shared divisibility rules — the placement planner prunes layouts by
    # the same function, so a plan it emits always initializes here
    from ..config import mesh_factorization

    sizes = mesh_factorization(
        world,
        tensor_parallel_size=tensor_model_parallel_size,
        pipeline_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size,
        expert_parallel_size=expert_model_parallel_size,
        data_parallel_size=data_parallel_size,
        dcn_data_parallel_size=dcn_data_parallel_size)
    tp, pp, cp, ep = sizes["tp"], sizes["pp"], sizes["cp"], sizes["ep"]
    dp, dp_exp = sizes["dp"], sizes["dp_exp"]

    if dcn_data_parallel_size and dcn_data_parallel_size > 1:
        arr = _hybrid_device_order(devices, (pp, dp, cp, tp),
                                   dcn_data_parallel_size)
    else:
        arr = _topology_device_order(devices, (pp, dp, cp, tp))
    _STATE.device_array = arr
    _STATE.mesh = Mesh(arr, MESH_AXES)
    _STATE.expert_mesh = Mesh(arr.reshape(pp, dp_exp, ep, tp), EXPERT_MESH_AXES)
    _STATE.sizes = dict(pp=pp, dp=dp, cp=cp, tp=tp, ep=ep, dp_exp=dp_exp,
                        world=world)
    if dcn_data_parallel_size and dcn_data_parallel_size > 1:
        # dp crosses DCN in the hybrid layout: every other data axis rides
        # ICI, so hierarchical gradient collectives should stage through
        # them first (comm_compressed.split_axis_hierarchy consumes this).
        fast = tuple(a for a, s in ((CP_AXIS, cp),) if s > 1)
        _STATE.axis_hierarchy = (fast, (DP_AXIS,))
    logger.info("initialized mesh: pp=%d dp=%d cp=%d tp=%d (ep=%d dp_exp=%d)",
                pp, dp, cp, tp, ep, dp_exp)
    return _STATE.mesh


def model_parallel_is_initialized() -> bool:
    """Reference: ``parallel_state.py`` ``model_parallel_is_initialized``."""
    return _STATE.mesh is not None


def destroy_model_parallel() -> None:
    """Reference: ``parallel_state.py:1226``."""
    _STATE.mesh = None
    _STATE.expert_mesh = None
    _STATE.device_array = None
    _STATE.sizes = {}
    _STATE.aot_mode = False
    _STATE.phase_meshes = {}
    _STATE.axis_hierarchy = None


def _require_init() -> None:
    if _STATE.mesh is None:
        raise RuntimeError(
            "model parallel mesh not initialized; call "
            "initialize_model_parallel() first")


def get_mesh() -> Mesh:
    _require_init()
    return _STATE.mesh  # type: ignore[return-value]


def get_expert_mesh() -> Mesh:
    _require_init()
    return _STATE.expert_mesh  # type: ignore[return-value]


def declare_axis_hierarchy(fast: Sequence[str],
                           slow: Sequence[str]) -> None:
    """Declare which mesh axes ride fast links (ICI) vs slow links (DCN).

    Hierarchical collectives (``parallel.comm_compressed``) stage through
    the fast axes first, so only 1/N_fast of the payload crosses the slow
    axes. ``initialize_model_parallel(dcn_data_parallel_size=...)``
    auto-declares ``dp`` slow; call this to override or for custom
    topologies. Axes must be mesh axis names and the two sets disjoint.
    """
    _require_init()
    fast = tuple(fast)
    slow = tuple(slow)
    valid = set(MESH_AXES) | set(EXPERT_MESH_AXES)
    unknown = [a for a in fast + slow if a not in valid]
    if unknown:
        raise ValueError(f"unknown mesh axes in hierarchy: {unknown}; "
                         f"valid axes: {sorted(valid)}")
    overlap = set(fast) & set(slow)
    if overlap:
        raise ValueError(f"axes cannot be both fast and slow: "
                         f"{sorted(overlap)}")
    _STATE.axis_hierarchy = (fast, slow)


def get_axis_hierarchy() -> Optional[Tuple[Tuple[str, ...],
                                           Tuple[str, ...]]]:
    """The declared ``(fast_axes, slow_axes)`` split, or None when
    undeclared (consumers fall back to mesh-axis-order conventions)."""
    return _STATE.axis_hierarchy


def get_moe_phase_mesh(tensor_parallel_size: int,
                       expert_parallel_size: int) -> Mesh:
    """Per-phase (prefill vs decode) TP x EP mesh view.

    Analogue of the reference's prefill/token-gen MoE process groups
    (``moe_process_group.py:12`` — separate CTE and TKG tp x ep groups over
    the same cores): a RESHAPED VIEW of the already-initialised device
    array with axes ``("dp", "ep", "tp")``, cached per (tp, ep). No
    re-initialisation and no manual mesh juggling between phases — serve
    context encoding under ``get_moe_phase_mesh(cte_tp, cte_ep)`` and token
    generation under ``get_moe_phase_mesh(tkg_tp, tkg_ep)`` in the same
    process. Axis names match the global mesh so the parallel layers work
    unchanged inside ``shard_map`` over the view.
    """
    _require_init()
    key = (int(tensor_parallel_size), int(expert_parallel_size))
    if key not in _STATE.phase_meshes:
        tp, ep = key
        world = int(_STATE.sizes["world"])
        if tp < 1 or ep < 1 or world % (tp * ep) != 0:
            raise ValueError(
                f"world size {world} not divisible by phase tp*ep = "
                f"{tp}*{ep}")
        flat = _STATE.device_array.reshape(-1)
        _STATE.phase_meshes[key] = Mesh(
            flat.reshape(world // (tp * ep), ep, tp),
            (DP_AXIS, EP_AXIS, TP_AXIS))
    return _STATE.phase_meshes[key]


def set_aot_mode(flag: bool) -> None:
    """Reference: ``parallel_state.py:1593-1602`` (AOT trace mode for
    inference builds on abstract meshes)."""
    _STATE.aot_mode = flag


def get_aot_mode() -> bool:
    return _STATE.aot_mode


# --------------------------------------------------------------------------
# Size getters (host-side; reference getters at parallel_state.py:826-1684)
# --------------------------------------------------------------------------

def _size(name: str) -> int:
    _require_init()
    return int(_STATE.sizes[name])


def get_tensor_model_parallel_size() -> int:
    return _size("tp")


def get_pipeline_model_parallel_size() -> int:
    return _size("pp")


def get_data_parallel_size() -> int:
    return _size("dp")


def get_context_parallel_size() -> int:
    return _size("cp")


def get_expert_model_parallel_size() -> int:
    return _size("ep")


def get_expert_data_parallel_size() -> int:
    return _size("dp_exp")


def get_world_size() -> int:
    return _size("world")


# --------------------------------------------------------------------------
# In-graph rank getters (traced; only valid under shard_map over the mesh)
# --------------------------------------------------------------------------

# Imported once at module load so JAX private-API drift fails LOUDLY here
# (a silent "axis unbound" fallback would skip every collective and produce
# garbage numerics instead of an error).
try:
    from jax._src.core import get_axis_env as _get_axis_env
    _get_axis_env().axis_exists("_nxd_probe_")
except (ImportError, AttributeError) as _e:  # pragma: no cover
    raise ImportError(
        "neuronx_distributed_tpu requires jax._src.core.get_axis_env with "
        "an axis_exists method (present in jax 0.9.x). This JAX version "
        f"changed the private axis-env API: {_e}") from _e


def _axis_bound(name: str) -> bool:
    return bool(_get_axis_env().axis_exists(name))


def axis_bound(name: str) -> bool:
    """True when ``name`` is a bound (shard_map-mapped) axis in the current
    trace. Used by the collective mappings layer to pick the explicit
    (collective) vs GSPMD (annotation) path."""
    return _axis_bound(name)


def _rank(axis: str):
    if not _axis_bound(axis):
        raise RuntimeError(
            f"get rank of axis {axis!r} requires a shard_map context binding "
            "that axis (SPMD programs have no ambient rank)")
    return jax.lax.axis_index(axis)


def get_tensor_model_parallel_rank():
    return _rank(TP_AXIS)


def get_pipeline_model_parallel_rank():
    return _rank(PP_AXIS)


def get_data_parallel_rank():
    return _rank(DP_AXIS)


def get_context_parallel_rank():
    return _rank(CP_AXIS)


def get_expert_model_parallel_rank():
    return _rank(EP_AXIS)


# --------------------------------------------------------------------------
# Replica groups (host-side; for tests and parity with the reference's
# ``get_*_replica_groups``, parallel_state.py:785-823)
# --------------------------------------------------------------------------

def _device_ids() -> np.ndarray:
    _require_init()
    ids = np.vectorize(lambda d: d.id)(_STATE.device_array)
    return ids  # [pp, dp, cp, tp]


def _groups_over(ids: np.ndarray, axis: int) -> List[List[int]]:
    moved = np.moveaxis(ids, axis, -1)
    return [list(map(int, row)) for row in moved.reshape(-1, moved.shape[-1])]


def get_tensor_model_parallel_replica_groups() -> List[List[int]]:
    return _groups_over(_device_ids(), 3)


def get_data_parallel_replica_groups() -> List[List[int]]:
    return _groups_over(_device_ids(), 1)


def get_pipeline_model_parallel_replica_groups() -> List[List[int]]:
    return _groups_over(_device_ids(), 0)


def get_context_parallel_replica_groups() -> List[List[int]]:
    return _groups_over(_device_ids(), 2)


def get_expert_model_parallel_replica_groups() -> List[List[int]]:
    ids = _device_ids()
    pp, dp, cp, tp = ids.shape
    ep = _size("ep")
    dp_exp = _size("dp_exp")
    resh = ids.reshape(pp, dp_exp, ep, tp)
    return _groups_over(resh, 2)


def get_expert_data_parallel_replica_groups() -> List[List[int]]:
    ids = _device_ids()
    pp, dp, cp, tp = ids.shape
    ep = _size("ep")
    dp_exp = _size("dp_exp")
    resh = ids.reshape(pp, dp_exp, ep, tp)
    return _groups_over(resh, 1)


def get_zero1_sharding_replica_groups() -> List[List[int]]:
    """ZeRO-1 shards optimizer state over merged DP×CP (reference:
    ``parallel_state.py:1684``)."""
    ids = _device_ids()
    pp, dp, cp, tp = ids.shape
    merged = ids.reshape(pp, dp * cp, tp)
    return _groups_over(merged, 1)


def get_context_parallel_ring_pairs() -> List[Tuple[int, int]]:
    """Ring edges (src, tgt) over the cp axis for ring attention, expressed
    as cp-axis indices for ``jax.lax.ppermute`` (reference precomputes device
    src/tgt pairs from CollectivesConfig, ``parallel_state.py:737-742``)."""
    cp = get_context_parallel_size()
    return [(i, (i + 1) % cp) for i in range(cp)]


# --------------------------------------------------------------------------
# Sharding helpers
# --------------------------------------------------------------------------

def named_sharding(*spec: Any) -> NamedSharding:
    """NamedSharding over the global mesh from a PartitionSpec-like tuple."""
    return NamedSharding(get_mesh(), PartitionSpec(*spec))


_EXPERT_ONLY_AXES = frozenset((EP_AXIS, EXP_DP_AXIS))


def spec_uses_expert_axes(spec: PartitionSpec) -> bool:
    """True when a PartitionSpec names an expert-view axis (``ep`` /
    ``dp_exp``) — such specs must be placed on the expert mesh view."""
    for p in spec:
        if p is None:
            continue
        names = p if isinstance(p, tuple) else (p,)
        if any(n in _EXPERT_ONLY_AXES for n in names):
            return True
    return False


def named_sharding_for_spec(spec: PartitionSpec) -> NamedSharding:
    """NamedSharding on the mesh view matching the spec's axis names.

    Expert-view specs (naming ``ep``/``dp_exp``) land on the expert mesh,
    everything else on the dense mesh. Both views are reshapes of the SAME
    flat device order, so their NamedShardings are mutually compatible
    inside one ``jit`` — the TPU analogue of the reference holding dense and
    expert process groups side by side (``parallel_state.py:629``).
    """
    mesh = get_expert_mesh() if spec_uses_expert_axes(spec) else get_mesh()
    return NamedSharding(mesh, spec)


def with_sharding_constraint(x, *spec: Any):
    """``lax.with_sharding_constraint`` against the global mesh; no-op when
    the mesh is uninitialised (single-device eager use)."""
    if _STATE.mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, named_sharding(*spec))


try:
    _SHARD_MAP_IMPL = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:  # jax < 0.6: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _SHARD_MAP_IMPL
    _SHARD_MAP_CHECK_KW = "check_rep"


def shard_map(f, mesh: Optional[Mesh] = None, *, in_specs, out_specs,
              check_vma: bool = False, **kw):
    """``jax.shard_map`` over the global mesh.

    ``check_vma`` defaults to False: TP-style programs routinely all-gather a
    sharded value and treat the result as replicated (e.g. the output of
    ``gather_from_tensor_parallel_region``), which JAX's static
    varying-manual-axes analysis cannot prove replicated. (On pre-0.6 jax
    the same switch is spelled ``check_rep``.)
    """
    if mesh is None:
        mesh = get_mesh()
    kw[_SHARD_MAP_CHECK_KW] = check_vma
    return _SHARD_MAP_IMPL(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)
