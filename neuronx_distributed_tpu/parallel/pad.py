"""Attention-head padding for TP divisibility (inference-only).

Analogue of the reference's ``parallel_layers/pad.py`` (``pad_model:32``,
``get_number_of_extra_heads:14``, ``generate_padding_mask:114``): when a
checkpoint's head count doesn't divide the tp degree, heads are padded with
zero weights so each shard gets an integer number of heads; padded heads are
masked out of the output projection.

Here padding operates on the *param tree* (the functional analogue of the
reference's module rewrite): q/o kernels gain zero head-columns/rows.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


def get_number_of_extra_heads(num_heads: int, tp: int) -> int:
    """Reference ``get_number_of_extra_heads:14``."""
    return (tp - num_heads % tp) % tp


def pad_attention_params(q_kernel, o_kernel, num_heads: int, head_dim: int,
                         tp: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Zero-pad ``q_kernel [.., H, num_heads*hd]`` and ``o_kernel
    [.., num_heads*hd, H]`` to a tp-divisible head count (reference
    ``pad_model:32``). Returns ``(q_padded, o_padded, padded_heads)``."""
    extra = get_number_of_extra_heads(num_heads, tp)
    if extra == 0:
        return np.asarray(q_kernel), np.asarray(o_kernel), num_heads
    q = np.asarray(q_kernel)
    o = np.asarray(o_kernel)
    q_pad = np.zeros((*q.shape[:-1], extra * head_dim), q.dtype)
    o_pad = np.zeros((*o.shape[:-2], extra * head_dim, o.shape[-1]), o.dtype)
    return (np.concatenate([q, q_pad], axis=-1),
            np.concatenate([o, o_pad], axis=-2),
            num_heads + extra)


def generate_padding_mask(num_real_heads: int, num_padded_heads: int,
                          head_dim: int) -> jnp.ndarray:
    """[num_padded*hd] mask, 1 for real-head features (reference
    ``generate_padding_mask:114``)."""
    m = np.zeros((num_padded_heads * head_dim,), np.float32)
    m[:num_real_heads * head_dim] = 1.0
    return jnp.asarray(m)
