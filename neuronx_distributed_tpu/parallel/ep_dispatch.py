"""Expert-parallel token dispatch: overlapped, quantized EP collectives.

Token dispatch is the largest activation collective of the dropless MoE
path: every EP rank gathers every token shard over ``ep`` (the
reference-style no-a2a layout of ``ExpertMLPs._forward_blockwise_ep``) and
reduce-scatters per-rank partial expert outputs back. This module gives
that pair the two treatments the codebase already proved on the TP
collectives (PR 5 / PR 9, :mod:`..ops.collective_matmul`):

* **decomposed rings**: the gather/combine run as ``ppermute`` rings inside
  shard_map, exposing each arriving token chunk as its own array so the
  expert compute for chunk ``t`` overlaps the ``t+1``-th hop through XLA's
  latency-hiding scheduler (no barrier between hops and compute);
* **wire quantization**: dispatch/combine payloads ride the shared
  :mod:`.wire_codec` (int8/fp8 values + per-block fp32 scales, the EQuARX
  recipe) — ~3.9x fewer dispatch bytes at int8's default 256-element
  blocks.

Parity contracts (tested in ``tests/test_moe.py``):

* fp32 wire: the ring is pure payload movement, bitwise identical to the
  monolithic ``all_gather``; the ring combine materializes contributions
  into a source-rank-indexed buffer and sums them with
  :func:`_ordered_sum` — the ascending-rank order ``psum_scatter``
  implements — so the fp32 fallback is bitwise identical to the
  unoverlapped collective;
* quantized wire: every chunk crosses the codec exactly once in either
  impl (``DQ(Q(chunk))``), and both impls sum through the same
  DUS-materialized buffer, so ring == monolithic bitwise for dispatch and
  combine, forward and backward.

The two collectives are exact ``custom_vjp`` duals: the backward of the
chunked gather is the chunked combine of the cotangents (and vice versa),
riding the same wire config — quantized dispatch quantizes its backward
too, which is what keeps the wire ratio symmetric in training.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import comm
from .wire_codec import (CompressionConfig, decode_payload, encode_payload,
                         payload_wire_bytes)

__all__ = ["wire_config", "overlap_engaged", "gather_token_chunks",
           "combine_token_chunks", "MIN_AUTO_AXIS_SIZE"]

#: auto mode (``overlap=None``) engages the ring only at axis sizes where
#: it has enough hops to pipeline (same threshold as the TP rings in
#: ``ops/collective_matmul.py``).
MIN_AUTO_AXIS_SIZE = 4


def wire_config(dtype: Optional[str],
                block_size: int = 256) -> Optional[CompressionConfig]:
    """EP-wire config: None (no compression) for ``None``/``"fp32"``, else
    a hashable :class:`CompressionConfig` safe for ``custom_vjp``
    nondiff_argnums (mirrors ``ops.collective_matmul.wire_config`` — kept
    local so ``parallel`` does not import ``ops``)."""
    if not dtype or dtype == "fp32":
        return None
    return CompressionConfig(dtype=dtype, block_size=int(block_size),
                             hierarchical=False, error_feedback=False)


def _norm_wire(wire: Optional[CompressionConfig]
               ) -> Optional[CompressionConfig]:
    return wire if (wire is not None and wire.quantized) else None


def overlap_engaged(overlap: Optional[bool], axis) -> bool:
    """Layer-level engagement of the decomposed (ring) dispatch.

    ``None`` (auto): on when the axis is bound with size ≥
    ``MIN_AUTO_AXIS_SIZE``; ``True``: on whenever the axis is bound with
    size > 1 (never an error — size-1 axes are identity); ``False``: off.
    """
    if overlap is False:
        return False
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return False
    if overlap is None:
        return n >= MIN_AUTO_AXIS_SIZE
    return True


# ---------------------------------------------------------------------------
# ring plumbing (the decomposed-collective idiom of ops/collective_matmul)
# ---------------------------------------------------------------------------

def _shift_perm(n: int, shift: int):
    """ppermute pairs moving every shard ``shift`` ranks forward."""
    return [(i, (i + shift) % n) for i in range(n)]


def _ship(pair, axis, perm):
    """ppermute a ``(q, scales)`` wire pair one ring step; scales are
    absent (None) on the fp path, which then matches the uncompressed ring
    byte-for-byte."""
    q, s = pair
    q = comm.ppermute(q, axis, perm)
    if s is not None:
        s = comm.ppermute(s, axis, perm)
    return q, s


def _open(pair, wire, dtype):
    q, s = pair
    return decode_payload(q, s, wire, dtype)


def _ordered_sum(buf, n: int):
    """Left-to-right ascending-source-rank summation of a ``[n, ...]``
    contribution buffer (see ``ops.collective_matmul._ordered_sum``: the
    DUS-materialized buffer keeps the dequantization multiply out of the
    accumulation adds, so the sum is bitwise identical whichever program —
    ring or monolithic all-to-all — produced the buffer, and matches
    ``psum_scatter``'s ascending-rank accumulation on the fp32 path)."""
    buf = lax.optimization_barrier(buf)
    acc = buf[0]
    for r in range(1, n):
        acc = acc + buf[r]
    return acc


def _rank(axis):
    return comm.combined_axis_index(axis)


# ---------------------------------------------------------------------------
# gather: token shards -> per-source chunks (hop order)
# ---------------------------------------------------------------------------

def _gather_impl(x, axis, wire, decomposed) -> Tuple[jax.Array, ...]:
    """All-gather ``x`` over ``axis`` as a TUPLE of per-source chunks in
    *hop order*: element ``t`` is rank ``(me + t) % n``'s shard (element 0
    is the caller's own, round-tripped through the codec like every other
    chunk). Exposing chunks as separate arrays — instead of one
    concatenated buffer — is what lets per-chunk consumer compute overlap
    the remaining hops: chunk ``t`` depends on ``t`` ppermutes only."""
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return (x,)
    if decomposed:
        pair = encode_payload(x, wire)
        chunks = [_open(pair, wire, x.dtype)]
        perm = _shift_perm(n, -1)
        for _ in range(1, n):
            pair = _ship(pair, axis, perm)
            chunks.append(_open(pair, wire, x.dtype))
        return tuple(chunks)
    # monolithic: encode once, all-gather the (q, scales) pair, decode per
    # chunk — each chunk is DQ(Q(shard)) exactly as the ring delivers it
    me = _rank(axis)
    q, s = encode_payload(x, wire)
    qg = comm.all_gather(q, axis, dim=0).reshape((n,) + q.shape)
    sg = (comm.all_gather(s, axis, dim=0).reshape((n,) + s.shape)
          if s is not None else None)
    chunks = []
    for t in range(n):
        src = (me + t) % n
        qt = lax.dynamic_index_in_dim(qg, src, 0, keepdims=False)
        st = (lax.dynamic_index_in_dim(sg, src, 0, keepdims=False)
              if sg is not None else None)
        chunks.append(decode_payload(qt, st, wire, x.dtype))
    return tuple(chunks)


# ---------------------------------------------------------------------------
# combine: per-destination partial outputs -> own token shard (reduced)
# ---------------------------------------------------------------------------

def _combine_impl(ys, axis, wire, decomposed) -> jax.Array:
    """Reduce-scatter the per-destination partials ``ys`` (tuple in hop
    order: ``ys[t]`` is this rank's contribution to rank ``(me + t) % n``'s
    tokens) back to the caller's own token shard, summing contributions
    over source ranks in ascending-rank order."""
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return ys[0]
    me = _rank(axis)
    shape = ys[0].shape
    dtype = ys[0].dtype
    buf = jnp.zeros((n,) + shape, dtype)
    zeros = (0,) * len(shape)
    if decomposed:
        for t in range(n):
            pair = encode_payload(ys[t], wire)
            if t:
                # direct delivery: shift +t lands this contribution at its
                # destination in ONE hop (rank me receives, from rank
                # me - t, that rank's chunk destined for me)
                pair = _ship(pair, axis, _shift_perm(n, t))
            contrib = _open(pair, wire, dtype)
            src = ((me - t) % n).astype(jnp.int32)
            buf = lax.dynamic_update_slice(buf, contrib[None],
                                           (src,) + zeros)
        return _ordered_sum(buf, n)
    # monolithic: stack per-destination chunks in destination-rank order,
    # one all-to-all of the encoded pair, then materialize the decoded
    # contributions by source rank and ordered-sum — bitwise the ring
    stacked = jnp.stack(ys)                            # [n, ...] hop order
    dest_order = jnp.roll(stacked, shift=me, axis=0)   # [r] -> chunk for r
    q, s = encode_payload(dest_order, wire)
    qr = comm.all_to_all(q, axis, split_dim=0, concat_dim=0)
    sr = (comm.all_to_all(s, axis, split_dim=0, concat_dim=0)
          if s is not None else None)
    dec = decode_payload(qr, sr, wire, dtype)          # [n, ...] by source
    for r in range(n):
        buf = lax.dynamic_update_slice(buf, dec[r:r + 1], (r,) + zeros)
    return _ordered_sum(buf, n)


# ---------------------------------------------------------------------------
# custom_vjp duals
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _gather_chunks(x, axis, wire, decomposed):
    return _gather_impl(x, axis, wire, decomposed)


def _gather_fwd(x, axis, wire, decomposed):
    return _gather_impl(x, axis, wire, decomposed), None


def _gather_bwd(axis, wire, decomposed, _, dchunks):
    # chunk t came from rank (me + t): its cotangent must return there and
    # sum over all receivers — exactly the chunked combine of the
    # cotangents, over the same wire
    return (_combine_impl(tuple(dchunks), axis, wire, decomposed),)


_gather_chunks.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _combine_chunks(ys, axis, wire, decomposed):
    return _combine_impl(ys, axis, wire, decomposed)


def _combine_fwd(ys, axis, wire, decomposed):
    return _combine_impl(ys, axis, wire, decomposed), None


def _combine_bwd(axis, wire, decomposed, _, dy):
    # ys[t] fed rank (me + t)'s output: its cotangent is that rank's dy —
    # the chunked gather of the cotangents, over the same wire
    return (_gather_impl(dy, axis, wire, decomposed),)


_combine_chunks.defvjp(_combine_fwd, _combine_bwd)


# ---------------------------------------------------------------------------
# public wrappers (+ traced-bytes accounting, public-wrapper-only — the
# custom_vjp internals are traced per-chunk codec calls that would
# double-count)
# ---------------------------------------------------------------------------

def _record_ep_wire(kind: str, shape: Tuple[int, ...],
                    wire: Optional[CompressionConfig],
                    passes: float) -> None:
    from ..obs.accounting import record_wire_bytes
    from ..obs.metrics import get_registry

    if not get_registry().enabled:
        return
    m = 1
    for d in shape:
        m *= int(d)
    wire_b = payload_wire_bytes(shape, wire) * passes
    raw_b = 4.0 * m * passes
    record_wire_bytes(kind, wire.dtype if wire is not None else "fp32",
                      wire_b, raw_b)


def gather_token_chunks(x: jax.Array, axis, *,
                        wire: Optional[CompressionConfig] = None,
                        overlap: bool = False) -> Tuple[jax.Array, ...]:
    """Dispatch side of EP: gather the ``[T, ...]`` token shard over
    ``axis`` as a tuple of per-source chunks in hop order (element ``t`` =
    rank ``(me + t) % n``'s tokens; n==1/unbound → ``(x,)``, untouched).

    ``wire``: :func:`wire_config` result — int8/fp8 quantizes every hop's
    payload. ``overlap=True`` runs the ppermute ring (chunk ``t`` is ready
    after ``t`` hops, so per-chunk expert compute overlaps later hops);
    ``False`` the monolithic gather — bitwise the same chunks either way.
    """
    wire = _norm_wire(wire)
    n = comm._axis_size(axis)
    if n is not None and n > 1:
        _record_ep_wire("ep_dispatch", tuple(x.shape), wire, n - 1)
    return _gather_chunks(x, axis, wire, bool(overlap))


def combine_token_chunks(ys: Tuple[jax.Array, ...], axis, *,
                         wire: Optional[CompressionConfig] = None,
                         overlap: bool = False) -> jax.Array:
    """Combine side of EP: return the per-destination partial outputs
    (``ys[t]`` → rank ``(me + t) % n``) to their token shards, summed over
    source ranks in ascending-rank (``psum_scatter``) order. Dual of
    :func:`gather_token_chunks` (same hop ordering, same wire)."""
    ys = tuple(ys)
    wire = _norm_wire(wire)
    n = comm._axis_size(axis)
    if n is not None and n > 1:
        _record_ep_wire("ep_combine", tuple(ys[0].shape), wire, n - 1)
    return _combine_chunks(ys, axis, wire, bool(overlap))


# -- nxdlint jaxpr-audit entry point ---------------------------------------

from ..analysis.audit_registry import BuiltEntry, register_entry_point


@register_entry_point(
    "ep-dispatch-ring",
    description="quantized EP dispatch ring: gather + combine of token "
                "chunks under shard_map on the expert mesh",
    tags=("train", "serve"),
    wire_dtype="int8",
    in_shardings=(("ep", None),),
)
def _audit_ep_dispatch_ring() -> BuiltEntry:
    """Builder for ``analysis --jaxpr``: the int8-wire dispatch ring on
    a 4-way expert mesh. Every ``ppermute`` hop must ship the encoded
    payload — a full-precision hop is a wire-precision violation. The
    mesh-protocol tier additionally checks the token shard stays
    ep-sharded after propagation and the ring hops cover the axis."""
    from jax.sharding import PartitionSpec as P

    from ..config import neuronx_distributed_config
    from . import mesh as ps

    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    neuronx_distributed_config(expert_parallel_size=4)
    em = ps.get_expert_mesh()
    wire = wire_config("int8")

    def ring(x):
        chunks = gather_token_chunks(x, "ep", wire=wire, overlap=True)
        return combine_token_chunks(chunks, "ep", wire=wire, overlap=True)

    fn = jax.jit(ps.shard_map(ring, em, in_specs=P("ep", None),
                              out_specs=P("ep", None)))
    x = jnp.zeros((4 * 8, 64), jnp.float32)
    return BuiltEntry(fn=fn, args=(x,), mesh=em)
