"""Parallel RNG state management.

Analogue of the reference's ``parallel_layers/random.py``
(``XLARNGStatesTracker:20``, ``model_parallel_xla_manual_seed:100``): TP ranks
need *different* streams for tp-sharded weight init / dropout inside the TP
region, and the *same* stream for replicated init. In JAX this is
``jax.random.fold_in`` of the axis index — functional, no mutable tracker.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax import lax

from . import comm
from . import mesh as ps

# The reference offsets the tp stream by 2718 (random.py:100); we keep the
# constant for checkpoint-reproducibility documentation, not bit-parity.
TENSOR_PARALLEL_SEED_OFFSET = 2718


def fold_in_bound_axes(key: jax.Array,
                       axes: Sequence[str] = (ps.TP_AXIS,)) -> jax.Array:
    """Fold the index of each *bound* axis into ``key`` — shards along those
    axes get decorrelated streams; unbound axes (GSPMD path) leave the key
    unchanged (GSPMD random ops are sharded by XLA itself)."""
    for ax in axes:
        if comm._axis_size(ax):
            key = jax.random.fold_in(key, lax.axis_index(ax))
    return key


def model_parallel_rng(key: jax.Array) -> jax.Array:
    """Stream for tp-region randomness (dropout inside attention/MLP shards):
    differs per tp rank (reference ``get_xla_rng_tracker().fork()``)."""
    key = jax.random.fold_in(key, TENSOR_PARALLEL_SEED_OFFSET)
    return fold_in_bound_axes(key, (ps.TP_AXIS,))


def data_parallel_rng(key: jax.Array) -> jax.Array:
    """Stream differing per dp (and cp) shard — e.g. for data augmentation."""
    return fold_in_bound_axes(key, (ps.DP_AXIS, ps.CP_AXIS))
