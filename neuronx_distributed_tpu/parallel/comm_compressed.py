"""Compressed & hierarchical gradient collectives.

The reference NxD spends most cross-replica bandwidth on full-precision
gradient all-reduces (``bucket_allreduce_gradients``) and ZeRO-1
reduce-scatters; this module provides the quantized / hierarchical
counterparts for the explicit (``shard_map``) path:

* **Blockwise quantized collectives** (EQuARX-style, arxiv 2506.17615):
  ``all_reduce`` / ``reduce_scatter`` / ``all_gather`` quantize the payload
  into int8 or fp8 blocks with a per-block fp32 scale transmitted alongside,
  so a gradient all-reduce moves ~4x (int8) fewer bytes. The all-reduce is
  composed as quantized reduce-scatter (all-to-all exchange of per-rank
  chunks, dequantize, accumulate in fp32) followed by a quantized
  all-gather of the reduced chunks — two compressed passes over the wire
  regardless of group size, the same shape as a ring all-reduce.

* **Error feedback** (1-bit-Adam lineage, kept ZeRO++-compatible): the
  quantization residue of step *t* is carried in the train-step state and
  re-injected into the gradient at step *t+1* before quantizing, so the
  *accumulated* update stays bit-close to fp32 communication even though
  each individual step is lossy. Pass the per-rank ``error`` buffer to a
  collective and it returns ``(result, new_error)``.

* **Hierarchical two-stage composition** (ZeRO++-style, arxiv 2306.10209):
  when the reduce group spans both fast (ICI / intra-slice) and slow
  (DCN / inter-slice) mesh axes, ``all_reduce`` with
  ``hierarchical=True`` reduce-scatters over the fast axes first and only
  then all-reduces the 1/N_fast-size shard over the slow axes — cutting
  slow-link traffic by the fast-group size. The fast/slow split comes from
  :func:`..mesh.get_axis_hierarchy` (auto-declared for
  ``dcn_data_parallel_size`` meshes) and otherwise defaults to
  "major-most bound axis is slow" per the mesh's ``[pp, dp, cp, tp]``
  major-to-minor ordering.

Everything here runs *inside* ``shard_map`` over named mesh axes (the same
contract as :mod:`.comm`); every collective is a no-op when its axis is
unbound or size 1, so the same code runs on a 1-device CPU mesh. These are
non-differentiated primal-path collectives (gradient synchronisation), not
``custom_vjp`` mappings — never place them on a path you differentiate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from . import comm
from . import mesh as ps
# The quantizer/scale-layout machinery lives in the shared wire codec
# (used by both these gradient collectives and the activation rings in
# ops/collective_matmul.py); re-exported here so the PR 3 public API —
# CompressionConfig, quantize_blockwise, wire_bytes_per_element, … — keeps
# importing from this module.
from .wire_codec import (  # noqa: F401  (re-exports)
    _QMAX, _WIRE_DTYPES, CompressionConfig, _dequantize, _quantize,
    dequantize_blockwise, quantize_blockwise, quantize_dequantize,
    wire_bytes_per_element)

Axis = Union[str, Sequence[str]]


def from_config(cfg: Any) -> Optional[CompressionConfig]:
    """Build a :class:`CompressionConfig` from an ``NxDConfig`` (its
    ``optimizer.grad_comm_*`` fields); None when gradient communication is
    plain fp32 flat (nothing to do)."""
    oc = cfg.optimizer
    dtype = getattr(oc, "grad_comm_dtype", "fp32")
    hier = bool(getattr(oc, "grad_comm_hierarchical", False))
    if dtype == "fp32" and not hier:
        return None
    return CompressionConfig(
        dtype=dtype,
        block_size=int(getattr(oc, "grad_comm_block_size", 256)),
        hierarchical=hier,
        error_feedback=bool(getattr(oc, "grad_comm_error_feedback", True)))


# --------------------------------------------------------------------------
# Flat chunk layout shared by the collectives
# --------------------------------------------------------------------------

def _chunk_blocks(x: jax.Array, n: int,
                  block: int) -> Tuple[jax.Array, int]:
    """Flatten + zero-pad ``x`` to ``[n, cb, block]``: ``n`` equal per-rank
    chunks of whole blocks (blocks never straddle a chunk boundary).
    Returns ``(blocks, n_elements)``."""
    flat = x.astype(jnp.float32).reshape(-1)
    m = flat.shape[0]
    per = n * block
    cb = max(1, -(-m // per))
    pad = n * cb * block - m
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat.reshape(n, cb, block), m


def _axis_arg(names: Tuple[str, ...]) -> Axis:
    return names if len(names) > 1 else names[0]


def _numel(x: jax.Array) -> int:
    m = 1
    for d in jnp.shape(x):
        m *= int(d)
    return m


def _record_wire(kind: str, n_elements: int,
                 cfg: Optional[CompressionConfig], passes: float) -> None:
    """Traced-bytes accounting for one logical collective.

    Runs in the *public wrapper* — host code executed at trace time, so
    there is never a callback inside the compiled program and the jit
    cache is untouched. Shapes are static here, so the byte figures are
    exact per trace; see ``obs.accounting`` for the traced-bytes
    semantics (counted once per compile, ratio invariant to run count).
    """
    from ..obs.accounting import record_wire_bytes
    from ..obs.metrics import get_registry

    if not get_registry().enabled:
        return
    from .wire_codec import blockwise_wire_bytes

    wire = blockwise_wire_bytes(n_elements, cfg) * passes
    raw = 4.0 * n_elements * passes
    record_wire_bytes(kind, cfg.dtype if cfg is not None else "fp32",
                      wire, raw)


def _exchange_reduce(q: jax.Array, s: Optional[jax.Array], ax: Axis,
                     dtype: str) -> jax.Array:
    """Quantized reduce-scatter core: all-to-all the per-destination chunks
    (+ scales), dequantize each source's contribution, accumulate in fp32.
    ``q``: ``[n, cb, block]`` — chunk ``r`` is destined for rank ``r``.
    Returns this rank's fp32 reduced chunk ``[cb, block]``."""
    qr = lax.all_to_all(q, ax, split_axis=0, concat_axis=0, tiled=True)
    sr = None
    if s is not None:
        sr = lax.all_to_all(s, ax, split_axis=0, concat_axis=0, tiled=True)
    return jnp.sum(_dequantize(qr, sr, dtype), axis=0)


def _gather_chunks(q: jax.Array, s: Optional[jax.Array], ax: Axis,
                   dtype: str) -> jax.Array:
    """Quantized all-gather core: gather every rank's ``[cb, block]`` chunk
    (+ scales) in rank order and dequantize → ``[n*cb, block]`` fp32."""
    qg = lax.all_gather(q, ax, axis=0, tiled=True)
    sg = None
    if s is not None:
        sg = lax.all_gather(s, ax, axis=0, tiled=True)
    return _dequantize(qg, sg, dtype)


def _unflatten(full: jax.Array, m: int, like: jax.Array) -> jax.Array:
    return full.reshape(-1)[:m].reshape(jnp.shape(like)).astype(like.dtype)


# --------------------------------------------------------------------------
# Hierarchy resolution
# --------------------------------------------------------------------------

def _mesh_axis_rank(name: str) -> int:
    order = ps.MESH_AXES + (ps.EXP_DP_AXIS, ps.EP_AXIS)
    return order.index(name) if name in order else len(order)

def split_axis_hierarchy(names: Sequence[str]
                         ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """Split bound reduce axes into ``(fast, slow)`` stages.

    A hierarchy declared on the mesh (:func:`..mesh.declare_axis_hierarchy`)
    wins; otherwise the convention is that the mesh's axis order
    ``[pp, dp, cp, tp]`` runs major (slow, e.g. DCN-crossing dp) to minor
    (fast ICI rings), so the major-most bound axis becomes the slow stage
    and the rest the fast stage. Either side may come back empty (→ the
    caller falls back to a flat collective)."""
    decl = ps.get_axis_hierarchy()
    if decl is not None:
        fast_decl, slow_decl = decl
        fast = tuple(a for a in names if a in fast_decl)
        slow = tuple(a for a in names if a not in fast_decl)
        return fast, slow
    if len(names) < 2:
        return (), tuple(names)
    ordered = sorted(names, key=_mesh_axis_rank)
    return tuple(ordered[1:]), (ordered[0],)


# --------------------------------------------------------------------------
# Collectives
# --------------------------------------------------------------------------

def all_reduce(x: jax.Array, axis: Axis = (ps.DP_AXIS, ps.CP_AXIS),
               config: Optional[CompressionConfig] = None,
               op: str = "mean",
               error: Optional[jax.Array] = None):
    """Compressed (and optionally hierarchical) all-reduce over ``axis``.

    Returns the reduced array — or ``(reduced, new_error)`` when an
    ``error`` feedback buffer is passed (the residue to re-inject next
    step; zeros for fp32 configs). ``op`` is ``"mean"`` or ``"sum"``.
    """
    if op not in ("mean", "sum"):
        raise ValueError(f"op must be 'mean' or 'sum', got {op!r}")
    cfg = config if config is not None else CompressionConfig(dtype="fp32")
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    if not names or n is None or n == 1:
        return (x, error) if error is not None else x
    # RS + AG composition: two compressed passes over the wire. The
    # hierarchical path recurses through this public wrapper for its
    # slow stage, so the shard-sized stage-2 traffic accounts itself.
    _record_wire("grad_all_reduce", _numel(x), cfg, passes=2)

    if cfg.hierarchical:
        fast, slow = split_axis_hierarchy(names)
        if fast and slow:
            return _two_stage_all_reduce(x, fast, slow, cfg, op, error)

    if not cfg.quantized:
        ax = _axis_arg(names)
        y = lax.pmean(x, ax) if op == "mean" else lax.psum(x, ax)
        if error is not None:
            return y, jnp.zeros_like(error)
        return y
    return _flat_quantized_all_reduce(x, names, n, cfg, op, error)


def _stage1_quantize(x, error, n, cfg):
    """Shared sender-side stage: inject error feedback, chunk, quantize,
    and compute the new residue. Returns ``(q, s, m, new_error)``."""
    g = x if error is None else (x + error.astype(x.dtype))
    blocks, m = _chunk_blocks(g, n, cfg.block_size)
    q, s = _quantize(blocks, cfg.dtype)
    new_error = None
    if error is not None:
        if cfg.quantized:
            dec = _dequantize(q, s, cfg.dtype)
            new_error = _unflatten(blocks - dec, m, error)
        else:
            new_error = jnp.zeros_like(error)
    return q, s, m, new_error


def _flat_quantized_all_reduce(x, names, n, cfg, op, error):
    ax = _axis_arg(names)
    q, s, m, new_error = _stage1_quantize(x, error, n, cfg)
    chunk = _exchange_reduce(q, s, ax, cfg.dtype)
    if op == "mean":
        chunk = chunk / n
    q2, s2 = _quantize(chunk, cfg.dtype)
    full = _gather_chunks(q2, s2, ax, cfg.dtype)
    y = _unflatten(full, m, x)
    return (y, new_error) if error is not None else y


def _two_stage_all_reduce(x, fast, slow, cfg, op, error):
    """ZeRO++-style composition: reduce-scatter over the fast axes, reduce
    the 1/N_fast shard over the slow axes, all-gather back over the fast
    axes. Slow-axis traffic shrinks by N_fast on top of quantization."""
    n_fast = comm._axis_size(fast)
    n_slow = comm._axis_size(slow)
    af = _axis_arg(tuple(fast))
    q, s, m, new_error = _stage1_quantize(x, error, n_fast, cfg)
    chunk = _exchange_reduce(q, s, af, cfg.dtype)
    # stage 2 on the shard: compressed flat all-reduce over the slow axes.
    # Its own requantization error lives only on the chunk owner and is
    # deliberately NOT error-fed-back (ZeRO++ does the same); stage 1
    # carries the dominant residue.
    chunk = all_reduce(chunk, tuple(slow), config=dataclasses.replace(
        cfg, hierarchical=False), op="sum")
    if op == "mean":
        chunk = chunk / (n_fast * n_slow)
    q2, s2 = _quantize(chunk, cfg.dtype)
    full = _gather_chunks(q2, s2, af, cfg.dtype)
    y = _unflatten(full, m, x)
    return (y, new_error) if error is not None else y


def reduce_scatter_flat(x: jax.Array, axis: Axis,
                        config: Optional[CompressionConfig] = None,
                        op: str = "mean",
                        error: Optional[jax.Array] = None):
    """Reduce ``x`` over ``axis`` and keep this rank's flat chunk (the
    ZeRO-1 gradient dataflow: rank ``r`` owns chunk ``r`` of the flattened
    leaf, zero-padded to whole blocks). Returns the 1-D chunk, or
    ``(chunk, new_error)`` with error feedback. Group size 1 → the whole
    (flattened, unpadded) tensor."""
    if op not in ("mean", "sum"):
        raise ValueError(f"op must be 'mean' or 'sum', got {op!r}")
    cfg = config if config is not None else CompressionConfig(dtype="fp32")
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    if not names or n is None or n == 1:
        y = x.reshape(-1)
        return (y, error) if error is not None else y
    _record_wire("grad_reduce_scatter", _numel(x), cfg, passes=1)
    ax = _axis_arg(names)
    q, s, m, new_error = _stage1_quantize(x, error, n, cfg)
    chunk = _exchange_reduce(q, s, ax, cfg.dtype)
    if op == "mean":
        chunk = chunk / n
    chunk = chunk.reshape(-1)
    return (chunk, new_error) if error is not None else chunk


def all_gather_flat(chunk: jax.Array, shape: Sequence[int], axis: Axis,
                    config: Optional[CompressionConfig] = None) -> jax.Array:
    """Inverse of :func:`reduce_scatter_flat`: gather every rank's flat
    chunk over ``axis`` (quantizing the payload per ``config``), trim the
    block padding and reshape to ``shape``."""
    cfg = config if config is not None else CompressionConfig(dtype="fp32")
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    m = 1
    for d in shape:
        m *= int(d)
    if not names or n is None or n == 1:
        return chunk.reshape(-1)[:m].reshape(tuple(shape))
    _record_wire("grad_all_gather", m, cfg, passes=1)
    ax = _axis_arg(names)
    b = cfg.block_size
    flat = chunk.astype(jnp.float32).reshape(-1)
    cb = flat.shape[0] // b
    if cb * b != flat.shape[0]:
        # chunk not produced by reduce_scatter_flat: pad to whole blocks
        cb += 1
        flat = jnp.concatenate(
            [flat, jnp.zeros((cb * b - flat.shape[0],), jnp.float32)])
    q, s = _quantize(flat.reshape(cb, b), cfg.dtype)
    full = _gather_chunks(q, s, ax, cfg.dtype)
    return full.reshape(-1)[:m].reshape(tuple(shape)).astype(chunk.dtype)


def reduce_scatter(x: jax.Array, axis: Axis, dim: int = 0,
                   config: Optional[CompressionConfig] = None,
                   op: str = "sum",
                   error: Optional[jax.Array] = None):
    """Dim-scattering compressed reduce-scatter (the :func:`..comm.
    reduce_scatter` shape contract: ``x.shape[dim]`` must divide by the
    group size; this rank keeps slice ``r``). Returns the chunk, or
    ``(chunk, new_error)`` with error feedback."""
    if op not in ("mean", "sum"):
        raise ValueError(f"op must be 'mean' or 'sum', got {op!r}")
    cfg = config if config is not None else CompressionConfig(dtype="fp32")
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    if not names or n is None or n == 1:
        return (x, error) if error is not None else x
    _record_wire("grad_reduce_scatter", _numel(x), cfg, passes=1)
    ax = _axis_arg(names)
    dim = dim % x.ndim
    if x.shape[dim] % n != 0:
        raise ValueError(
            f"dim {dim} size {x.shape[dim]} not divisible by reduce group "
            f"size {n} over axis {names}")
    if not cfg.quantized:
        y = lax.psum_scatter(x, ax, scatter_dimension=dim, tiled=True)
        if op == "mean":
            y = y / n
        if error is not None:
            return y, jnp.zeros_like(error)
        return y
    lead = jnp.moveaxis(x, dim, 0)
    chunk_shape = (lead.shape[0] // n,) + lead.shape[1:]
    per = jnp.reshape(lead, (n, -1))  # [n, chunk_elems]
    ce = per.shape[1]
    b = cfg.block_size
    cb = max(1, -(-ce // b))
    pad = cb * b - ce
    g = per if error is None else per + jnp.reshape(
        jnp.moveaxis(error, dim, 0), (n, -1)).astype(per.dtype)
    gf = g.astype(jnp.float32)
    if pad:
        gf = jnp.concatenate(
            [gf, jnp.zeros((n, pad), jnp.float32)], axis=1)
    blocks = gf.reshape(n, cb, b)
    q, s = _quantize(blocks, cfg.dtype)
    new_error = None
    if error is not None:
        dec = _dequantize(q, s, cfg.dtype).reshape(n, -1)[:, :ce]
        ne = (gf.reshape(n, -1)[:, :ce] - dec).reshape(lead.shape)
        new_error = jnp.moveaxis(ne, 0, dim).astype(error.dtype)
    red = _exchange_reduce(q, s, ax, cfg.dtype)  # [cb, b]
    if op == "mean":
        red = red / n
    y = red.reshape(-1)[:ce].reshape(chunk_shape)
    y = jnp.moveaxis(y, 0, dim).astype(x.dtype)
    return (y, new_error) if error is not None else y


def all_gather(x: jax.Array, axis: Axis, dim: int = 0,
               config: Optional[CompressionConfig] = None) -> jax.Array:
    """Compressed all-gather concatenating every rank's ``x`` along
    ``dim`` (the :func:`..comm.all_gather` contract with a quantized
    payload)."""
    cfg = config if config is not None else CompressionConfig(dtype="fp32")
    names = comm._bound_names(axis)
    n = comm._axis_size(axis)
    if not names or n is None or n == 1:
        return x
    _record_wire("grad_all_gather", n * _numel(x), cfg, passes=1)
    ax = _axis_arg(names)
    dim = dim % x.ndim
    if not cfg.quantized:
        return lax.all_gather(x, ax, axis=dim, tiled=True)
    q, s, m = quantize_blockwise(x, cfg)
    qg = lax.all_gather(q, ax, axis=0, tiled=False)   # [n, nb, b]
    sg = lax.all_gather(s, ax, axis=0, tiled=False)
    dq = _dequantize(qg, sg, cfg.dtype).reshape(n, -1)[:, :m]
    per = dq.reshape((n,) + tuple(x.shape))
    stacked = jnp.moveaxis(per, 0, dim)  # [..., n, dim_size, ...]
    out_shape = x.shape[:dim] + (n * x.shape[dim],) + x.shape[dim + 1:]
    return stacked.reshape(out_shape).astype(x.dtype)


# --------------------------------------------------------------------------
# Error-feedback buffers (per reduce-group-rank residue, carried in the
# train-step state; see docs/comm_compression.md)
# --------------------------------------------------------------------------

def _spec_axes(spec) -> set:
    axes = set()
    if isinstance(spec, PartitionSpec):
        for p in spec:
            if p is None:
                continue
            if isinstance(p, tuple):
                axes.update(p)
            else:
                axes.add(p)
    return axes


def _mesh_sizes() -> dict:
    if not ps.model_parallel_is_initialized():
        return {}
    return dict(ps.get_mesh().shape)


def leaf_reduce_axes(spec, axes: Sequence[str] = (ps.DP_AXIS, ps.CP_AXIS)
                     ) -> Tuple[str, ...]:
    """The subset of ``axes`` a leaf with PartitionSpec ``spec`` is actually
    reduced over: mesh axes of size > 1 not already sharding the leaf
    (FSDP-style leaves skip their own axis, mirroring
    ``grads.allreduce_gradients``)."""
    sizes = _mesh_sizes()
    mentioned = _spec_axes(spec)
    return tuple(ax for ax in axes
                 if sizes.get(ax, 1) > 1 and ax not in mentioned)


def error_feedback_spec(spec: PartitionSpec,
                        axes: Sequence[str] = (ps.DP_AXIS, ps.CP_AXIS)
                        ) -> PartitionSpec:
    """PartitionSpec of a leaf's error-feedback buffer.

    The residue is *per reduce-group rank* (each rank quantizes a different
    shard of the data), so the buffer gains a leading dim of size
    ``prod(reduce axes)`` sharded over exactly those axes — each device
    holds only its own ``[1, ...]`` residue slice, and a checkpoint holds
    every rank's (preemption-safe, see docs/resilience.md)."""
    red = leaf_reduce_axes(spec, axes)
    lead = red if len(red) > 1 else (red[0] if red else None)
    return PartitionSpec(lead, *spec)


def error_feedback_specs(param_specs: Any,
                         axes: Sequence[str] = (ps.DP_AXIS, ps.CP_AXIS)
                         ) -> Any:
    """Spec tree for :func:`init_error_feedback` buffers."""
    return jax.tree_util.tree_map(
        lambda s: error_feedback_spec(s, axes), param_specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec))


def init_error_feedback(params: Any, param_specs: Any,
                        axes: Sequence[str] = (ps.DP_AXIS, ps.CP_AXIS)
                        ) -> Any:
    """Zero residue buffers, one leading reduce-rank dim per leaf. The
    caller places them (``named_sharding_for_spec`` over
    :func:`error_feedback_specs`)."""
    sizes = _mesh_sizes()

    def zero(p, spec):
        red = leaf_reduce_axes(spec, axes)
        lead = 1
        for ax in red:
            lead *= sizes.get(ax, 1)
        return jnp.zeros((lead,) + tuple(jnp.shape(p)), jnp.float32)

    return jax.tree_util.tree_map(
        zero, params, param_specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec))
