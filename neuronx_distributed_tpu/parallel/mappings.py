"""Autograd-aware collective mappings.

Analogue of the reference's ``parallel_layers/mappings.py`` — the
forward/backward collective *pairs* that make tensor parallelism differentiable
(``mappings.py:175-353``):

====================================  ============  =================
mapping                               forward       backward
====================================  ============  =================
copy_to_tensor_parallel_region        identity      all-reduce
reduce_from_tensor_parallel_region    all-reduce    identity
scatter_to_tensor_parallel_region     split         all-gather
gather_from_tensor_parallel_region    all-gather    split
scatter_to_sequence_parallel_region   split(seq)    all-gather(seq)
gather_from_sequence_parallel_region  all-gather    reduce-scatter/split
reduce_scatter_to_seq_parallel_region reduce-scat.  all-gather
enter/exit_expert_parallel_region     all-to-all    all-to-all (inverse)
====================================  ============  =================

Implemented as ``jax.custom_vjp`` functions over the named-axis collectives in
:mod:`.comm`. When the axis is *not bound* (i.e. running under plain ``jit``
with GSPMD sharding constraints rather than ``shard_map``), every mapping is
an identity — GSPMD derives the collectives from sharding annotations instead.

.. warning:: **Compute gradients INSIDE the shard_map region** (the
   ``grad_fn`` convention in ``trainer.make_train_step``), or via GSPMD.
   Differentiating *through* a ``check_vma=False`` shard_map boundary from
   outside silently deflates the cotangents of axis-sharded inputs (e.g. TP-
   sharded weights) by ``1/axis_size``: the boundary splits a replicated
   output's cotangent evenly across ranks, and while replicated inputs
   recover the full gradient (boundary sum composed with the ``copy_to``
   psum), sharded-param cotangents cross no compensating collective.
   Measured: weight grads exactly ``1/tp`` under ``jax.grad`` outside a
   shard_map-wrapped MoE at tp=2/4 (x and gate grads exact).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from . import comm
from . import mesh as ps


def _check_seq_divisible(x, axis: str, seq_dim: int, op: str) -> None:
    """Pointed shape validation for the sequence-parallel reduce-scatters.

    ``psum_scatter`` requires the scattered dim to tile evenly over the
    axis; without this check a non-divisible sequence length surfaces as an
    opaque XLA shape error from deep inside the compiled program.
    """
    n = comm._axis_size(axis)
    if n is None or n <= 1:
        return
    d = seq_dim % x.ndim
    if x.shape[d] % n != 0:
        raise ValueError(
            f"{op}: sequence length {x.shape[d]} (dim {seq_dim}) does not "
            f"divide evenly over mesh axis {axis!r} of size {n}; pad or "
            f"trim the sequence to a multiple of {n}")


# ---------------------------------------------------------------------------
# copy / reduce (reference: _CopyToModelParallelRegion mappings.py:175,
# _ReduceFromModelParallelRegion mappings.py:196)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_parallel_region(x, axis: str = ps.TP_AXIS):
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, g):
    return (comm.all_reduce(g, axis),)


copy_to_tensor_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_parallel_region(x, axis: str = ps.TP_AXIS):
    return comm.all_reduce(x, axis)


def _reduce_fwd(x, axis):
    return comm.all_reduce(x, axis), None


def _reduce_bwd(axis, _, g):
    return (g,)


reduce_from_tensor_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# ---------------------------------------------------------------------------
# scatter / gather along an arbitrary dim (reference: _ScatterToModelParallel-
# Region mappings.py:214, _GatherFromModelParallelRegion mappings.py:235)
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_tensor_parallel_region(x, axis: str = ps.TP_AXIS, dim: int = -1):
    return comm.split_along_dim(x, axis, dim)


def _scatter_fwd(x, axis, dim):
    return comm.split_along_dim(x, axis, dim), None


def _scatter_bwd(axis, dim, _, g):
    return (comm.all_gather(g, axis, dim),)


scatter_to_tensor_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_tensor_parallel_region(x, axis: str = ps.TP_AXIS, dim: int = -1):
    return comm.all_gather(x, axis, dim)


def _gather_fwd(x, axis, dim):
    return comm.all_gather(x, axis, dim), None


def _gather_bwd(axis, dim, _, g):
    return (comm.split_along_dim(g, axis, dim),)


gather_from_tensor_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ---------------------------------------------------------------------------
# Sequence-parallel region (reference: mappings.py:256-353). Sequence dim is
# 0 in the reference ([S, B, H] layout); we default to dim 1 for [B, S, H]
# and let callers override.
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(x, axis: str = ps.TP_AXIS, seq_dim: int = 1):
    return comm.split_along_dim(x, axis, seq_dim)


def _sp_scatter_fwd(x, axis, seq_dim):
    return comm.split_along_dim(x, axis, seq_dim), None


def _sp_scatter_bwd(axis, seq_dim, _, g):
    return (comm.all_gather(g, axis, seq_dim),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(
        x, axis: str = ps.TP_AXIS, seq_dim: int = 1,
        to_model_parallel: bool = True):
    """Forward all-gather along the sequence dim.

    ``to_model_parallel=True`` (entering a TP block, reference
    ``mappings.py:280``): backward is reduce-scatter — gradient contributions
    from all TP ranks are summed then re-sharded.
    ``to_model_parallel=False``: backward is a plain split.
    """
    return comm.all_gather(x, axis, seq_dim)


def _sp_gather_fwd(x, axis, seq_dim, to_model_parallel):
    return comm.all_gather(x, axis, seq_dim), None


def _sp_gather_bwd(axis, seq_dim, to_model_parallel, _, g):
    if to_model_parallel:
        # g normally has the gathered length (axis_size * local), but a
        # consumer that reshaped/truncated the sequence hands back a
        # cotangent psum_scatter can't re-shard — fail with names attached
        _check_seq_divisible(
            g, axis, seq_dim,
            "gather_from_sequence_parallel_region (backward reduce-scatter)")
        return (comm.reduce_scatter(g, axis, seq_dim),)
    return (comm.split_along_dim(g, axis, seq_dim),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis: str = ps.TP_AXIS,
                                               seq_dim: int = 1):
    """Exit a TP block into the SP region (reference ``mappings.py:322``)."""
    _check_seq_divisible(x, axis, seq_dim,
                         "reduce_scatter_to_sequence_parallel_region")
    return comm.reduce_scatter(x, axis, seq_dim)


def _sp_rs_fwd(x, axis, seq_dim):
    # the primal body above is skipped when differentiated — validate here too
    _check_seq_divisible(x, axis, seq_dim,
                         "reduce_scatter_to_sequence_parallel_region")
    return comm.reduce_scatter(x, axis, seq_dim), None


def _sp_rs_bwd(axis, seq_dim, _, g):
    return (comm.all_gather(g, axis, seq_dim),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)


# ---------------------------------------------------------------------------
# Expert-parallel region: all-to-all token dispatch (reference:
# _EnterExpertParallelRegion mappings.py:355,481; exit :521). Forward
# all-to-all splitting the expert dim and concatenating tokens; backward is
# the inverse all-to-all. lax.all_to_all differentiates correctly on its own,
# but we keep explicit custom_vjp for parity and to pin the collective pair.
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def enter_expert_parallel_region(x, axis: str = ps.EP_AXIS,
                                 split_dim: int = 0, concat_dim: int = 1):
    return comm.all_to_all(x, axis, split_dim, concat_dim)


def _ep_enter_fwd(x, axis, split_dim, concat_dim):
    return comm.all_to_all(x, axis, split_dim, concat_dim), None


def _ep_enter_bwd(axis, split_dim, concat_dim, _, g):
    return (comm.all_to_all(g, axis, concat_dim, split_dim),)


enter_expert_parallel_region.defvjp(_ep_enter_fwd, _ep_enter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def exit_expert_parallel_region(x, axis: str = ps.EP_AXIS,
                                split_dim: int = 1, concat_dim: int = 0):
    return comm.all_to_all(x, axis, split_dim, concat_dim)


def _ep_exit_fwd(x, axis, split_dim, concat_dim):
    return comm.all_to_all(x, axis, split_dim, concat_dim), None


def _ep_exit_bwd(axis, split_dim, concat_dim, _, g):
    return (comm.all_to_all(g, axis, concat_dim, split_dim),)


exit_expert_parallel_region.defvjp(_ep_exit_fwd, _ep_exit_bwd)
