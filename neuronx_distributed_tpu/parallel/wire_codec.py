"""Shared wire codec for compressed collectives.

One quantizer for every byte this repo puts on the wire: the gradient
collectives in :mod:`.comm_compressed` (PR 3) and the activation rings in
:mod:`..ops.collective_matmul` both ship blockwise-quantized payloads with
exactly this scale layout, so the placement planner's cost model can charge
both with the same :func:`wire_bytes_per_element` arithmetic.

* **Blockwise symmetric quantization** (EQuARX-style, arxiv 2506.17615):
  a payload is flattened into ``block_size``-element blocks, each block
  transmitted as int8 (or float8_e4m3fn) values plus one fp32 scale
  ``amax / qmax``. All-zero blocks get scale 1.0 so their round-trip is
  exact. int8 at the default 256-element blocks moves
  ``1 + 4/256 ≈ 1.016`` bytes per element — a ~3.94x wire reduction.

* **Scale layout**: scales ride *alongside* the quantized values with the
  same leading block structure (``q: [..., nb, b]``, ``scales:
  [..., nb, 1]``), so a collective ships both through the identical
  permute/gather pattern and the receiver dequantizes positionally.

Everything here is pure array math — no mesh axes, no collectives — and
safe to call inside or outside ``shard_map``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

#: Largest representable magnitude of each wire dtype (int8 symmetric;
#: float8_e4m3fn max finite = 448).
_QMAX = {"int8": 127.0, "fp8": 448.0}

_WIRE_DTYPES = ("fp32", "int8", "fp8")


def wire_bytes_per_element(dtype: str, block_size: int = 256) -> float:
    """Static wire accounting for one payload element at ``dtype``:
    1 quantized byte + one fp32 scale per block, 4 bytes unquantized.
    Module-level and pure so the placement planner's cost model
    (``plan/cost.py``) charges compressed collectives — gradient *and*
    activation — with the exact arithmetic the codec implements instead
    of duplicating it. Single source of truth:
    :attr:`CompressionConfig.wire_bytes_per_element` delegates here."""
    if dtype not in _WIRE_DTYPES:
        raise ValueError(f"unknown wire dtype {dtype!r}")
    if dtype == "fp32":
        return 4.0
    return 1.0 + 4.0 / block_size


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """How a compressed collective moves bytes.

    ``dtype``: wire dtype — ``"fp32"`` (no quantization), ``"int8"``
    (blockwise symmetric int8) or ``"fp8"`` (float8_e4m3fn).
    ``block_size``: elements per quantization block (one fp32 scale each).
    ``hierarchical``: two-stage fast-axes-then-slow-axes composition
    (gradient collectives only; ignored by the activation rings).
    ``error_feedback``: carry the quantization residue across steps
    (consumed by the trainer; the collectives themselves only use it when
    an ``error`` buffer is actually passed).

    Frozen and hashable, so instances can ride through
    ``jax.custom_vjp`` ``nondiff_argnums`` and jit static arguments
    without triggering recompiles across identical configs.
    """

    dtype: str = "int8"
    block_size: int = 256
    hierarchical: bool = False
    error_feedback: bool = True

    def __post_init__(self) -> None:
        if self.dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"wire dtype must be one of {_WIRE_DTYPES}, got "
                f"{self.dtype!r}")
        if not isinstance(self.block_size, int) or self.block_size < 1:
            raise ValueError(
                f"block_size must be a positive int, got {self.block_size!r}")

    @property
    def quantized(self) -> bool:
        return self.dtype != "fp32"

    @property
    def wire_bytes_per_element(self) -> float:
        """Payload bytes per element including the per-block scales
        (1 fp32 scale per ``block_size`` elements)."""
        return wire_bytes_per_element(self.dtype, self.block_size)

    @property
    def ratio(self) -> float:
        """Wire-compression ratio vs fp32 (same collective shape)."""
        return 4.0 / self.wire_bytes_per_element


# --------------------------------------------------------------------------
# Blockwise quantization
# --------------------------------------------------------------------------

def _quantize(x: jax.Array, dtype: str) -> Tuple[jax.Array,
                                                 Optional[jax.Array]]:
    """Quantize ``x`` (f32, blocks along the last dim) → ``(q, scales)``;
    identity ``(x, None)`` for fp32."""
    if dtype == "fp32":
        return x, None
    qmax = _QMAX[dtype]
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    # all-zero blocks get scale 1.0: q is exactly 0, dequant exact
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    y = x / scale
    if dtype == "int8":
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        q = y.astype(jnp.float8_e4m3fn)
    return q, scale


def _dequantize(q: jax.Array, scale: Optional[jax.Array],
                dtype: str) -> jax.Array:
    if dtype == "fp32":
        return q
    return q.astype(jnp.float32) * scale


def quantize_blockwise(x: jax.Array, config: CompressionConfig
                       ) -> Tuple[jax.Array, Optional[jax.Array], int]:
    """Flatten + zero-pad ``x`` into ``[n_blocks, block_size]`` and quantize.
    Returns ``(q, scales, n_elements)``; for fp32 configs ``q`` is the
    padded f32 blocks and ``scales`` is None."""
    flat = x.astype(jnp.float32).reshape(-1)
    m = flat.shape[0]
    b = config.block_size
    nb = max(1, -(-m // b))
    pad = nb * b - m
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, s = _quantize(flat.reshape(nb, b), config.dtype)
    return q, s, m


def dequantize_blockwise(q: jax.Array, scales: Optional[jax.Array],
                         shape: Sequence[int],
                         config: CompressionConfig) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (drops the padding)."""
    flat = _dequantize(q, scales, config.dtype).reshape(-1)
    n = 1
    for d in shape:
        n *= int(d)
    return flat[:n].reshape(tuple(shape))


def quantize_dequantize(x: jax.Array,
                        config: CompressionConfig) -> jax.Array:
    """The round-trip operator ``DQ(Q(x))`` — what the receiving side of a
    compressed collective reconstructs from this rank's payload."""
    if not config.quantized:
        return x
    q, s, _ = quantize_blockwise(x, config)
    return dequantize_blockwise(q, s, jnp.shape(x), config).astype(x.dtype)


# --------------------------------------------------------------------------
# Ring-payload codec (fixed tensor layout, no flattening)
# --------------------------------------------------------------------------

def encode_payload(x: jax.Array, config: Optional[CompressionConfig]
                   ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Quantize a ring/collective payload *in place* (no flatten, no pad):
    the trailing dim is split into whole ``block_size`` blocks when it
    divides evenly, else the whole trailing dim becomes one block. Returns
    ``(q, scales)`` with ``scales`` broadcastable against the blocked view;
    identity ``(x, None)`` for fp32 / None configs.

    Shipping the payload in its original layout (rather than the flat
    ``[nb, b]`` layout of :func:`quantize_blockwise`) keeps the ppermute
    shapes identical to the uncompressed ring, so the decomposed
    collective-matmuls stay layout-compatible with their monolithic
    fallbacks — block boundaries land at the same trailing-dim offsets
    either way, which is what makes ring-vs-monolithic quantized parity
    bitwise (see docs/tp_overlap.md)."""
    if config is None or not config.quantized:
        return x, None
    d = x.shape[-1] if x.ndim else 1
    b = config.block_size
    if d % b == 0 and d >= b:
        blocked = x.astype(jnp.float32).reshape(x.shape[:-1] + (d // b, b))
        q, s = _quantize(blocked, config.dtype)
        return q.reshape(x.shape), s
    q, s = _quantize(x.astype(jnp.float32), config.dtype)
    return q, s


def decode_payload(q: jax.Array, scales: Optional[jax.Array],
                   config: Optional[CompressionConfig],
                   out_dtype: jnp.dtype = jnp.float32) -> jax.Array:
    """Inverse of :func:`encode_payload`; fp32 payloads pass through
    (already in their original dtype)."""
    if config is None or not config.quantized or scales is None:
        return q
    d = q.shape[-1] if q.ndim else 1
    b = config.block_size
    if d % b == 0 and d >= b:
        blocked = q.reshape(q.shape[:-1] + (d // b, b))
        return _dequantize(blocked, scales, config.dtype) \
            .reshape(q.shape).astype(out_dtype)
    return _dequantize(q, scales, config.dtype).astype(out_dtype)


# --------------------------------------------------------------------------
# Wire-integrity spot checks
# --------------------------------------------------------------------------

def spot_check_roundtrip(x: jax.Array, config: Optional[CompressionConfig],
                         fingerprint_fn,
                         corrupt=None,
                         out_dtype: jnp.dtype = jnp.float32
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One sampled ring hop with integrity accounting: encode ``x``,
    fingerprint the encoded payload sender-side, (optionally) corrupt it
    in transit, fingerprint receiver-side, decode. Returns
    ``(decoded, fp_tx, fp_rx)`` — ``fp_tx != fp_rx`` means the bytes that
    arrived are not the bytes that were sent, independent of quantization
    (both fingerprints digest the *encoded* payload, so the codec's lossy
    round-trip never trips the check).

    ``fingerprint_fn(q, scales) -> int32`` is dependency-injected (use
    ``resilience.integrity.payload_fingerprint``) so this module stays
    pure array math; ``corrupt(q, scales) -> (q, scales)`` models the
    fault (chaos ``bitflip`` drills flip one bit in ``q``). Everything is
    trace-safe: a ring samples hops under ``lax.cond`` at ~4 bytes of
    extra wire per sampled hop, no extra programs."""
    q, s = encode_payload(x, config)
    fp_tx = fingerprint_fn(q, s)
    if corrupt is not None:
        q, s = corrupt(q, s)
    fp_rx = fingerprint_fn(q, s)
    return decode_payload(q, s, config, out_dtype), fp_tx, fp_rx


# --------------------------------------------------------------------------
# Exact byte accounting (observability)
# --------------------------------------------------------------------------
#
# ``wire_bytes_per_element`` above is the *asymptotic* figure the planner
# charges with. The two helpers below compute the byte count a concrete
# payload actually ships — including quantization padding and the
# whole-trailing-dim block fallback — so the runtime wire counters in
# ``obs`` account what the codec really moves, not the idealised rate.
# Pure int/float arithmetic: callable at trace time with static shapes.

def blockwise_wire_bytes(n_elements: int,
                         config: Optional[CompressionConfig]) -> float:
    """Bytes shipped for an ``n_elements`` payload through
    :func:`quantize_blockwise` (flat ``[nb, b]`` layout): padded int8/fp8
    values + one fp32 scale per block; ``4 * n`` for fp32/None configs."""
    n = int(n_elements)
    if config is None or not config.quantized:
        return 4.0 * n
    b = config.block_size
    nb = max(1, -(-n // b))
    return float(nb * b) + 4.0 * nb


def payload_wire_bytes(shape: Sequence[int],
                       config: Optional[CompressionConfig]) -> float:
    """Bytes shipped for a payload of ``shape`` through
    :func:`encode_payload` (in-layout trailing-dim blocks; the whole
    trailing dim becomes one block when ``block_size`` doesn't divide it)."""
    dims = tuple(int(d) for d in shape)
    n = 1
    for d in dims:
        n *= d
    if config is None or not config.quantized:
        return 4.0 * n
    d = dims[-1] if dims else 1
    b = config.block_size
    n_scales = (n // b) if (d % b == 0 and d >= b) else (n // max(d, 1))
    return float(n) + 4.0 * max(1, n_scales)
