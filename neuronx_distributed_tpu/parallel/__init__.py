"""Tensor/sequence/expert parallel core (reference: ``parallel_layers/``)."""

from . import mesh
from . import comm
from . import comm_compressed
from . import ep_dispatch
from . import mappings
from . import grads
from . import layers
from . import loss_functions
from . import random
from .layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    ParallelEmbedding,
    GQAQKVColumnParallelLinear,
)
from .loss_functions import parallel_cross_entropy
from .comm_compressed import CompressionConfig
from .mesh import (
    initialize_distributed,
    initialize_model_parallel,
    model_parallel_is_initialized,
    destroy_model_parallel,
    declare_axis_hierarchy,
    get_axis_hierarchy,
    get_mesh,
    get_expert_mesh,
    get_moe_phase_mesh,
    TP_AXIS,
    PP_AXIS,
    DP_AXIS,
    CP_AXIS,
    EP_AXIS,
    EXP_DP_AXIS,
)

__all__ = [
    "mesh",
    "comm",
    "comm_compressed",
    "CompressionConfig",
    "ep_dispatch",
    "mappings",
    "initialize_distributed",
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "destroy_model_parallel",
    "declare_axis_hierarchy",
    "get_axis_hierarchy",
    "get_mesh",
    "get_expert_mesh",
    "get_moe_phase_mesh",
    "TP_AXIS",
    "PP_AXIS",
    "DP_AXIS",
    "CP_AXIS",
    "EP_AXIS",
    "EXP_DP_AXIS",
]
