"""nxdlint core: findings, rule registry, suppressions, config, file walk.

The analyzer is purely syntactic (``ast``): it never imports the code under
analysis, so it can lint files whose import would initialise an accelerator
backend, and it runs in milliseconds in CI. Rules register themselves into
:data:`_RULES` via :func:`register`; :func:`analyze_paths` is the single
entry point used by both the CLI (``__main__``) and the self-lint test.

Suppressions
------------
``# nxdlint: disable=<rule>[,<rule>...]`` on the offending line (or on a
standalone comment line directly above it) marks findings of those rules on
that line as suppressed. ``disable=all`` suppresses every rule.
``# nxdlint: disable-file=<rule>`` anywhere in the file suppresses the rule
for the whole file. Suppressed findings are retained (``Finding.suppressed``)
so tooling can audit them, but they do not fail the CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import (Callable, Dict, FrozenSet, Iterable, Iterator, List,
                    Optional, Sequence, Set, Tuple)

#: Fallback canonical mesh-axis names, kept in sync with
#: ``parallel/mesh.py`` — used only when the scanned tree does not contain
#: a ``parallel/mesh.py`` to read the ``*_AXIS`` constants from.
DEFAULT_AXES: FrozenSet[str] = frozenset(
    {"pp", "dp", "cp", "tp", "ep", "dp_exp"})


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}{tag}")


@dataclasses.dataclass
class LintContext:
    """Per-file state handed to every rule.

    ``dataflow`` is the tier-2 :class:`..dataflow.ModuleDataflow` taint
    state for the file, or ``None`` when the analysis runs in
    heuristics-only (v1) mode — rules fall back to name regexes then.
    """

    path: str
    source: str
    tree: ast.Module
    axes: FrozenSet[str]
    dataflow: Optional[object] = None


RuleFn = Callable[[LintContext], Iterator[Finding]]


@dataclasses.dataclass
class Rule:
    """A registered rule plus its declarative path scoping.

    ``scope``: run only on paths matching one of these patterns;
    empty = everywhere. ``exempt``: skip matching paths. A pattern with a
    ``/`` is a path suffix (``"inference/paging.py"``); one without is a
    single path component — a directory name or a bare filename
    (``"parallel"``, ``"aot_cache.py"``). Both are overridable per rule
    from ``[tool.nxdlint.scope]`` / ``[tool.nxdlint.exempt]``.
    """

    name: str
    description: str
    check: RuleFn
    scope: Tuple[str, ...] = ()
    exempt: Tuple[str, ...] = ()


_RULES: Dict[str, Rule] = {}


def register(name: str, description: str, *,
             scope: Tuple[str, ...] = (),
             exempt: Tuple[str, ...] = ()) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        if name in _RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        _RULES[name] = Rule(name, description, fn,
                            scope=tuple(scope), exempt=tuple(exempt))
        return fn
    return deco


def path_matches(path: str, patterns: Iterable[str]) -> bool:
    """Declarative path matcher for :class:`Rule` scoping."""
    norm = path.replace("\\", "/")
    parts = norm.split("/")
    for pat in patterns:
        pat = pat.replace("\\", "/").strip("/")
        if not pat:
            continue
        if "/" in pat:
            if norm == pat or norm.endswith("/" + pat):
                return True
        elif pat in parts:
            return True
    return False


def rule_applies(rule: Rule, path: str,
                 scope_overrides: Optional[Dict[str, List[str]]] = None,
                 exempt_overrides: Optional[Dict[str, List[str]]] = None,
                 ) -> bool:
    scope = tuple((scope_overrides or {}).get(rule.name, rule.scope))
    exempt = tuple((exempt_overrides or {}).get(rule.name, rule.exempt))
    if scope and not path_matches(path, scope):
        return False
    if exempt and path_matches(path, exempt):
        return False
    return True


def all_rules() -> Dict[str, Rule]:
    _ensure_rules_loaded()
    return dict(_RULES)


def _ensure_rules_loaded() -> None:
    # Import for side effect (registration). Local import breaks the cycle
    # core -> rules -> core.
    from . import (rules_comm_compression,  # noqa: F401
                   rules_custom_vjp,  # noqa: F401
                   rules_elasticity,  # noqa: F401
                   rules_integrity,  # noqa: F401
                   rules_mesh_axes,  # noqa: F401
                   rules_observability,  # noqa: F401
                   rules_paging,  # noqa: F401
                   rules_plan,  # noqa: F401
                   rules_quantization,  # noqa: F401
                   rules_recompile,  # noqa: F401
                   rules_resilience,  # noqa: F401
                   rules_serving_resilience,  # noqa: F401
                   rules_slo,  # noqa: F401
                   rules_speculation,  # noqa: F401
                   rules_tp_overlap,  # noqa: F401
                   rules_trace_safety)  # noqa: F401


# --------------------------------------------------------------------------
# Suppression comments
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*nxdlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """``(per_line, file_level)``; ``per_line`` maps 1-based line numbers to
    the set of rule names disabled there."""
    per_line: Dict[int, Set[str]] = {}
    file_level: Set[str] = set()
    for i, ln in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("file"):
            file_level |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
            if ln.lstrip().startswith("#"):
                # standalone comment line: also covers the next line
                per_line.setdefault(i + 1, set()).update(rules)
    return per_line, file_level


#: compound statements whose span would cover their whole body — only the
#: header (up to the first body statement) participates in span-based
#: suppression, so a ``disable=`` on an ``if`` line does not silently
#: suppress the entire block under it.
_COMPOUND_STMTS = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
                   ast.AsyncWith, ast.Try, ast.FunctionDef,
                   ast.AsyncFunctionDef, ast.ClassDef)


def statement_spans(tree: ast.Module) -> List[Tuple[int, int]]:
    """Multi-line spans ``(lineno, end_lineno)`` of simple statements
    (plus multi-line headers of compound statements). A suppression
    comment anywhere inside a span covers findings anywhere in it — a
    ``# nxdlint: disable=`` on the first line of a three-line call must
    suppress a finding reported at an argument's line."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        if isinstance(node, _COMPOUND_STMTS):
            body = getattr(node, "body", None)
            end = (min(c.lineno for c in body) - 1) if body else node.lineno
        else:
            end = getattr(node, "end_lineno", None) or node.lineno
        if end > node.lineno:
            spans.append((node.lineno, end))
    return spans


def _is_suppressed(f: Finding, per_line: Dict[int, Set[str]],
                   file_level: Set[str],
                   spans: Sequence[Tuple[int, int]] = ()) -> bool:
    def hit(rules: Set[str]) -> bool:
        return f.rule in rules or "all" in rules

    if hit(file_level):
        return True
    if hit(per_line.get(f.line, set())):
        return True
    for s, e in spans:
        if s <= f.line <= e:
            joint: Set[str] = set()
            for ln in range(s, e + 1):
                joint |= per_line.get(ln, set())
            if hit(joint):
                return True
    return False


# --------------------------------------------------------------------------
# Canonical axis discovery + pyproject config
# --------------------------------------------------------------------------

def axes_from_mesh_source(source: str) -> FrozenSet[str]:
    """Extract ``X_AXIS = "name"`` constants from ``parallel/mesh.py``."""
    axes: Set[str] = set()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return frozenset()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                axes.add(node.value.value)
    return frozenset(axes)


def _find_mesh_py(paths: Sequence[str]) -> Optional[str]:
    """Locate ``parallel/mesh.py`` under (or next to) the scanned paths so
    the canonical axis set tracks the source of truth automatically."""
    seen: Set[str] = set()
    for p in paths:
        root = p if os.path.isdir(p) else os.path.dirname(p) or "."
        # look in the scan root and up to two parents (linting a submodule
        # like ops/ still finds the sibling parallel/mesh.py)
        for up in range(3):
            cand = os.path.join(root, "parallel", "mesh.py")
            if cand not in seen:
                seen.add(cand)
                if os.path.isfile(cand):
                    return cand
            root = os.path.dirname(root) or "."
    return None


_TOML_LIST_RE = re.compile(
    r"^\s*(?P<key>[A-Za-z0-9_\-]+)\s*=\s*\[(?P<body>[^\]]*)\]")


def load_pyproject_config(start: str) -> Dict[str, object]:
    """Minimal ``[tool.nxdlint]`` reader (py3.10: no tomllib).

    ``[tool.nxdlint]`` keys ``extra_axes`` / ``disable`` are lists of
    strings. The ``[tool.nxdlint.scope]`` / ``[tool.nxdlint.exempt]``
    subsections map a rule name to a list of path patterns, overriding
    the rule's declarative defaults (see :class:`Rule`)."""
    d = os.path.abspath(start if os.path.isdir(start)
                        else os.path.dirname(start) or ".")
    pyproject = None
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            pyproject = cand
            break
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    cfg: Dict[str, object] = {}
    if pyproject is None:
        return cfg
    section = None
    try:
        with open(pyproject, "r", encoding="utf-8") as fh:
            for ln in fh:
                s = ln.strip()
                if s.startswith("["):
                    if s == "[tool.nxdlint]":
                        section = "top"
                    elif s == "[tool.nxdlint.scope]":
                        section = "scope"
                    elif s == "[tool.nxdlint.exempt]":
                        section = "exempt"
                    else:
                        section = None
                    continue
                if section is None:
                    continue
                m = _TOML_LIST_RE.match(ln)
                if not m:
                    continue
                vals = re.findall(r"[\"']([^\"']+)[\"']", m.group("body"))
                if section == "top":
                    cfg[m.group("key")] = vals
                else:
                    cfg.setdefault(section, {})[m.group("key")] = vals
    except OSError:
        pass
    return cfg


# --------------------------------------------------------------------------
# Analysis entry points
# --------------------------------------------------------------------------

def analyze_source(source: str, path: str, axes: FrozenSet[str],
                   rules: Optional[Iterable[str]] = None, *,
                   dataflow: bool = True,
                   scope_overrides: Optional[Dict[str, List[str]]] = None,
                   exempt_overrides: Optional[Dict[str, List[str]]] = None,
                   ) -> List[Finding]:
    _ensure_rules_loaded()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "parse-error",
                        f"syntax error: {e.msg}")]
    df = None
    if dataflow:
        from .dataflow import ModuleDataflow
        try:
            df = ModuleDataflow(tree)
        except RecursionError:  # pathological nesting: fall back to tier 1
            df = None
    ctx = LintContext(path=path, source=source, tree=tree, axes=axes,
                      dataflow=df)
    per_line, file_level = parse_suppressions(source)
    spans: List[Tuple[int, int]] = []
    if per_line:
        lines = set(per_line)
        spans = [sp for sp in statement_spans(tree)
                 if any(sp[0] <= ln <= sp[1] for ln in lines)]
    active = (_RULES.keys() if rules is None else rules)
    findings: List[Finding] = []
    for name in active:
        rule = _RULES[name]
        if not rule_applies(rule, path, scope_overrides, exempt_overrides):
            continue
        for f in rule.check(ctx):
            f.suppressed = _is_suppressed(f, per_line, file_level, spans)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def analyze_paths(paths: Sequence[str],
                  select: Optional[Iterable[str]] = None,
                  disable: Iterable[str] = (),
                  extra_axes: Iterable[str] = (), *,
                  dataflow: bool = True,
                  exclude: Iterable[str] = (),
                  only_files: Optional[Iterable[str]] = None
                  ) -> List[Finding]:
    """Lint every ``.py`` file under ``paths``. Returns ALL findings; the
    caller decides what to do with suppressed ones. ``exclude`` skips
    files matching the given path patterns (same syntax as
    :func:`path_matches`); ``dataflow=False`` runs in heuristics-only
    (v1) mode. ``only_files`` (``--changed-only``) restricts the walk to
    the given files (compared as absolute paths); ``None`` = no
    restriction, an empty iterable = lint nothing."""
    _ensure_rules_loaded()
    if not paths:
        raise ValueError("no paths to analyze")
    cfg = load_pyproject_config(paths[0])
    axes: Set[str] = set(DEFAULT_AXES)
    mesh_py = _find_mesh_py(paths)
    if mesh_py is not None:
        try:
            with open(mesh_py, "r", encoding="utf-8") as fh:
                found = axes_from_mesh_source(fh.read())
            if found:
                axes = set(found)
        except OSError:
            pass
    axes.update(cfg.get("extra_axes", ()))
    axes.update(extra_axes)

    names = set(select) if select is not None else set(_RULES)
    names -= set(disable)
    names -= set(cfg.get("disable", ()))
    unknown = names - set(_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}; "
                         f"known: {sorted(_RULES)}")

    scope_over = dict(cfg.get("scope", {}))
    exempt_over = dict(cfg.get("exempt", {}))
    exclude = tuple(exclude)
    only_set = (None if only_files is None
                else {os.path.abspath(f) for f in only_files})
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        if exclude and path_matches(path, exclude):
            continue
        if only_set is not None and os.path.abspath(path) not in only_set:
            continue
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(path, 1, 0, "parse-error",
                                    f"cannot read file: {e}"))
            continue
        findings.extend(analyze_source(src, path, frozenset(axes),
                                       rules=sorted(names),
                                       dataflow=dataflow,
                                       scope_overrides=scope_over,
                                       exempt_overrides=exempt_over))
    return findings
