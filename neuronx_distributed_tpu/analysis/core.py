"""nxdlint core: findings, rule registry, suppressions, config, file walk.

The analyzer is purely syntactic (``ast``): it never imports the code under
analysis, so it can lint files whose import would initialise an accelerator
backend, and it runs in milliseconds in CI. Rules register themselves into
:data:`_RULES` via :func:`register`; :func:`analyze_paths` is the single
entry point used by both the CLI (``__main__``) and the self-lint test.

Suppressions
------------
``# nxdlint: disable=<rule>[,<rule>...]`` on the offending line (or on a
standalone comment line directly above it) marks findings of those rules on
that line as suppressed. ``disable=all`` suppresses every rule.
``# nxdlint: disable-file=<rule>`` anywhere in the file suppresses the rule
for the whole file. Suppressed findings are retained (``Finding.suppressed``)
so tooling can audit them, but they do not fail the CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import (Callable, Dict, FrozenSet, Iterable, Iterator, List,
                    Optional, Sequence, Set, Tuple)

#: Fallback canonical mesh-axis names, kept in sync with
#: ``parallel/mesh.py`` — used only when the scanned tree does not contain
#: a ``parallel/mesh.py`` to read the ``*_AXIS`` constants from.
DEFAULT_AXES: FrozenSet[str] = frozenset(
    {"pp", "dp", "cp", "tp", "ep", "dp_exp"})


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}{tag}")


@dataclasses.dataclass
class LintContext:
    """Per-file state handed to every rule."""

    path: str
    source: str
    tree: ast.Module
    axes: FrozenSet[str]


RuleFn = Callable[[LintContext], Iterator[Finding]]


@dataclasses.dataclass
class Rule:
    name: str
    description: str
    check: RuleFn


_RULES: Dict[str, Rule] = {}


def register(name: str, description: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        if name in _RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        _RULES[name] = Rule(name, description, fn)
        return fn
    return deco


def all_rules() -> Dict[str, Rule]:
    _ensure_rules_loaded()
    return dict(_RULES)


def _ensure_rules_loaded() -> None:
    # Import for side effect (registration). Local import breaks the cycle
    # core -> rules -> core.
    from . import (rules_comm_compression,  # noqa: F401
                   rules_custom_vjp,  # noqa: F401
                   rules_elasticity,  # noqa: F401
                   rules_integrity,  # noqa: F401
                   rules_mesh_axes,  # noqa: F401
                   rules_observability,  # noqa: F401
                   rules_paging,  # noqa: F401
                   rules_plan,  # noqa: F401
                   rules_recompile,  # noqa: F401
                   rules_resilience,  # noqa: F401
                   rules_serving_resilience,  # noqa: F401
                   rules_tp_overlap,  # noqa: F401
                   rules_trace_safety)  # noqa: F401


# --------------------------------------------------------------------------
# Suppression comments
# --------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*nxdlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """``(per_line, file_level)``; ``per_line`` maps 1-based line numbers to
    the set of rule names disabled there."""
    per_line: Dict[int, Set[str]] = {}
    file_level: Set[str] = set()
    for i, ln in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(ln)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("file"):
            file_level |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
            if ln.lstrip().startswith("#"):
                # standalone comment line: also covers the next line
                per_line.setdefault(i + 1, set()).update(rules)
    return per_line, file_level


def _is_suppressed(f: Finding, per_line: Dict[int, Set[str]],
                   file_level: Set[str]) -> bool:
    def hit(rules: Set[str]) -> bool:
        return f.rule in rules or "all" in rules

    if hit(file_level):
        return True
    return hit(per_line.get(f.line, set()))


# --------------------------------------------------------------------------
# Canonical axis discovery + pyproject config
# --------------------------------------------------------------------------

def axes_from_mesh_source(source: str) -> FrozenSet[str]:
    """Extract ``X_AXIS = "name"`` constants from ``parallel/mesh.py``."""
    axes: Set[str] = set()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return frozenset()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                axes.add(node.value.value)
    return frozenset(axes)


def _find_mesh_py(paths: Sequence[str]) -> Optional[str]:
    """Locate ``parallel/mesh.py`` under (or next to) the scanned paths so
    the canonical axis set tracks the source of truth automatically."""
    seen: Set[str] = set()
    for p in paths:
        root = p if os.path.isdir(p) else os.path.dirname(p) or "."
        # look in the scan root and up to two parents (linting a submodule
        # like ops/ still finds the sibling parallel/mesh.py)
        for up in range(3):
            cand = os.path.join(root, "parallel", "mesh.py")
            if cand not in seen:
                seen.add(cand)
                if os.path.isfile(cand):
                    return cand
            root = os.path.dirname(root) or "."
    return None


_TOML_LIST_RE = re.compile(r"^\s*(?P<key>[A-Za-z_]+)\s*=\s*\[(?P<body>[^\]]*)\]")


def load_pyproject_config(start: str) -> Dict[str, List[str]]:
    """Minimal ``[tool.nxdlint]`` reader (py3.10: no tomllib). Supported
    keys: ``extra_axes``, ``disable`` — both lists of strings."""
    d = os.path.abspath(start if os.path.isdir(start)
                        else os.path.dirname(start) or ".")
    pyproject = None
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            pyproject = cand
            break
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    cfg: Dict[str, List[str]] = {}
    if pyproject is None:
        return cfg
    in_section = False
    try:
        with open(pyproject, "r", encoding="utf-8") as fh:
            for ln in fh:
                s = ln.strip()
                if s.startswith("["):
                    in_section = (s == "[tool.nxdlint]")
                    continue
                if not in_section:
                    continue
                m = _TOML_LIST_RE.match(ln)
                if m:
                    vals = re.findall(r"[\"']([^\"']+)[\"']",
                                      m.group("body"))
                    cfg[m.group("key")] = vals
    except OSError:
        pass
    return cfg


# --------------------------------------------------------------------------
# Analysis entry points
# --------------------------------------------------------------------------

def analyze_source(source: str, path: str, axes: FrozenSet[str],
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    _ensure_rules_loaded()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "parse-error",
                        f"syntax error: {e.msg}")]
    ctx = LintContext(path=path, source=source, tree=tree, axes=axes)
    per_line, file_level = parse_suppressions(source)
    active = (_RULES.keys() if rules is None else rules)
    findings: List[Finding] = []
    for name in active:
        rule = _RULES[name]
        for f in rule.check(ctx):
            f.suppressed = _is_suppressed(f, per_line, file_level)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def analyze_paths(paths: Sequence[str],
                  select: Optional[Iterable[str]] = None,
                  disable: Iterable[str] = (),
                  extra_axes: Iterable[str] = ()) -> List[Finding]:
    """Lint every ``.py`` file under ``paths``. Returns ALL findings; the
    caller decides what to do with suppressed ones."""
    _ensure_rules_loaded()
    if not paths:
        raise ValueError("no paths to analyze")
    cfg = load_pyproject_config(paths[0])
    axes: Set[str] = set(DEFAULT_AXES)
    mesh_py = _find_mesh_py(paths)
    if mesh_py is not None:
        try:
            with open(mesh_py, "r", encoding="utf-8") as fh:
                found = axes_from_mesh_source(fh.read())
            if found:
                axes = set(found)
        except OSError:
            pass
    axes.update(cfg.get("extra_axes", ()))
    axes.update(extra_axes)

    names = set(select) if select is not None else set(_RULES)
    names -= set(disable)
    names -= set(cfg.get("disable", ()))
    unknown = names - set(_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}; "
                         f"known: {sorted(_RULES)}")

    findings: List[Finding] = []
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(path, 1, 0, "parse-error",
                                    f"cannot read file: {e}"))
            continue
        findings.extend(analyze_source(src, path, frozenset(axes),
                                       rules=sorted(names)))
    return findings
