"""Rule ``trace-safety``: host-side operations on traced values.

Inside a function that JAX traces (``jit``/``shard_map``/``scan``/``cond``/
``grad``/``checkpoint``/... bodies), values that dataflow from the function's
parameters are tracers. Calling ``.item()`` / ``float()`` / ``int()`` /
``bool()`` on them, handing them to ``np.*``, or branching Python control
flow on them either raises ``TracerConversionError`` at trace time on the
one config that reaches the line, or silently constant-folds (``np.*`` on a
concrete-looking tracer aval).

Detection is a conservative name-level taint analysis:

* a function is *traced* when it is decorated with ``jit``-likes, or passed
  as a callable to a tracing consumer (``shard_map``, ``lax.scan``,
  ``lax.cond``, ``jax.vjp``, ``jax.checkpoint``/``remat``, ``grad``...);
* its parameters are tainted; taint propagates through assignments;
* static accessors sanitize: ``.shape``/``.dtype``/``.ndim``/``.size``,
  ``len()``, ``jnp.shape()``, ``isinstance()``, ``x is None``, ... — so
  ``if x.shape[0] % 2:`` is fine while ``if x[0] > 0:`` is flagged.

Functions only *returned* to callers that jit them later (the factory idiom)
are out of scope — taint starts at the syntactic tracing boundary.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set

from . import astutil
from .core import Finding, LintContext, register

# call-name -> positional indices holding traced callables
_CALLABLE_CONSUMERS: Dict[str, Sequence[int]] = {
    "jit": (0,),
    "pjit": (0,),
    "shard_map": (0,),
    "scan": (0,),
    "associative_scan": (0,),
    "cond": (1, 2),
    "switch": (1,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "checkpoint": (0,),
    "remat": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "vjp": (0,),
    "jvp": (0,),
    "linearize": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "eval_shape": (0,),
    "pallas_call": (0,),
    "custom_vjp": (0,),
    "custom_jvp": (0,),
}

# attribute accesses that yield static (host) values from a tracer
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "aval",
                           "sharding", "itemsize", "nbytes", "weak_type"})

# calls whose result is host-static even on tainted args
_SANITIZING_CALLS = frozenset({"len", "isinstance", "type", "hasattr",
                               "callable", "shape", "result_type",
                               "eval_shape", "ndim", "format", "repr",
                               "str", "id"})

_HOST_COERCIONS = frozenset({"float", "int", "bool", "complex"})

_NUMPY_ROOTS = frozenset({"np", "numpy", "onp"})

# numpy calls that are fine on tracers (metadata / dtype queries)
_NUMPY_STATIC = frozenset({"dtype", "shape", "ndim", "result_type", "issubdtype",
                           "iinfo", "finfo", "prod"})


def _collect_defs(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _custom_vjp_nondiff(dec: ast.AST) -> Optional[List[int]]:
    """nondiff_argnums of a custom_vjp/custom_jvp decorator, [] when the
    decorator carries none, None when ``dec`` is not such a decorator."""
    tail = astutil.tail_name(dec)
    if tail in ("custom_vjp", "custom_jvp"):
        return []
    if isinstance(dec, ast.Call):
        f_tail = astutil.tail_name(dec.func)
        inner = None
        if f_tail in ("custom_vjp", "custom_jvp"):
            inner = dec
        elif f_tail == "partial" and dec.args and \
                astutil.tail_name(dec.args[0]) in ("custom_vjp",
                                                   "custom_jvp"):
            inner = dec
        if inner is not None:
            return astutil.int_tuple_values(
                astutil.get_kwarg(inner, "nondiff_argnums")) or []
    return None


def _traced_function_nodes(tree: ast.AST) -> Dict[int, Set[str]]:
    """Map from id(FunctionDef/Lambda) of every JAX-traced function to the
    set of parameter NAMES that are static (not traced): jit
    static_argnames/static_argnums, custom_vjp nondiff_argnums, and the
    leading nondiff args of a defvjp bwd."""
    defs = _collect_defs(tree)
    traced: Dict[int, Set[str]] = {}

    def mark_callable(expr: ast.AST,
                      static_of: Optional[Callable] = None) -> None:
        # unwrap partial(f, ...) / functools.partial(f, ...)
        if isinstance(expr, ast.Call) and \
                astutil.tail_name(expr.func) == "partial" and expr.args:
            expr = expr.args[0]
        if isinstance(expr, ast.Lambda):
            traced.setdefault(id(expr), set())
        elif isinstance(expr, ast.Name):
            for d in defs.get(expr.id, ()):
                statics = static_of(d) if static_of is not None else set()
                traced.setdefault(id(d), set()).update(statics)

    # primal name -> nondiff indices (for defvjp fwd/bwd statics)
    primal_nondiff: Dict[str, List[int]] = {}

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if astutil.is_jit_decorator(dec):
                    traced.setdefault(id(node), set()).update(
                        astutil.jit_static_param_names(dec, node))
                nondiff = _custom_vjp_nondiff(dec)
                if nondiff is not None:
                    params = astutil.positional_args(node)
                    statics = {params[i].arg for i in nondiff
                               if 0 <= i < len(params)}
                    traced.setdefault(id(node), set()).update(statics)
                    primal_nondiff[node.name] = nondiff
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                astutil.tail_name(node.value.func) == "custom_vjp" and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            nondiff = astutil.int_tuple_values(
                astutil.get_kwarg(node.value, "nondiff_argnums")) or []
            primal_nondiff[node.targets[0].id] = nondiff

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.call_tail(node)
        if tail in _CALLABLE_CONSUMERS:
            for pos in _CALLABLE_CONSUMERS[tail]:
                if len(node.args) > pos:
                    mark_callable(node.args[pos])
        if tail == "defvjp" and isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name):
            nondiff = primal_nondiff.get(node.func.value.id, [])

            def fwd_statics(d, _nd=nondiff):
                params = astutil.positional_args(d)
                return {params[i].arg for i in _nd if 0 <= i < len(params)}

            def bwd_statics(d, _n=len(nondiff)):
                # bwd signature: (*nondiff_args, residuals, cotangent)
                params = astutil.positional_args(d)
                return {p.arg for p in params[:_n]}

            if len(node.args) > 0:
                mark_callable(node.args[0], fwd_statics)
            if len(node.args) > 1:
                mark_callable(node.args[1], bwd_statics)
    return traced


class _Scope:
    def __init__(self, tainted: Set[str]):
        self.tainted = set(tainted)


def _expr_tainted(expr: ast.AST, scope: _Scope) -> bool:
    """Conservative: does ``expr`` (possibly) evaluate to a traced value?"""
    if isinstance(expr, ast.Name):
        return expr.id in scope.tainted
    if isinstance(expr, ast.Constant):
        return False
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(expr.value, scope)
    if isinstance(expr, ast.Subscript):
        return _expr_tainted(expr.value, scope)
    if isinstance(expr, ast.Call):
        tail = astutil.tail_name(expr.func)
        if tail in _SANITIZING_CALLS:
            return False
        if tail in _NUMPY_STATIC and \
                astutil.root_name(expr.func) in _NUMPY_ROOTS:
            return False
        args_tainted = any(_expr_tainted(a, scope) for a in expr.args) or \
            any(_expr_tainted(kw.value, scope) for kw in expr.keywords)
        if isinstance(expr.func, ast.Attribute) and \
                _expr_tainted(expr.func.value, scope):
            return True  # method on a traced value
        return args_tainted
    if isinstance(expr, ast.BinOp):
        return _expr_tainted(expr.left, scope) or \
            _expr_tainted(expr.right, scope)
    if isinstance(expr, ast.UnaryOp):
        return _expr_tainted(expr.operand, scope)
    if isinstance(expr, ast.BoolOp):
        return any(_expr_tainted(v, scope) for v in expr.values)
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False  # identity checks are host-safe
        return _expr_tainted(expr.left, scope) or \
            any(_expr_tainted(c, scope) for c in expr.comparators)
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(_expr_tainted(e, scope) for e in expr.elts)
    if isinstance(expr, ast.Dict):
        return any(_expr_tainted(v, scope) for v in expr.values
                   if v is not None)
    if isinstance(expr, ast.IfExp):
        return _expr_tainted(expr.body, scope) or \
            _expr_tainted(expr.orelse, scope)
    if isinstance(expr, ast.Starred):
        return _expr_tainted(expr.value, scope)
    if isinstance(expr, (ast.JoinedStr, ast.FormattedValue)):
        return False
    return False


def _target_names(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _target_names(e)
    elif isinstance(target, ast.Starred):
        yield from _target_names(target.value)


def _check_violations(expr: ast.AST, scope: _Scope, ctx: LintContext,
                      out: List[Finding]) -> None:
    """Scan one expression tree for host-side ops on tainted values."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.tail_name(node.func)
        if tail in ("item", "tolist") and \
                isinstance(node.func, ast.Attribute) and \
                _expr_tainted(node.func.value, scope):
            out.append(Finding(
                ctx.path, node.lineno, node.col_offset, "trace-safety",
                f".{tail}() on a traced value forces a host sync and fails "
                "under jit/shard_map tracing"))
        elif tail in _HOST_COERCIONS and isinstance(node.func, ast.Name) \
                and len(node.args) == 1 and \
                _expr_tainted(node.args[0], scope):
            out.append(Finding(
                ctx.path, node.lineno, node.col_offset, "trace-safety",
                f"{tail}() coercion of a traced value raises "
                "TracerConversionError inside traced code"))
        elif astutil.root_name(node.func) in _NUMPY_ROOTS and \
                isinstance(node.func, ast.Attribute) and \
                tail not in _NUMPY_STATIC and \
                (any(_expr_tainted(a, scope) for a in node.args)
                 or any(_expr_tainted(kw.value, scope)
                        for kw in node.keywords)):
            out.append(Finding(
                ctx.path, node.lineno, node.col_offset, "trace-safety",
                f"np.{tail}() on a traced value escapes the trace (use the "
                "jnp equivalent)"))


def _analyze_function(fn: astutil.FuncNode, inherited: Set[str],
                      traced_ids: Dict[int, Set[str]], ctx: LintContext,
                      out: List[Finding]) -> None:
    tainted = set(inherited)
    if id(fn) in traced_ids:
        statics = traced_ids[id(fn)]
        tainted.update(a.arg for a in astutil.positional_args(fn)
                       if a.arg not in statics)
    scope = _Scope(tainted)

    if isinstance(fn, ast.Lambda):
        _check_violations(fn.body, scope, ctx, out)
        return

    body: Sequence[ast.stmt] = fn.body
    # two passes: the first settles assignment taint (handles simple
    # use-before-def ordering), the second reports violations
    for reporting in (False, True):
        for stmt in body:
            _walk_stmt(stmt, scope, traced_ids, ctx, out, reporting)


def _walk_stmt(stmt: ast.stmt, scope: _Scope,
               traced_ids: Dict[int, Set[str]],
               ctx: LintContext, out: List[Finding],
               reporting: bool) -> None:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        if reporting:
            _analyze_function(stmt, scope.tainted, traced_ids, ctx, out)
        return
    if isinstance(stmt, ast.ClassDef):
        return

    if isinstance(stmt, ast.Assign):
        if _expr_tainted(stmt.value, scope):
            for t in stmt.targets:
                scope.tainted.update(_target_names(t))
        if reporting:
            _check_violations(stmt.value, scope, ctx, out)
        return
    if isinstance(stmt, ast.AugAssign):
        if _expr_tainted(stmt.value, scope):
            scope.tainted.update(_target_names(stmt.target))
        if reporting:
            _check_violations(stmt.value, scope, ctx, out)
        return
    if isinstance(stmt, ast.AnnAssign):
        if stmt.value is not None:
            if _expr_tainted(stmt.value, scope):
                scope.tainted.update(_target_names(stmt.target))
            if reporting:
                _check_violations(stmt.value, scope, ctx, out)
        return

    if isinstance(stmt, (ast.If, ast.While)):
        if reporting:
            if _expr_tainted(stmt.test, scope):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                out.append(Finding(
                    ctx.path, stmt.lineno, stmt.col_offset, "trace-safety",
                    f"Python `{kind}` on a traced value — data-dependent "
                    "control flow must use lax.cond/lax.select/jnp.where"))
            _check_violations(stmt.test, scope, ctx, out)
        for s in stmt.body + stmt.orelse:
            _walk_stmt(s, scope, traced_ids, ctx, out, reporting)
        return

    if isinstance(stmt, ast.For):
        if _expr_tainted(stmt.iter, scope):
            scope.tainted.update(_target_names(stmt.target))
        if reporting:
            _check_violations(stmt.iter, scope, ctx, out)
        for s in stmt.body + stmt.orelse:
            _walk_stmt(s, scope, traced_ids, ctx, out, reporting)
        return

    if isinstance(stmt, ast.With):
        for s in stmt.body:
            _walk_stmt(s, scope, traced_ids, ctx, out, reporting)
        return

    if isinstance(stmt, ast.Try):
        for s in stmt.body + stmt.orelse + stmt.finalbody:
            _walk_stmt(s, scope, traced_ids, ctx, out, reporting)
        for h in stmt.handlers:
            for s in h.body:
                _walk_stmt(s, scope, traced_ids, ctx, out, reporting)
        return

    if isinstance(stmt, (ast.Return, ast.Expr)):
        if reporting and stmt.value is not None:
            _check_violations(stmt.value, scope, ctx, out)
        return
    # Raise/Assert/Pass/Import/...: nothing traced-unsafe to report beyond
    # calls, which only appear inside the expressions handled above.


@register(
    "trace-safety",
    "host-side ops (.item(), float()/int()/bool(), np.*, Python if/while) "
    "on values that dataflow from traced function parameters")
def check(ctx: LintContext) -> Iterator[Finding]:
    traced_ids = _traced_function_nodes(ctx.tree)
    out: List[Finding] = []
    seen: Set[int] = set()

    def visit_defs(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(child) not in seen:
                    seen.add(id(child))
                    _analyze_function(child, set(), traced_ids, ctx, out)
            elif isinstance(child, ast.Lambda):
                if id(child) in traced_ids and id(child) not in seen:
                    seen.add(id(child))
                    _analyze_function(child, set(), traced_ids, ctx, out)
                continue
            else:
                visit_defs(child)

    visit_defs(ctx.tree)
    # nested defs are analyzed by _analyze_function recursion; dedupe
    # findings that could be emitted twice via the two-pass walk
    uniq = {}
    for f in out:
        uniq[(f.line, f.col, f.message)] = f
    yield from uniq.values()
