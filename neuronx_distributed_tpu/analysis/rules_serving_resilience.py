"""Rule ``serving-resilience``: failure-handling hygiene in ``inference/``.

The router/engine contract (``docs/serving.md``) is that serving failures
are *typed* and *bounded*: a replica failure surfaces as
``ReplicaCrashed``/``CacheExhaustedError``/``RequestRejected`` and is
handled by the circuit breaker with bounded, backed-off resubmission.
The cross-host transport (``inference/transport.py``) extends the same
contract to the wire: chunk loss/corruption surfaces as
``ChunkError``/``ChunkIntegrityError`` and is healed by *bounded*
retransmission with exponential backoff. Three anti-patterns silently
void that contract:

* **Bare ``except``/``except Exception`` swallowing around
  ``engine.step``/``submit`` call sites** — a handler that catches
  everything and does not re-raise turns a replica death into a silent
  no-op: the health monitor never sees the failure, in-flight requests
  are never resubmitted, and the request is simply lost. Catch the typed
  serving exceptions instead.

* **Bare excepts swallowing around chunk ``send``/``recv`` call
  sites** — a swallowed link failure becomes silence: the receiver can
  never NACK what it never learned was sent, the sender's retransmit
  timers never arm, and the stream wedges instead of healing or
  aborting into the re-prefill fallback.

* **Unbounded retry/retransmit loops without backoff** — a ``while
  True:`` retry whose handler ``continue``s straight back without
  sleeping/backing off hammers a sick replica in a hot loop, and a
  ``while True:`` retransmit around ``.send(...)`` with neither an
  attempt bound nor pacing floods a degraded link forever. Retries must
  be bounded (attempt counter, like ``max_chunk_attempts``) or paced
  (backoff), like the router's ``max_retries`` + exponential backoff.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from . import astutil
from .core import Finding, LintContext, register

_ENGINE_CALLS = ("step", "submit")
_LINK_CALLS = ("send", "recv")
_BROAD = ("Exception", "BaseException")
_PACING = ("sleep", "backoff", "wait", "delay")
#: identifier fragments that signal a retransmit loop is attempt-bounded
_BOUND_NAMES = ("attempt", "retr", "tries")


def _call_in(body, names) -> Optional[ast.Call]:
    """First ``<obj>.<name>(...)`` call under these statements, for any
    ``name`` in ``names``, or None."""
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in names):
                return node
    return None


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:                       # bare `except:`
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(astutil.tail_name(t) in _BROAD for t in types)


def _swallows(handler: ast.ExceptHandler) -> bool:
    return not any(isinstance(n, ast.Raise)
                   for stmt in handler.body for n in ast.walk(stmt))


def _calls_pacing(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = (astutil.tail_name(sub.func) or "").lower()
            if any(p in name for p in _PACING):
                return True
    return False


def _has_attempt_bound(loop: ast.While) -> bool:
    """Any identifier in the loop that smells like an attempt counter
    (``attempts``, ``retries``, ``tries``...) — the loop then has a
    termination signal the rule trusts."""
    for node in ast.walk(loop):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(b in name.lower() for b in _BOUND_NAMES):
            return True
    return False


def _is_while_true(loop: ast.While) -> bool:
    return isinstance(loop.test, ast.Constant) and loop.test.value is True


def _broad_swallow_findings(ctx, node: ast.Try
                            ) -> Iterator[Tuple[ast.ExceptHandler, str]]:
    engine_call = _call_in(node.body, _ENGINE_CALLS)
    link_call = (None if engine_call is not None
                 else _call_in(node.body, _LINK_CALLS))
    if engine_call is None and link_call is None:
        return
    for handler in node.handlers:
        if not (_is_broad_handler(handler) and _swallows(handler)):
            continue
        if engine_call is not None:
            yield handler, (
                f"broad except swallows failures around "
                f"`.{engine_call.func.attr}(...)` — a replica death "
                "becomes a silent no-op and the request is lost; "
                "catch the typed serving exceptions "
                "(RequestRejected / CacheExhaustedError / "
                "ReplicaCrashed) or re-raise")
        else:
            yield handler, (
                f"broad except swallows failures around chunk "
                f"`.{link_call.func.attr}(...)` — a lost or corrupt "
                "chunk becomes silence: no NACK, no retransmit timer, "
                "no abort into the re-prefill fallback; catch the "
                "typed transport exceptions (ChunkError / "
                "ChunkIntegrityError) or re-raise")


@register(
    "serving-resilience",
    "bare except swallowing around engine.step/submit and chunk "
    "send/recv call sites, and unbounded retry/retransmit loops without "
    "an attempt bound or backoff inside inference/ (voids the "
    "typed-failure + bounded-failover contract)",
    scope=("inference",))
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Try):
            for handler, msg in _broad_swallow_findings(ctx, node):
                findings.append(Finding(
                    ctx.path, handler.lineno, handler.col_offset,
                    "serving-resilience", msg))
        elif isinstance(node, ast.While) and _is_while_true(node):
            for sub in ast.walk(node):
                if not isinstance(sub, ast.ExceptHandler):
                    continue
                has_continue = any(
                    isinstance(n, ast.Continue)
                    for stmt in sub.body for n in ast.walk(stmt))
                if has_continue and not _calls_pacing(sub):
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "serving-resilience",
                        "unbounded retry: `while True` handler continues "
                        "without backoff or an attempt bound — this "
                        "hammers a sick replica in a hot loop; bound the "
                        "retries (max_retries) and pace them "
                        "(exponential backoff)"))
            send_call = _call_in(node.body, ("send",))
            if (send_call is not None and not _calls_pacing(node)
                    and not _has_attempt_bound(node)):
                findings.append(Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "serving-resilience",
                    "unbounded retransmit: `while True` around "
                    "`.send(...)` with neither an attempt cap nor "
                    "backoff floods a degraded link forever; cap the "
                    "attempts (max_chunk_attempts) and pace the "
                    "retransmits (exponential backoff)"))
    yield from findings
