"""Rule ``serving-resilience``: failure-handling hygiene in ``inference/``.

The router/engine contract (``docs/serving.md``) is that serving failures
are *typed* and *bounded*: a replica failure surfaces as
``ReplicaCrashed``/``CacheExhaustedError``/``RequestRejected`` and is
handled by the circuit breaker with bounded, backed-off resubmission.
Two anti-patterns silently void that contract:

* **Bare ``except``/``except Exception`` swallowing around
  ``engine.step``/``submit`` call sites** — a handler that catches
  everything and does not re-raise turns a replica death into a silent
  no-op: the health monitor never sees the failure, in-flight requests
  are never resubmitted, and the request is simply lost. Catch the typed
  serving exceptions instead.

* **Unbounded retry loops without backoff** — a ``while True:`` retry
  whose handler ``continue``s straight back without sleeping/backing off
  hammers a sick replica in a hot loop (and, with the point above, can
  spin forever). Retries must be bounded (attempt counter) or paced
  (backoff), like the router's ``max_retries`` + exponential backoff.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from . import astutil
from .core import Finding, LintContext, register

_ENGINE_CALLS = ("step", "submit")
_BROAD = ("Exception", "BaseException")
_PACING = ("sleep", "backoff", "wait", "delay")


def _engine_call_in(body) -> ast.Call:
    """First ``<obj>.step(...)`` / ``<obj>.submit(...)`` call under these
    statements, or None."""
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENGINE_CALLS):
                return node
    return None


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:                       # bare `except:`
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(astutil.tail_name(t) in _BROAD for t in types)


def _swallows(handler: ast.ExceptHandler) -> bool:
    return not any(isinstance(n, ast.Raise)
                   for stmt in handler.body for n in ast.walk(stmt))


def _calls_pacing(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = (astutil.tail_name(node.func) or "").lower()
                if any(p in name for p in _PACING):
                    return True
    return False


def _is_while_true(loop: ast.While) -> bool:
    return isinstance(loop.test, ast.Constant) and loop.test.value is True


@register(
    "serving-resilience",
    "bare except swallowing around engine.step/submit call sites and "
    "unbounded retry loops without backoff inside inference/ (voids the "
    "typed-failure + bounded-failover contract)",
    scope=("inference",))
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Try):
            call = _engine_call_in(node.body)
            if call is None:
                continue
            for handler in node.handlers:
                if _is_broad_handler(handler) and _swallows(handler):
                    findings.append(Finding(
                        ctx.path, handler.lineno, handler.col_offset,
                        "serving-resilience",
                        f"broad except swallows failures around "
                        f"`.{call.func.attr}(...)` — a replica death "
                        "becomes a silent no-op and the request is lost; "
                        "catch the typed serving exceptions "
                        "(RequestRejected / CacheExhaustedError / "
                        "ReplicaCrashed) or re-raise"))
        elif isinstance(node, ast.While) and _is_while_true(node):
            for sub in ast.walk(node):
                if not isinstance(sub, ast.ExceptHandler):
                    continue
                has_continue = any(
                    isinstance(n, ast.Continue)
                    for stmt in sub.body for n in ast.walk(stmt))
                if has_continue and not _calls_pacing(sub):
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "serving-resilience",
                        "unbounded retry: `while True` handler continues "
                        "without backoff or an attempt bound — this "
                        "hammers a sick replica in a hot loop; bound the "
                        "retries (max_retries) and pace them "
                        "(exponential backoff)"))
    yield from findings
