"""Rule ``quantization``: whole-pool dequantization stays inside ``ops/``.

The int8 paged-KV tier's memory win exists only while the pool is *read*
quantized: the Pallas kernel DMAs int8 blocks plus per-row scales and
fuses the dequant into the online-softmax inner loop, and the XLA
fallback dequantizes only the blocks a sequence's table actually maps
(``ops/paged_attention.py``). Code outside ``ops/`` that calls
``dequantize_kv``/``dequantize_blockwise`` on a pool-sized array
materializes a float copy of the entire pool in HBM — silently spending
the 2-4x capacity the tier was selected for, on every decode step.

What is NOT this rule's business:

* per-layer contiguous-cache slices (the non-paged serving path in
  ``models/llama.py`` dequantizes one layer's ``[B, T, KV, D]`` slice —
  bounded by the batch, not the pool);
* the wire codec's chunk-at-a-time ``dequantize_blockwise`` in
  ``inference/transport.py`` (payload chunks, not resident pools);
* ``ops/`` itself, where the gather-then-dequant order makes the
  dequantized working set per-sequence.

The heuristic is therefore name-based: the first argument must be
pool-named (``k_pool``, ``pool.k``, ``cache.k_pool[...]``, …) for the
rule to fire.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from .core import Finding, LintContext, register

_DEQUANT_FNS = ("dequantize_kv", "dequantize_blockwise")


def _call_name(func) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _expr_names(node) -> List[str]:
    """Identifier components of an expression: ``cache.k_pool[idx]`` →
    ``["cache", "k_pool"]`` (subscripts peel to their value — indexing a
    pool still reads the pool array)."""
    names: List[str] = []
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        names.extend(_expr_names(node.value))
        names.append(node.attr)
    elif isinstance(node, ast.Name):
        names.append(node.id)
    return names


def _is_pool_named(node) -> bool:
    return any("pool" in n.lower() for n in _expr_names(node))


@register(
    "quantization",
    "whole-pool dequantize_kv/dequantize_blockwise call on a pool-named "
    "array outside ops/ — materializes a float copy of the entire paged "
    "pool in HBM, forfeiting the quantized tier's capacity win; the "
    "fused read in ops/paged_attention.py (or a per-sequence gather "
    "first) is the supported path",
    scope=("inference", "models"))
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _call_name(node.func)
        if fn not in _DEQUANT_FNS:
            continue
        if not node.args or not _is_pool_named(node.args[0]):
            continue
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "quantization",
            f"`{fn}(...)` on pool-named array "
            f"`{'.'.join(_expr_names(node.args[0]))}` dequantizes the "
            "whole paged pool to float — gather the sequence's blocks "
            "first or use the fused kernel read in ops/paged_attention.py"))
    yield from findings
