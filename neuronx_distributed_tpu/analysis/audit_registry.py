"""Entry-point registry for the jaxpr-level program auditor.

Subsystems register the functions whose *compiled form* carries
contracts the syntactic tiers cannot see — the train step, the serving
engine step, the disaggregated prefill/decode workers, the EP dispatch
ring. ``python -m neuronx_distributed_tpu.analysis --jaxpr`` builds each
registered entry point and abstract-traces it with ``jax.make_jaxpr``
(no execution of the traced function — tracing evaluates shapes/dtypes
only), then :mod:`.jaxpr_audit` walks the resulting ClosedJaxpr.

Registration is declarative and lazy: ``register_entry_point`` stores a
zero-argument *builder*; nothing JAX-related happens until the auditor
asks for the entry point. The default entry points live next to the
subsystems they audit (``trainer/trainer.py``, ``inference/engine.py``,
``parallel/ep_dispatch.py``) and are pulled in by
:func:`load_default_entry_points`.

This module itself has no JAX imports — importing it from a subsystem
module costs nothing.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

#: default "large buffer" threshold for the donation check (bytes)
DEFAULT_DONATION_MIN_BYTES = 1 << 20


@dataclasses.dataclass
class BuiltEntry:
    """What a builder returns: the function to abstract-trace plus the
    example arguments (arrays or ``jax.ShapeDtypeStruct``s — tracing
    never reads values). ``mesh`` (a ``jax.sharding.Mesh``) anchors the
    entry's sharding contract: the mesh-protocol verifier builds
    ``NamedSharding``s from it when checking the registered
    ``in_shardings`` / ``max_replicated_bytes`` fields."""

    fn: Callable[..., Any]
    args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...] = ()
    mesh: Any = None


@dataclasses.dataclass
class EntryPoint:
    name: str
    build: Callable[[], BuiltEntry]
    description: str = ""
    tags: Tuple[str, ...] = ()
    #: when set, ring hops (ppermute/all_to_all) in this entry are
    #: expected to ship this wire dtype — full-precision hops are flagged
    wire_dtype: Optional[str] = None
    #: train-style steps must donate their large input buffers
    expects_donation: bool = False
    #: minimum buffer size (bytes) for the donation check
    donation_min_bytes: int = DEFAULT_DONATION_MIN_BYTES
    #: minimum element count for the wire-precision check
    wire_min_elems: int = 64
    #: sharding contract for the mesh-protocol verifier: one entry per
    #: *flattened* example-argument leaf — ``None`` (no expectation) or a
    #: plain tuple of ``PartitionSpec`` dim assignments (axis name,
    #: ``None``, or a tuple of axis names; ``()`` = fully replicated).
    #: Expressed jax-free so registration stays import-cheap.
    in_shardings: Optional[Tuple[Any, ...]] = None
    #: mesh-protocol replication ceiling: any input/output leaf at least
    #: this many bytes that lowers to a *fully replicated* sharding on a
    #: multi-device mesh is flagged (``jaxpr-silent-replication``)
    max_replicated_bytes: Optional[int] = None
    #: ``path:lineno`` of the registration site, for findings
    source: str = ""


_ENTRY_POINTS: Dict[str, EntryPoint] = {}
_DEFAULTS_LOADED = False


def register_entry_point(name: str, *,
                         description: str = "",
                         tags: Sequence[str] = (),
                         wire_dtype: Optional[str] = None,
                         expects_donation: bool = False,
                         donation_min_bytes: int =
                         DEFAULT_DONATION_MIN_BYTES,
                         wire_min_elems: int = 64,
                         in_shardings: Optional[Sequence[Any]] = None,
                         max_replicated_bytes: Optional[int] = None,
                         ) -> Callable[[Callable[[], BuiltEntry]],
                                       Callable[[], BuiltEntry]]:
    """Decorator: register ``build`` as the builder for entry ``name``.

    Re-registering a name replaces the previous entry (so re-importing a
    fixture module in tests is idempotent)."""

    def deco(build: Callable[[], BuiltEntry]) -> Callable[[], BuiltEntry]:
        try:
            src = (inspect.getsourcefile(build) or "?",
                   build.__code__.co_firstlineno)
            source = f"{src[0]}:{src[1]}"
        except (TypeError, OSError):
            source = "?"
        _ENTRY_POINTS[name] = EntryPoint(
            name=name, build=build, description=description,
            tags=tuple(tags), wire_dtype=wire_dtype,
            expects_donation=expects_donation,
            donation_min_bytes=donation_min_bytes,
            wire_min_elems=wire_min_elems,
            in_shardings=(tuple(in_shardings)
                          if in_shardings is not None else None),
            max_replicated_bytes=max_replicated_bytes, source=source)
        return build
    return deco


def all_entry_points() -> Dict[str, EntryPoint]:
    return dict(_ENTRY_POINTS)


def get_entry_point(name: str) -> EntryPoint:
    try:
        return _ENTRY_POINTS[name]
    except KeyError:
        known = sorted(_ENTRY_POINTS)
        raise KeyError(f"unknown entry point {name!r}; known: {known}")


def load_default_entry_points() -> Dict[str, EntryPoint]:
    """Import the subsystem modules whose module scope registers the
    default entry points, then return the registry. The imports are the
    package's own modules (the audited *entry functions* are still only
    abstract-traced, never executed)."""
    global _DEFAULTS_LOADED
    if not _DEFAULTS_LOADED:
        from ..trainer import trainer as _trainer  # noqa: F401
        from ..inference import engine as _engine  # noqa: F401
        from ..parallel import ep_dispatch as _epd  # noqa: F401
        from ..ops import flash_decoding as _fd  # noqa: F401
        from ..ops import ring_attention as _ra  # noqa: F401
        from ..ops import ulysses as _ul  # noqa: F401
        _DEFAULTS_LOADED = True
    return dict(_ENTRY_POINTS)
