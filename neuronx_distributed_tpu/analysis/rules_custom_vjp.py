"""Rule ``custom-vjp``: every ``jax.custom_vjp`` must be completed with a
``defvjp`` call, and the backward's returned tuple must match the primal's
differentiable-argument count.

A ``custom_vjp`` without ``defvjp`` raises only when someone first
differentiates through it; a bwd returning the wrong arity raises a shape
error deep inside backprop on the config that reaches it. Both are paired by
hand in ``parallel/mappings.py`` and the Pallas kernels — exactly the
string-typed drift this linter exists to catch.

Checked forms::

    @jax.custom_vjp                      # or @partial(jax.custom_vjp,
    def f(x, axis): ...                  #       nondiff_argnums=(1,))
    f.defvjp(f_fwd, f_bwd)

    g = jax.custom_vjp(fn, nondiff_argnums=(0,))

The bwd arity check fires only when the bwd function is defined in the same
file and returns a literal tuple; anything dynamic is skipped (no false
positives from conservatively unknown code).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, LintContext, register


@dataclasses.dataclass
class _Primal:
    name: str
    node: ast.AST          # def or assignment site (for the finding location)
    n_args: Optional[int]  # None when unknown (e.g. *args)
    nondiff: int
    has_defvjp: bool = False
    bwd_name: Optional[str] = None


def _int_tuple_len(expr: Optional[ast.AST]) -> Optional[int]:
    """len of a literal tuple/list of ints, 1 for a bare int, else None."""
    if expr is None:
        return 0
    if isinstance(expr, (ast.Tuple, ast.List)):
        return len(expr.elts)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return 1
    return None


def _custom_vjp_decorator(dec: ast.AST) -> Optional[Optional[int]]:
    """Returns the nondiff count when ``dec`` is a custom_vjp decorator
    (0 when none given, None-inner when unparseable), or raises StopIteration
    semantics via a sentinel: returns None when not a custom_vjp decorator.
    """
    if astutil.tail_name(dec) == "custom_vjp":
        return 0
    if isinstance(dec, ast.Call):
        if astutil.tail_name(dec.func) == "custom_vjp":
            return _int_tuple_len(astutil.get_kwarg(dec, "nondiff_argnums"))
        if astutil.tail_name(dec.func) == "partial" and dec.args and \
                astutil.tail_name(dec.args[0]) == "custom_vjp":
            return _int_tuple_len(astutil.get_kwarg(dec, "nondiff_argnums"))
    return None


def _def_arity(fn: ast.AST) -> Optional[int]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    if fn.args.vararg is not None:
        return None
    return len(astutil.positional_args(fn))


def _literal_return_lens(fn: ast.AST) -> List[Tuple[ast.Return, int]]:
    """(return-node, tuple-len) for every literal-tuple return directly in
    ``fn`` (nested defs excluded)."""
    out: List[Tuple[ast.Return, int]] = []
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return out
    for node in astutil.walk_stop_at_functions(fn):
        if isinstance(node, ast.Return) and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            out.append((node, len(node.value.elts)))
    return out


@register(
    "custom-vjp",
    "jax.custom_vjp primals must call defvjp, and the bwd must return a "
    "tuple matching the primal's differentiable-argument count")
def check(ctx: LintContext) -> Iterator[Finding]:
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    primals: Dict[str, _Primal] = {}

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                nd = _custom_vjp_decorator(dec)
                if nd is None:
                    continue
                primals[node.name] = _Primal(
                    node.name, node, _def_arity(node), nd or 0)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if astutil.tail_name(call.func) != "custom_vjp":
                continue
            if len(node.targets) != 1 or \
                    not isinstance(node.targets[0], ast.Name):
                continue
            tgt = node.targets[0].id
            nd = _int_tuple_len(astutil.get_kwarg(call, "nondiff_argnums"))
            n_args = None
            if call.args and isinstance(call.args[0], ast.Name):
                n_args = _def_arity(defs.get(call.args[0].id))
            primals[tgt] = _Primal(tgt, node, n_args, nd or 0)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not isinstance(node.func, ast.Attribute) or \
                node.func.attr != "defvjp":
            continue
        owner = node.func.value
        if not isinstance(owner, ast.Name) or owner.id not in primals:
            continue
        p = primals[owner.id]
        p.has_defvjp = True
        bwd = astutil.get_kwarg(node, "bwd")
        if bwd is None and len(node.args) >= 2:
            bwd = node.args[1]
        if isinstance(bwd, ast.Name):
            p.bwd_name = bwd.id

    for p in primals.values():
        if not p.has_defvjp:
            yield Finding(
                ctx.path, p.node.lineno, p.node.col_offset, "custom-vjp",
                f"custom_vjp {p.name!r} never calls {p.name}.defvjp(fwd, "
                "bwd) — differentiating through it will raise at trace time")
            continue
        if p.n_args is None or p.bwd_name is None:
            continue
        bwd_def = defs.get(p.bwd_name)
        if bwd_def is None:
            continue
        expected = p.n_args - p.nondiff
        for ret, n in _literal_return_lens(bwd_def):
            if n != expected:
                yield Finding(
                    ctx.path, ret.lineno, ret.col_offset, "custom-vjp",
                    f"bwd {p.bwd_name!r} of custom_vjp {p.name!r} returns a "
                    f"{n}-tuple but the primal has {expected} "
                    f"differentiable arg(s) ({p.n_args} args, {p.nondiff} "
                    "nondiff) — cotangent arity mismatch")
