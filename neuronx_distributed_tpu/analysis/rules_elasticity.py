"""Rule ``elasticity``: serving executables must go through the AOT cache.

The elastic-fleet contract (``docs/serving.md``) is that replica spin-up —
scale-up, failover revival, disaggregated worker registration — *loads* a
serialized executable instead of recompiling it, so a new replica is
serving in milliseconds instead of minutes. Two anti-patterns silently
reintroduce compile-on-scale:

* **Constructing ``ServingEngine(...)`` without ``aot_cache=``** in
  serving paths — the engine falls back to plain ``jax.jit``, every
  spin-up pays a cold compile, and the fleet's cold-start SLO quietly
  regresses from milliseconds to minutes.

* **Raw ``.lower(...).compile(...)`` chains** in ``inference/`` — AOT
  compilation outside :meth:`AotExecutableCache.compile_or_load` is
  invisible to the cache: the executable is rebuilt on every process and
  never persisted for the next replica.

``aot_cache.py`` itself (the one sanctioned compile site) and
``model_builder.py`` (whose ``compile()`` is the cache-aware entry point
with an explicit uncached fallback) are exempt by filename.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import astutil
from .core import Finding, LintContext, register

_ENGINE_CTORS = ("ServingEngine",)


def _is_lower_compile(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — an AOT compile chain."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "compile"):
        return False
    inner = f.value
    return (isinstance(inner, ast.Call)
            and isinstance(inner.func, ast.Attribute)
            and inner.func.attr == "lower")


@register(
    "elasticity",
    "serving engine/worker construction in inference/ that bypasses the "
    "AOT executable cache (ServingEngine without aot_cache=, raw "
    ".lower().compile() chains) — reintroduces compile-on-scale",
    scope=("inference",),
    exempt=("aot_cache.py", "model_builder.py"))
def check(ctx: LintContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.tail_name(node.func)
        if name in _ENGINE_CTORS:
            kwargs = {kw.arg for kw in node.keywords}
            if "aot_cache" not in kwargs and None not in kwargs:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "elasticity",
                    f"`{name}(...)` without `aot_cache=` — this replica "
                    "cold-compiles on every spin-up instead of loading "
                    "the fleet's serialized executable; pass the shared "
                    "AotExecutableCache (or aot_cache=None explicitly "
                    "for a deliberately uncached engine)")
        elif _is_lower_compile(node):
            yield Finding(
                ctx.path, node.lineno, node.col_offset, "elasticity",
                "raw `.lower(...).compile(...)` in a serving path — AOT "
                "compilation outside AotExecutableCache.compile_or_load "
                "is never persisted, so every new replica recompiles; "
                "route it through the cache")
