"""nxdlint tier 3: jaxpr-level program auditor (``--jaxpr``).

Abstract-traces registered entry points (:mod:`.audit_registry`) with
``jax.make_jaxpr`` on the CPU backend — tracing evaluates shapes and
dtypes only, the entry function itself is never executed — then walks
the ClosedJaxpr for contracts the syntactic tiers cannot see:

* ``jaxpr-host-callback`` — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` (``jax.debug.print``) equations reachable from
  compiled code: host round-trips that stall the device every step and
  violate the no-callbacks serving invariant.
* ``jaxpr-collective-scope`` — collective equations (``psum``,
  ``ppermute``, ``all_gather``, ``all_to_all``, ...) outside any
  ``shard_map`` scope: axis semantics smuggled in via ``vmap(...,
  axis_name=...)`` or stale manual-collective code paths that GSPMD will
  not partition the way the mesh intends.
* ``jaxpr-undonated-buffer`` — entry points tagged
  ``expects_donation`` (train steps) whose top-level ``pjit`` donates
  none of its large input buffers: optimizer state is double-buffered
  and HBM headroom silently halves.
* ``jaxpr-wire-precision`` — ring hops (``ppermute``/``all_to_all``)
  shipping >= 4-byte float payloads in an entry registered with a wire
  codec (``wire_dtype=``): the ring moves 4x the bytes the codec
  promises.

Each violation maps to a stable rule ID (above) and is reported at the
entry point's registration site, so baselines and SARIF work the same
as for the syntactic tiers.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

from .audit_registry import (EntryPoint, all_entry_points,
                             load_default_entry_points)
from .core import Finding

#: stable rule IDs -> short description (merged into ``--list-rules``,
#: ``--explain`` and the SARIF rule catalog)
RULES: Dict[str, str] = {
    "jaxpr-host-callback":
        "pure_callback/io_callback/debug_callback reachable from compiled "
        "code — a host round-trip on every step; compute on-device or "
        "record host-side around the call",
    "jaxpr-collective-scope":
        "collective equation outside any shard_map scope — axis semantics "
        "via vmap(axis_name=...) or manual collectives that GSPMD will "
        "not partition as the mesh intends; wrap the region in "
        "parallel.mesh.shard_map",
    "jaxpr-undonated-buffer":
        "train-step entry whose top-level pjit donates none of its large "
        "input buffers — state is double-buffered and HBM headroom "
        "halves; pass donate_argnums for the state argument",
    "jaxpr-wire-precision":
        "full-precision ring hop (ppermute/all_to_all on >=4-byte "
        "floats) in an entry registered with a wire codec — ships 4x "
        "the bytes the codec promises; route the hop through the wire "
        "quantizer",
    "jaxpr-audit-error":
        "the entry point's builder or abstract trace failed — the "
        "contract cannot be audited until the build is fixed",
}

_CALLBACK_PRIMS = frozenset(
    {"pure_callback", "io_callback", "debug_callback"})
_COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "pmean",
})
_RING_PRIMS = frozenset({"ppermute", "all_to_all"})
#: primitives that establish a legitimate manual-collective scope
_SCOPE_PRIMS = frozenset({"shard_map", "xla_pmap", "pmap"})


def ensure_cpu_backend(n_devices: int = 8) -> None:
    """Pin the audit to the host backend with a virtual multi-device
    mesh. Effective as long as no backend initialised yet in this
    process (importing jax alone is fine); afterwards the caller's
    backend stands."""
    from ..utils.cpu_mesh import force_cpu_platform
    force_cpu_platform(n_devices)


def _entry_location(ep: EntryPoint) -> Tuple[str, int]:
    path, _, line = ep.source.rpartition(":")
    try:
        return (path or ep.source), int(line)
    except ValueError:
        return ep.source, 1


def _subjaxprs(params: Dict[str, Any]) -> Iterator[Any]:
    """Inner jaxprs of an equation: pjit/shard_map bodies, scan/while
    bodies, cond branches — found structurally in the eqn params."""
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "jaxpr"):     # ClosedJaxpr
                yield x.jaxpr
            elif hasattr(x, "eqns"):    # raw Jaxpr
                yield x


def _iter_eqns(jaxpr: Any,
               in_scope: bool = False) -> Iterator[Tuple[Any, bool]]:
    for eqn in jaxpr.eqns:
        yield eqn, in_scope
        inner_scope = in_scope or eqn.primitive.name in _SCOPE_PRIMS
        for sub in _subjaxprs(eqn.params):
            yield from _iter_eqns(sub, inner_scope)


def _aval_bytes(aval: Any) -> int:
    try:
        return int(aval.size) * int(aval.dtype.itemsize)
    except (AttributeError, TypeError):
        return 0


def _aval_str(aval: Any) -> str:
    try:
        return f"{aval.dtype.name}[{','.join(str(d) for d in aval.shape)}]"
    except AttributeError:
        return str(aval)


def _is_wide_float(aval: Any) -> bool:
    try:
        import numpy as np
        return (np.issubdtype(aval.dtype, np.floating)
                and aval.dtype.itemsize >= 4)
    except (AttributeError, TypeError):
        return False


def audit_entry_point(ep: EntryPoint) -> List[Finding]:
    """Build, abstract-trace and audit one entry point."""
    import jax

    path, line = _entry_location(ep)
    try:
        built = ep.build()
        closed = jax.make_jaxpr(built.fn)(*built.args)
    except Exception as e:  # surfaced as a finding, not a crash
        return [Finding(path, line, 0, "jaxpr-audit-error",
                        f"entry point '{ep.name}': build/trace failed: "
                        f"{type(e).__name__}: {e}")]

    findings: List[Finding] = []

    def flag(rule: str, message: str) -> None:
        findings.append(Finding(path, line, 0, rule,
                                f"entry point '{ep.name}': {message}"))

    top_pjit = [eqn for eqn in closed.jaxpr.eqns
                if eqn.primitive.name == "pjit"]

    for eqn, in_scope in _iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            flag("jaxpr-host-callback",
                 f"{name} reachable from compiled code — every step "
                 "round-trips to the host; compute on-device or move the "
                 "host work outside the compiled call")
        elif name in _COLLECTIVE_PRIMS and not in_scope:
            opnd = _aval_str(eqn.invars[0].aval) if eqn.invars else "?"
            flag("jaxpr-collective-scope",
                 f"collective '{name}' on {opnd} outside any shard_map "
                 "scope — wrap the region in parallel.mesh.shard_map so "
                 "the axis semantics match the mesh instead of being "
                 "smuggled in via vmap(axis_name=...)")
        if name in _RING_PRIMS and ep.wire_dtype is not None:
            for v in eqn.invars:
                aval = getattr(v, "aval", None)
                if aval is None or not _is_wide_float(aval):
                    continue
                try:
                    elems = int(aval.size)
                except (AttributeError, TypeError):
                    continue
                if elems >= ep.wire_min_elems:
                    flag("jaxpr-wire-precision",
                         f"ring hop '{name}' ships {_aval_str(aval)} at "
                         f"full precision while the entry is registered "
                         f"with wire_dtype='{ep.wire_dtype}' — quantize "
                         "the hop through the wire codec")

    if ep.expects_donation:
        if top_pjit:
            for eqn in top_pjit:
                donated = tuple(eqn.params.get("donated_invars", ()))
                large = [i for i, v in enumerate(eqn.invars)
                         if _aval_bytes(getattr(v, "aval", None))
                         >= ep.donation_min_bytes]
                if large and not any(donated[i] for i in large
                                     if i < len(donated)):
                    biggest = max(
                        large,
                        key=lambda i: _aval_bytes(eqn.invars[i].aval))
                    flag("jaxpr-undonated-buffer",
                         f"no large input buffer is donated (largest: "
                         f"{_aval_str(eqn.invars[biggest].aval)}) — the "
                         "step double-buffers its state; pass "
                         "donate_argnums for the state argument")
        elif not built.donate_argnums:
            large_avals = [v.aval for v in closed.jaxpr.invars
                           if _aval_bytes(v.aval) >= ep.donation_min_bytes]
            if large_avals:
                flag("jaxpr-undonated-buffer",
                     f"no large input buffer is donated (largest: "
                     f"{_aval_str(max(large_avals, key=_aval_bytes))}) — "
                     "the step double-buffers its state; pass "
                     "donate_argnums for the state argument")
    return findings


def audit_entry_points(names: Optional[Iterable[str]] = None,
                       include_defaults: bool = True) -> List[Finding]:
    """Audit the selected (default: all registered) entry points."""
    entries = (load_default_entry_points() if include_defaults
               else all_entry_points())
    if names is not None:
        names = list(names)
        unknown = [n for n in names if n not in entries]
        if unknown:
            raise ValueError(
                f"unknown entry point(s): {unknown}; "
                f"known: {sorted(entries)}")
        entries = {n: entries[n] for n in names}
    findings: List[Finding] = []
    for name in sorted(entries):
        findings.extend(audit_entry_point(entries[name]))
    return findings
