"""Rule ``observability``: instrumentation that lies, and prints that
bypass it.

Three failure classes the ``obs`` subsystem makes tempting:

* **Host clock reads inside JAX-traced code** — ``time.time()`` /
  ``time.perf_counter()`` (and friends) in a ``jit``/``shard_map``/
  ``scan`` body run once at *trace* time: the recorded "timestamp" is a
  compile-time constant baked into every execution, so the measurement
  is silently wrong forever. Spans and timers belong *around* the
  compiled call, on the host.

* **Metric-record calls inside traced code** — ``counter.inc()``,
  ``gauge.dec()``, ``histogram.observe()``, ``tracer.span()`` and the
  Timeline ``mark_event_*`` surface are host-side APIs; inside traced
  code they fire once per trace (counting compiles, not events) and are
  exactly the host callbacks the no-callbacks invariant forbids. Only
  attribute calls (``x.inc(...)``) are matched — ``.set`` is deliberately
  not in the list (``x.at[i].set(...)`` is core JAX).

* **Bare ``print()`` in library modules** — output that bypasses the
  logger (rank-0 gating, levels) and the event channel (metrics, NXD_EVENT
  parsing). ``print(..., file=...)`` is considered deliberate stream
  writing and allowed. Exempt: ``obs``/``scripts``/``examples`` path
  segments, ``__main__.py`` CLI entry points, and test files
  (``test_*.py`` / ``conftest.py``).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List

from . import astutil, dataflow
from .core import Finding, LintContext, register
from .rules_trace_safety import _traced_function_nodes

#: zero-arg wall/CPU clock reads that become trace-time constants.
#: ``time.sleep`` is NOT here — the resilience rule owns it.
_CLOCKS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "process_time", "process_time_ns",
})

#: method tails of the obs record surface (attribute calls only).
_METRIC_TAILS = frozenset({
    "inc", "dec", "observe", "span",
    "mark_event_start", "mark_event_end",
})

_PRINT_EXEMPT_SEGMENTS = ("obs", "scripts", "examples")


def _is_clock_call(call: ast.Call) -> bool:
    tail = astutil.tail_name(call.func)
    if tail not in _CLOCKS:
        return False
    root = astutil.root_name(call.func)
    # time.perf_counter(...) or `from time import perf_counter` bare form
    return root == "time" or root == tail


def _is_metric_call(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in _METRIC_TAILS)


def _is_bare_print(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Name) and call.func.id == "print"
            and not any(kw.arg == "file" for kw in call.keywords))


def _print_exempt(path: str) -> bool:
    norm = path.replace("\\", "/")
    base = os.path.basename(norm)
    if base == "__main__.py" or base == "conftest.py" \
            or base.startswith("test_"):
        return True
    parts = norm.split("/")
    return any(seg in _PRINT_EXEMPT_SEGMENTS for seg in parts)


@register(
    "observability",
    "host clock reads / metric-record calls inside JAX-traced code "
    "(trace-time constants, not measurements) and bare print() in "
    "library modules (bypasses the logger and the obs event channel)")
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []

    traced = _traced_function_nodes(ctx.tree)
    if traced:
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            if id(node) not in traced:
                continue
            body = node.body if isinstance(node, ast.Lambda) else node
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                if _is_clock_call(sub):
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "observability",
                        "host clock read inside a JAX-traced function is "
                        "a trace-time constant, not a measurement — time "
                        "the compiled call from the host (obs tracer "
                        "span) instead"))
                elif _is_metric_call(sub):
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "observability",
                        f".{sub.func.attr}() inside a JAX-traced function "
                        "records once per trace, not per execution — and "
                        "is a host callback in compiled code; move the "
                        "metric/span to the host side around the call"))
                elif ctx.dataflow is not None and dataflow.HOST_TIME \
                        in ctx.dataflow.call_intrinsic(sub):
                    # tier-2 taint: a local helper whose body reads the
                    # host clock — the indirection hides the same
                    # trace-time constant from the name-level check
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "observability",
                        "call to a local helper that reads the host "
                        "clock, inside a JAX-traced function — the clock "
                        "read still happens once at trace time; time the "
                        "compiled call from the host (obs tracer span) "
                        "instead"))

    if not _print_exempt(ctx.path):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_bare_print(node):
                findings.append(Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "observability",
                    "bare print() in a library module bypasses the "
                    "rank-aware logger and the obs event channel — use "
                    "utils.logger.get_logger / log_event (or print with "
                    "an explicit file= for deliberate stream output)"))

    yield from findings
