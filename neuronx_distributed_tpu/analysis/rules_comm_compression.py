"""Rule ``comm-compression``: raw gradient collectives bypass the
compression layer.

A raw ``lax.pmean``/``lax.psum`` on a gradient bypasses everything
``parallel.grads.allreduce_gradients`` layers on top of the collective:
FSDP-aware axis skipping from the param specs, the quantized int8/fp8 wire
format, hierarchical fast/slow staging, and the error-feedback residue
(docs/comm_compression.md). It also fragments the hot path the
``grad_comm_*`` config fields are supposed to control — a model whose
gradients are pmean'd inline stays fp32 no matter what the config says.

The rule fires on ``lax.pmean``/``lax.psum`` calls whose first argument
carries the GRADIENT value kind (PR 14: the tier-2 dataflow engine —
``jax.grad``/``value_and_grad`` outputs tracked through renames, tuple
unpacking and helper calls; gradient-*named* variables seed the same
lattice, so every v1 finding is preserved) outside ``parallel/`` and
``pipeline/`` — the wrappers themselves (and the pipeline stage rings,
which own their collectives by contract) legitimately issue raw
collectives. In heuristics-only mode the name regexes alone decide.

Activation extension (PR 9): when a compression config is in scope —
the module imports ``wire_codec``/``comm_compressed`` or references
``CompressionConfig``/``tp_activation_comm_dtype``/
``activation_comm_dtype`` — raw ``lax.psum``/``lax.pmean``/
``lax.all_gather`` calls on activation-named variables also fire: the
module has opted into quantized activation wires, so a full-precision
collective silently ships 4x the bytes the config promises. Route these
through the parallel-layer primitives (or
``ops.collective_matmul.*(..., wire=...)``) instead. Modules with no
compression config in scope are untouched — plain activation
collectives remain the model's own business. ``ops/`` is exempt like
``parallel/``: the decomposed primitives compose raw collectives with
the codec by design.

EP-dispatch extension (PR 13): under the same in-scope condition (now
also armed by ``moe_ep_wire_dtype``/``ep_wire_dtype``/``ep_dispatch``
references), raw ``lax.all_to_all``/``lax.ppermute`` calls on
dispatch-named variables (``dispatch*``/``chunks``/``routed*``/
``payload*``/``send``/``recv``) also fire: token dispatch payloads are
exactly what ``parallel.ep_dispatch.gather_token_chunks`` /
``combine_token_chunks(..., wire=wire_config(...))`` quantize and
overlap, so a full-precision monolithic exchange next to an EP wire
config ships 4x the configured bytes and serializes the ring
(docs/moe.md).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from . import astutil, dataflow
from .core import Finding, LintContext, register

# name heuristics live in dataflow.py now (they seed the taint lattice);
# kept as module aliases for the heuristics-only (v1) fallback path
_GRAD_NAME = dataflow.GRAD_NAME
_ACT_NAME = dataflow.ACT_NAME


def _in_ops_package(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/ops/" in norm or norm.startswith("ops/")


# a compression config is "in scope" when the module references the codec
# or one of the activation-wire knobs — only then do full-precision
# activation collectives contradict the module's own configuration
_COMPRESSION_IN_SCOPE = re.compile(
    r"\b(wire_codec|comm_compressed|CompressionConfig|"
    r"tp_activation_comm_dtype|activation_comm_dtype|"
    r"moe_ep_wire_dtype|ep_wire_dtype|ep_dispatch)\b")

_ACT_COLLECTIVES = ("pmean", "psum", "all_gather")

_DISPATCH_NAME = dataflow.DISPATCH_NAME

_DISPATCH_COLLECTIVES = ("all_to_all", "ppermute")


def _gradient_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _GRAD_NAME.search(name))


def _activation_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _ACT_NAME.search(name))


def _dispatch_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _DISPATCH_NAME.search(name))


@register(
    "comm-compression",
    "raw lax.pmean/lax.psum on gradient-valued variables outside "
    "parallel/ — use parallel.grads.allreduce_gradients so spec-aware "
    "skipping, quantization and error feedback apply",
    exempt=("parallel", "pipeline"))
def check(ctx: LintContext) -> Iterator[Finding]:
    # declarative exempt: parallel/ (the wrappers themselves issue raw
    # collectives) and pipeline/ (stage grad rings own their collectives
    # and stay uncompressed by design — see make_train_step's contract)
    act_scope = (not _in_ops_package(ctx.path)
                 and _COMPRESSION_IN_SCOPE.search(ctx.source) is not None)
    df = ctx.dataflow

    def has_kind(node: ast.AST, kind: str, named) -> bool:
        # tier-2 taint subsumes the name heuristic (names seed the
        # lattice); heuristics-only mode falls back to the regex
        if df is not None:
            return kind in df.expr_kinds(node)
        return named(node)

    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.tail_name(node.func)
        if tail in ("pmean", "psum") and node.args \
                and has_kind(node.args[0], dataflow.GRADIENT,
                             _gradient_named):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"raw lax.{tail} on a gradient — use "
                "parallel.grads.allreduce_gradients(..., specs=, "
                "compression=) so FSDP-spec skipping, quantized wire "
                "formats and error feedback apply "
                "(docs/comm_compression.md)"))
            continue
        if act_scope and tail in _ACT_COLLECTIVES and node.args \
                and has_kind(node.args[0], dataflow.ACTIVATION,
                             _activation_named):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"full-precision lax.{tail} on an activation in a module "
                "with an activation-compression config in scope — the "
                "collective ships the fp32 wire the config promises to "
                "quantize; route it through the parallel layers or "
                "ops.collective_matmul(..., wire=wire_config(...)) "
                "(docs/comm_compression.md)"))
            continue
        if act_scope and tail in _DISPATCH_COLLECTIVES and node.args \
                and has_kind(node.args[0], dataflow.DISPATCH_PAYLOAD,
                             _dispatch_named):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"full-precision lax.{tail} on an EP dispatch payload in a "
                "module with a wire-codec config in scope — the monolithic "
                "exchange ships the fp32 wire the config promises to "
                "quantize and serializes against the expert compute; use "
                "parallel.ep_dispatch.gather_token_chunks / "
                "combine_token_chunks(..., wire=wire_config(...)) "
                "(docs/moe.md)"))
    yield from findings
