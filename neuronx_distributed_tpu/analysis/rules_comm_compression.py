"""Rule ``comm-compression``: raw gradient collectives bypass the
compression layer.

A raw ``lax.pmean``/``lax.psum`` on a gradient bypasses everything
``parallel.grads.allreduce_gradients`` layers on top of the collective:
FSDP-aware axis skipping from the param specs, the quantized int8/fp8 wire
format, hierarchical fast/slow staging, and the error-feedback residue
(docs/comm_compression.md). It also fragments the hot path the
``grad_comm_*`` config fields are supposed to control — a model whose
gradients are pmean'd inline stays fp32 no matter what the config says.

The rule fires on ``lax.pmean``/``lax.psum`` calls whose first argument is
a gradient-named variable (``grad``/``grads``/``g_``.../``*_grad*``)
outside ``parallel/`` — inside the package the wrappers themselves (and
the compressed collectives) legitimately issue raw collectives.

Activation extension (PR 9): when a compression config is in scope —
the module imports ``wire_codec``/``comm_compressed`` or references
``CompressionConfig``/``tp_activation_comm_dtype``/
``activation_comm_dtype`` — raw ``lax.psum``/``lax.pmean``/
``lax.all_gather`` calls on activation-named variables also fire: the
module has opted into quantized activation wires, so a full-precision
collective silently ships 4x the bytes the config promises. Route these
through the parallel-layer primitives (or
``ops.collective_matmul.*(..., wire=...)``) instead. Modules with no
compression config in scope are untouched — plain activation
collectives remain the model's own business. ``ops/`` is exempt like
``parallel/``: the decomposed primitives compose raw collectives with
the codec by design.

EP-dispatch extension (PR 13): under the same in-scope condition (now
also armed by ``moe_ep_wire_dtype``/``ep_wire_dtype``/``ep_dispatch``
references), raw ``lax.all_to_all``/``lax.ppermute`` calls on
dispatch-named variables (``dispatch*``/``chunks``/``routed*``/
``payload*``/``send``/``recv``) also fire: token dispatch payloads are
exactly what ``parallel.ep_dispatch.gather_token_chunks`` /
``combine_token_chunks(..., wire=wire_config(...))`` quantize and
overlap, so a full-precision monolithic exchange next to an EP wire
config ships 4x the configured bytes and serializes the ring
(docs/moe.md).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from . import astutil
from .core import Finding, LintContext, register
from .rules_tp_overlap import _ACT_NAME

# identifier looks like a gradient: 'grad', 'grads', 'gradients', 'dw',
# 'g_acc', 'clipped_grads', ... — substring 'grad' or the g/dgrad naming
# convention with a separator
_GRAD_NAME = re.compile(r"(^|_)grads?(_|$)|gradient|(^|_)g(acc|sum)?(_|$)",
                        re.IGNORECASE)


def _in_parallel_package(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/parallel/" in norm or norm.startswith("parallel/")


def _in_ops_package(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/ops/" in norm or norm.startswith("ops/")


# a compression config is "in scope" when the module references the codec
# or one of the activation-wire knobs — only then do full-precision
# activation collectives contradict the module's own configuration
_COMPRESSION_IN_SCOPE = re.compile(
    r"\b(wire_codec|comm_compressed|CompressionConfig|"
    r"tp_activation_comm_dtype|activation_comm_dtype|"
    r"moe_ep_wire_dtype|ep_wire_dtype|ep_dispatch)\b")

_ACT_COLLECTIVES = ("pmean", "psum", "all_gather")

# identifier looks like an EP dispatch payload: the token chunks shipped
# between expert shards ('dispatch_buf', 'chunks', 'routed_tokens',
# 'payload', 'send'/'recv' buffers) — activation/loss/param names must
# NOT match so plain shuffles stay the model's own business
_DISPATCH_NAME = re.compile(
    r"dispatch|(^|_)chunks?(_|$)|routed|payload|(^|_)(send|recv)(buf)?(_|$)",
    re.IGNORECASE)

_DISPATCH_COLLECTIVES = ("all_to_all", "ppermute")


def _gradient_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _GRAD_NAME.search(name))


def _activation_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _ACT_NAME.search(name))


def _dispatch_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _DISPATCH_NAME.search(name))


@register(
    "comm-compression",
    "raw lax.pmean/lax.psum on gradient-named variables outside parallel/ "
    "— use parallel.grads.allreduce_gradients so spec-aware skipping, "
    "quantization and error feedback apply")
def check(ctx: LintContext) -> Iterator[Finding]:
    if _in_parallel_package(ctx.path):
        return
    act_scope = (not _in_ops_package(ctx.path)
                 and _COMPRESSION_IN_SCOPE.search(ctx.source) is not None)
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.tail_name(node.func)
        if tail in ("pmean", "psum") and node.args \
                and _gradient_named(node.args[0]):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"raw lax.{tail} on a gradient — use "
                "parallel.grads.allreduce_gradients(..., specs=, "
                "compression=) so FSDP-spec skipping, quantized wire "
                "formats and error feedback apply "
                "(docs/comm_compression.md)"))
            continue
        if act_scope and tail in _ACT_COLLECTIVES and node.args \
                and _activation_named(node.args[0]):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"full-precision lax.{tail} on an activation in a module "
                "with an activation-compression config in scope — the "
                "collective ships the fp32 wire the config promises to "
                "quantize; route it through the parallel layers or "
                "ops.collective_matmul(..., wire=wire_config(...)) "
                "(docs/comm_compression.md)"))
            continue
        if act_scope and tail in _DISPATCH_COLLECTIVES and node.args \
                and _dispatch_named(node.args[0]):
            findings.append(Finding(
                ctx.path, node.lineno, node.col_offset, "comm-compression",
                f"full-precision lax.{tail} on an EP dispatch payload in a "
                "module with a wire-codec config in scope — the monolithic "
                "exchange ships the fp32 wire the config promises to "
                "quantize and serializes against the expert compute; use "
                "parallel.ep_dispatch.gather_token_chunks / "
                "combine_token_chunks(..., wire=wire_config(...)) "
                "(docs/moe.md)"))
    yield from findings
