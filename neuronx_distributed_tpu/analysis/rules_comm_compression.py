"""Rule ``comm-compression``: raw gradient collectives bypass the
compression layer.

A raw ``lax.pmean``/``lax.psum`` on a gradient bypasses everything
``parallel.grads.allreduce_gradients`` layers on top of the collective:
FSDP-aware axis skipping from the param specs, the quantized int8/fp8 wire
format, hierarchical fast/slow staging, and the error-feedback residue
(docs/comm_compression.md). It also fragments the hot path the
``grad_comm_*`` config fields are supposed to control — a model whose
gradients are pmean'd inline stays fp32 no matter what the config says.

The rule fires on ``lax.pmean``/``lax.psum`` calls whose first argument is
a gradient-named variable (``grad``/``grads``/``g_``.../``*_grad*``)
outside ``parallel/`` — inside the package the wrappers themselves (and
the compressed collectives) legitimately issue raw collectives.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from . import astutil
from .core import Finding, LintContext, register

# identifier looks like a gradient: 'grad', 'grads', 'gradients', 'dw',
# 'g_acc', 'clipped_grads', ... — substring 'grad' or the g/dgrad naming
# convention with a separator
_GRAD_NAME = re.compile(r"(^|_)grads?(_|$)|gradient|(^|_)g(acc|sum)?(_|$)",
                        re.IGNORECASE)


def _in_parallel_package(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/parallel/" in norm or norm.startswith("parallel/")


def _gradient_named(node: ast.AST) -> bool:
    name = astutil.tail_name(node)
    if name is None and isinstance(node, ast.Name):
        name = node.id
    return bool(name and _GRAD_NAME.search(name))


@register(
    "comm-compression",
    "raw lax.pmean/lax.psum on gradient-named variables outside parallel/ "
    "— use parallel.grads.allreduce_gradients so spec-aware skipping, "
    "quantization and error feedback apply")
def check(ctx: LintContext) -> Iterator[Finding]:
    if _in_parallel_package(ctx.path):
        return
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.tail_name(node.func)
        if tail not in ("pmean", "psum"):
            continue
        if not node.args or not _gradient_named(node.args[0]):
            continue
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "comm-compression",
            f"raw lax.{tail} on a gradient — use "
            "parallel.grads.allreduce_gradients(..., specs=, compression=) "
            "so FSDP-spec skipping, quantized wire formats and error "
            "feedback apply (docs/comm_compression.md)"))
    yield from findings
