"""nxdlint tier 2: intraprocedural def-use dataflow (value-kind taint).

The tier-1 rules key on identifier *names* ("grads", "dispatch_buf", ...),
so a single rename defeats them.  This module tracks what a value *is*
through the statements of each scope, so ``g2 = rename(grads)[0]`` still
carries the GRADIENT kind and a raw ``lax.pmean(g2, "dp")`` still fires.

Value kinds (the lattice is a powerset of these — union on merge):

* ``GRADIENT``          — outputs of ``jax.grad`` / ``jax.value_and_grad``
                          and gradient-named seeds.
* ``ACTIVATION``        — layer-forward outputs (``model.apply``-style
                          calls) and activation-named seeds.
* ``DISPATCH_PAYLOAD``  — ``parallel.ep_dispatch.gather_token_chunks``
                          results and dispatch-named seeds.
* ``KV_BLOCK``          — paged-KV block handles (name seeds only).
* ``HOST_TIME``         — ``time.time()``-family wall/CPU clock reads.

Propagation is flow-insensitive within a scope (a fixpoint over the
scope's statements): aliases, tuple unpacking, ``AugAssign``, arithmetic,
subscripts, and calls to *local* functions via per-function summaries
(which arguments pass through to which return elements, plus the kinds
the body produces intrinsically).

Kind-specific call policy: GRADIENT, DISPATCH_PAYLOAD and HOST_TIME flow
through arbitrary call sites (a clipped gradient is still a gradient);
ACTIVATION and KV_BLOCK only flow through identity-ish constructs
(aliasing, tuple unpack, subscripts, summary passthrough) — ``f(x)`` of
an activation is usually a loss/score/norm, and ``x`` is far too common
a name to union through every call.

Provenance: :meth:`ModuleDataflow.provenance` classifies a node as
``"traced"`` (inside a JAX-traced function per the trace-safety
analysis) or ``"host"``.

Everything here is stdlib-``ast`` only — the analyzed file is never
imported or executed.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Union

from . import astutil

# ---------------------------------------------------------------------------
# Kinds
# ---------------------------------------------------------------------------

GRADIENT = "gradient"
ACTIVATION = "activation"
DISPATCH_PAYLOAD = "dispatch-payload"
KV_BLOCK = "kv-block"
HOST_TIME = "host-time"

#: the real (externally visible) kinds
KINDS: FrozenSet[str] = frozenset(
    {GRADIENT, ACTIVATION, DISPATCH_PAYLOAD, KV_BLOCK, HOST_TIME})

TRACED = "traced"
HOST = "host"

# internal pseudo-kinds — never escape through expr_kinds()
_GRAD_FN = "pseudo:grad-fn"        # gfn = jax.grad(f)
_VAG_FN = "pseudo:vag-fn"          # vfn = jax.value_and_grad(f)
_VAG_RESULT = "pseudo:vag-result"  # pair = vfn(params)  ->  (value, grads)
_ARG = "pseudo:a"                  # summary marker: identity flow of arg i
_ARGC = "pseudo:c"                 # summary marker: call-filtered flow of arg i

#: *weak* kinds are name-seeded ("grads" is probably a gradient); they
#: flow through identity-ish constructs only (aliasing, tuple unpack,
#: subscripts, summary passthrough) — flowing a guess through every call
#: argument would let a loop counter named ``g`` taint whole functions.
#: Structural seeds (actual ``jax.grad`` outputs, ``gather_token_chunks``
#: results, clock reads) are certain and survive call boundaries.
_WEAK = "weak:"

#: structurally-seeded kinds that survive an arbitrary call boundary
_CALL_TRANSPARENT: FrozenSet[str] = frozenset(
    {GRADIENT, DISPATCH_PAYLOAD, HOST_TIME})


def _promote(kinds: Set[str]) -> Set[str]:
    """Weak kinds become real at the query boundary."""
    out = set()
    for k in kinds:
        out.add(k[len(_WEAK):] if k.startswith(_WEAK) else k)
    return out

# ---------------------------------------------------------------------------
# Name seeds (the tier-1 heuristics, now feeding the taint lattice)
# ---------------------------------------------------------------------------

#: identifier looks like a gradient: 'grad', 'grads', 'gradients', 'g_acc',
#: 'clipped_grads', ... — substring 'grad' or the g/gacc/gsum convention
#: with a separator
GRAD_NAME = re.compile(r"(^|_)grads?(_|$)|gradient|(^|_)g(acc|sum)?(_|$)",
                       re.IGNORECASE)

#: activation-flavoured identifiers: the single-letter conventions (x, h,
#: y) plus the spelled-out ones; gradient/weight names must NOT match so
#: gradient psums stay the comm-compression rule's business
ACT_NAME = re.compile(
    r"^(x|h|y|xs|hs|out|attn_out|mlp_out)$|hidden|activation|(^|_)acts?(_|$)",
    re.IGNORECASE)

#: identifier looks like an EP dispatch payload: the token chunks shipped
#: between expert shards — activation/loss/param names must NOT match
DISPATCH_NAME = re.compile(
    r"dispatch|(^|_)chunks?(_|$)|routed|payload|(^|_)(send|recv)(buf)?(_|$)",
    re.IGNORECASE)

#: paged-KV block handles / tables
KV_NAME = re.compile(
    r"(^|_)kv(_|$)|kv_cache|(^|_)blocks?(_|$)|block_tables?|block_ids",
    re.IGNORECASE)

#: zero-arg wall/CPU clock reads (``time.*`` or bare-imported forms)
CLOCK_TAILS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "process_time", "process_time_ns",
})

#: call tails whose result is a layer-forward activation
_FORWARD_TAILS = frozenset({"apply", "forward"})


def name_kinds(name: Optional[str]) -> Set[str]:
    """Weak kinds an identifier is seeded with purely by its name."""
    if not name:
        return set()
    out: Set[str] = set()
    if GRAD_NAME.search(name):
        out.add(_WEAK + GRADIENT)
    if ACT_NAME.search(name):
        out.add(_WEAK + ACTIVATION)
    if DISPATCH_NAME.search(name):
        out.add(_WEAK + DISPATCH_PAYLOAD)
    if KV_NAME.search(name):
        out.add(_WEAK + KV_BLOCK)
    return out


def _is_clock_call(call: ast.Call) -> bool:
    tail = astutil.tail_name(call.func)
    if tail not in CLOCK_TAILS:
        return False
    root = astutil.root_name(call.func)
    return root == "time" or root == tail


# ---------------------------------------------------------------------------
# Function summaries
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FunctionSummary:
    """What a local function returns, as kinds plus per-argument markers.

    ``flat`` describes "the return value" as one blob; ``elts`` is the
    per-element view when every tuple-shaped return agrees on arity (so
    ``loss, g = helper(...)`` can unpack without cross-contamination).
    """

    flat: Set[str]
    elts: Optional[List[Set[str]]]

    @staticmethod
    def _resolve(kinds: Set[str], argk: Sequence[Set[str]]) -> Set[str]:
        out: Set[str] = set()
        for k in kinds:
            if k.startswith(_ARG + ":"):
                i = int(k.rsplit(":", 1)[1])
                if i < len(argk):
                    out |= argk[i]
            elif k.startswith(_ARGC + ":"):
                i = int(k.rsplit(":", 1)[1])
                if i < len(argk):
                    out |= {x for x in argk[i]
                            if x in _CALL_TRANSPARENT or x == _VAG_RESULT}
            else:
                out.add(k)
        return out

    def flat_result(self, argk: Sequence[Set[str]]) -> Set[str]:
        return self._resolve(self.flat, argk)

    def elt_results(self, n: int,
                    argk: Sequence[Set[str]]) -> Optional[List[Set[str]]]:
        if self.elts is None or len(self.elts) != n:
            return None
        return [self._resolve(e, argk) for e in self.elts]

    def intrinsic(self) -> FrozenSet[str]:
        """Real kinds the function produces regardless of its arguments."""
        return frozenset(k for k in self.flat if k in KINDS)


_ScopeKey = Union[str, int]
_MODULE: _ScopeKey = "module"
_MAX_FIXPOINT_ROUNDS = 10


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class ModuleDataflow:
    """Per-module taint state: one environment per scope (module plus each
    function/lambda, inheriting the enclosing scope's bindings), computed
    once and queried by rules via :meth:`expr_kinds`."""

    def __init__(self, tree: ast.Module) -> None:
        self._tree = tree
        self._defs: Dict[str, ast.AST] = {}
        self._scope_of: Dict[int, _ScopeKey] = {}
        self._scope_parent: Dict[_ScopeKey, _ScopeKey] = {}
        self._envs: Dict[_ScopeKey, Dict[str, Set[str]]] = {}
        self._summaries: Dict[int, Optional[FunctionSummary]] = {}
        self._traced: Optional[Set[int]] = None

        order: List[ast.AST] = []  # function nodes, pre-order (outer first)

        def visit(node: ast.AST, key: _ScopeKey) -> None:
            for child in ast.iter_child_nodes(node):
                self._scope_of[id(child)] = key
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        self._defs[child.name] = child
                    order.append(child)
                    self._scope_parent[id(child)] = key
                    visit(child, id(child))
                else:
                    visit(child, key)

        self._scope_of[id(tree)] = _MODULE
        visit(tree, _MODULE)

        env: Dict[str, Set[str]] = {}
        self._fixpoint(tree.body, env, ns=True)
        self._envs[_MODULE] = env
        for fn in order:
            parent = self._scope_parent[id(fn)]
            fenv = {k: set(v) for k, v in self._envs[parent].items()}
            for i, a in enumerate(self._params(fn)):
                fenv[a] = set(name_kinds(a))
            if isinstance(fn, ast.Lambda):
                pass  # a lambda body has no statements to execute
            else:
                self._fixpoint(fn.body, fenv, ns=True)
            self._envs[id(fn)] = fenv

    # -- public API --------------------------------------------------------

    def expr_kinds(self, expr: ast.AST) -> FrozenSet[str]:
        """The real kinds of an expression, evaluated in its scope's env."""
        key = self._scope_of.get(id(expr), _MODULE)
        env = self._envs.get(key) or self._envs[_MODULE]
        kinds = _promote(self._eval(expr, env, ns=True))
        if _VAG_RESULT in kinds:
            kinds = (kinds - {_VAG_RESULT}) | {GRADIENT}
        return frozenset(k for k in kinds if k in KINDS)

    def call_intrinsic(self, call: ast.Call) -> FrozenSet[str]:
        """Kinds a call to a *local* function produces regardless of its
        arguments (e.g. a helper whose body reads ``time.perf_counter()``
        has intrinsic HOST_TIME). Empty for non-local callees."""
        if isinstance(call.func, ast.Name) and call.func.id in self._defs:
            s = self._summary(self._defs[call.func.id])
            if s is not None:
                return s.intrinsic()
        return frozenset()

    def provenance(self, node: ast.AST) -> str:
        """``TRACED`` when the node sits inside a JAX-traced function
        (per the trace-safety analysis), else ``HOST``."""
        if self._traced is None:
            from .rules_trace_safety import _traced_function_nodes
            self._traced = set(_traced_function_nodes(self._tree).keys())
        key = self._scope_of.get(id(node), _MODULE)
        while key != _MODULE:
            if key in self._traced:
                return TRACED
            key = self._scope_parent.get(key, _MODULE)
        return HOST

    # -- scope execution ---------------------------------------------------

    @staticmethod
    def _params(fn: ast.AST) -> List[str]:
        a = fn.args
        names = [x.arg for x in list(a.posonlyargs) + list(a.args)]
        names += [x.arg for x in a.kwonlyargs]
        if a.vararg is not None:
            names.append(a.vararg.arg)
        if a.kwarg is not None:
            names.append(a.kwarg.arg)
        return names

    def _fixpoint(self, stmts: Sequence[ast.stmt],
                  env: Dict[str, Set[str]], ns: bool) -> None:
        for _ in range(_MAX_FIXPOINT_ROUNDS):
            changed = False
            for s in stmts:
                changed |= self._exec(s, env, ns)
            if not changed:
                return

    def _exec(self, stmt: ast.AST, env: Dict[str, Set[str]],
              ns: bool) -> bool:
        """Execute one statement's bindings into ``env`` (descending into
        compound-statement bodies but not into nested function scopes)."""
        changed = False
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return False
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                changed |= self._bind(tgt, stmt.value, env, ns)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            changed |= self._bind(stmt.target, stmt.value, env, ns)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                changed |= self._update(
                    env, stmt.target.id, self._eval(stmt.value, env, ns))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            # iterating a tainted collection yields tainted items
            changed |= self._bind_kinds(
                stmt.target, self._eval(stmt.iter, env, ns), env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    changed |= self._bind_kinds(
                        item.optional_vars,
                        self._eval(item.context_expr, env, ns), env)
        # walrus + comprehension-target bindings anywhere in the
        # statement's expressions. Comprehension targets don't leak in
        # py3 scoping, but rules query the *expressions inside* the
        # comprehension against this scope's env (expr_kinds), so the
        # targets must be visible here — binding them is a sound
        # overapproximation. The enclosing _fixpoint orders the chain
        # (comp target -> walrus reading it) across rounds.
        for sub in astutil.walk_stop_at_functions(stmt):
            if isinstance(sub, ast.NamedExpr) and \
                    isinstance(sub.target, ast.Name):
                changed |= self._update(
                    env, sub.target.id, self._eval(sub.value, env, ns))
            elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp, ast.DictComp)):
                for gen in sub.generators:
                    changed |= self._bind_kinds(
                        gen.target, self._eval(gen.iter, env, ns), env)
        # recurse into compound bodies
        for field in ("body", "orelse", "finalbody"):
            for child in getattr(stmt, field, ()) or ():
                if isinstance(child, ast.AST):
                    changed |= self._exec(child, env, ns)
        for handler in getattr(stmt, "handlers", ()) or ():
            for child in handler.body:
                changed |= self._exec(child, env, ns)
        return changed

    @staticmethod
    def _update(env: Dict[str, Set[str]], name: str,
                kinds: Set[str]) -> bool:
        cur = env.setdefault(name, set())
        before = len(cur)
        cur |= kinds
        return len(cur) != before

    def _bind(self, target: ast.AST, value: ast.AST,
              env: Dict[str, Set[str]], ns: bool) -> bool:
        if isinstance(target, ast.Name):
            return self._update(env, target.id, self._eval(value, env, ns))
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = self._value_elements(value, len(target.elts), env, ns)
            changed = False
            for t, ek in zip(target.elts, elts):
                changed |= self._bind_kinds(t, ek, env)
            return changed
        if isinstance(target, ast.Starred):
            return self._bind_kinds(target.value,
                                    self._eval(value, env, ns), env)
        return False  # Subscript/Attribute targets: container taint is out
        # of scope for an intraprocedural engine

    def _bind_kinds(self, target: ast.AST, kinds: Set[str],
                    env: Dict[str, Set[str]]) -> bool:
        kinds = {k for k in kinds if k != _VAG_RESULT} | (
            {GRADIENT} if _VAG_RESULT in kinds else set())
        if isinstance(target, ast.Name):
            return self._update(env, target.id, kinds)
        if isinstance(target, ast.Starred):
            return self._bind_kinds(target.value, kinds, env)
        if isinstance(target, (ast.Tuple, ast.List)):
            changed = False
            for t in target.elts:  # no structure left: all get the union
                changed |= self._bind_kinds(t, kinds, env)
            return changed
        return False

    def _value_elements(self, value: ast.AST, n: int,
                        env: Dict[str, Set[str]],
                        ns: bool) -> List[Set[str]]:
        """Per-element kinds for unpacking ``value`` into ``n`` targets."""
        if isinstance(value, (ast.Tuple, ast.List)) and \
                len(value.elts) == n:
            return [self._eval(e, env, ns) for e in value.elts]
        kinds = self._eval(value, env, ns)
        if _VAG_RESULT in kinds:
            rest = kinds - {_VAG_RESULT}
            if n == 2:  # (value, grads)
                return [set(rest), rest | {GRADIENT}]
            return [rest | {GRADIENT} for _ in range(n)]
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id in self._defs:
            s = self._summary(self._defs[value.func.id])
            if s is not None:
                argk = [self._eval(a, env, ns) for a in value.args]
                per = s.elt_results(n, argk)
                if per is not None:
                    return per
        return [set(kinds) for _ in range(n)]

    # -- expression evaluation --------------------------------------------

    def _eval(self, e: ast.AST, env: Dict[str, Set[str]],
              ns: bool) -> Set[str]:
        if isinstance(e, ast.Name):
            out = set(env.get(e.id, ()))
            if ns:
                out |= name_kinds(e.id)
            return out
        if isinstance(e, ast.Attribute):
            return name_kinds(e.attr) if ns else set()
        if isinstance(e, ast.Call):
            return self._eval_call(e, env, ns)
        if isinstance(e, ast.Subscript):
            base = self._eval(e.value, env, ns)
            if _VAG_RESULT in base:
                idx = e.slice
                rest = base - {_VAG_RESULT}
                if isinstance(idx, ast.Constant) and \
                        isinstance(idx.value, int) and idx.value == 0:
                    return rest
                return rest | {GRADIENT}
            return base
        if isinstance(e, ast.Starred):
            return self._eval(e.value, env, ns)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            out: Set[str] = set()
            for x in e.elts:
                out |= self._eval(x, env, ns)
            return out
        if isinstance(e, ast.Dict):
            out = set()
            for x in list(e.keys) + list(e.values):
                if x is not None:  # None key = **mapping splat
                    out |= self._eval(x, env, ns)
            return out
        if isinstance(e, ast.BinOp):
            return self._eval(e.left, env, ns) | \
                self._eval(e.right, env, ns)
        if isinstance(e, ast.UnaryOp):
            return self._eval(e.operand, env, ns)
        if isinstance(e, ast.IfExp):
            return self._eval(e.body, env, ns) | \
                self._eval(e.orelse, env, ns)
        if isinstance(e, ast.NamedExpr):
            return self._eval(e.value, env, ns)
        if isinstance(e, ast.Await):
            return self._eval(e.value, env, ns)
        if isinstance(e, (ast.ListComp, ast.GeneratorExp, ast.SetComp,
                          ast.DictComp)):
            cenv = {k: set(v) for k, v in env.items()}
            for gen in e.generators:
                # iter evaluated in cenv so later generators see earlier
                # targets ([y for xs in grads for y in xs])
                self._bind_kinds(gen.target,
                                 self._eval(gen.iter, cenv, ns), cenv)
            if isinstance(e, ast.DictComp):
                return self._eval(e.key, cenv, ns) | \
                    self._eval(e.value, cenv, ns)
            return self._eval(e.elt, cenv, ns)
        return set()

    def _eval_call(self, call: ast.Call, env: Dict[str, Set[str]],
                   ns: bool) -> Set[str]:
        func = call.func
        tail = astutil.tail_name(func)
        # time.* / bare-imported clock reads
        if _is_clock_call(call):
            return {HOST_TIME}
        # jax.grad(f)(x) / jax.value_and_grad(f)(x) called directly
        if isinstance(func, ast.Call):
            inner_tail = astutil.tail_name(func.func)
            if inner_tail == "grad":
                return {GRADIENT}
            if inner_tail == "value_and_grad":
                return {_VAG_RESULT}
        if tail == "grad":
            return {_GRAD_FN}
        if tail == "value_and_grad":
            return {_VAG_FN}
        if tail == "gather_token_chunks":
            return {DISPATCH_PAYLOAD}
        if tail in _FORWARD_TAILS:
            return {ACTIVATION}
        # call through a name bound to a grad/value_and_grad transform
        if isinstance(func, ast.Name):
            fk = env.get(func.id, ())
            if _GRAD_FN in fk:
                return {GRADIENT}
            if _VAG_FN in fk:
                return {_VAG_RESULT}
            # local function: apply its summary
            if func.id in self._defs:
                s = self._summary(self._defs[func.id])
                if s is not None:
                    argk = [self._eval(a, env, ns) for a in call.args]
                    return s.flat_result(argk)
        # default: only call-transparent kinds flow through
        out: Set[str] = set()
        for a in call.args:
            out |= self._eval(a, env, ns)
        for kw in call.keywords:
            out |= self._eval(kw.value, env, ns)
        if isinstance(func, ast.Attribute):
            out |= self._eval(func.value, env, ns)  # method on tainted obj
        res: Set[str] = set()
        for k in out:
            if k in _CALL_TRANSPARENT:
                res.add(k)
            elif k == _VAG_RESULT:
                res.add(GRADIENT)
            elif k.startswith(_ARG + ":"):
                res.add(_ARGC + ":" + k.rsplit(":", 1)[1])
            elif k.startswith(_ARGC + ":"):
                res.add(k)
        return res

    # -- summaries ---------------------------------------------------------

    def _summary(self, fn: ast.AST) -> Optional[FunctionSummary]:
        key = id(fn)
        if key in self._summaries:
            return self._summaries[key]
        self._summaries[key] = None  # recursion guard
        if isinstance(fn, ast.Lambda):
            self._summaries[key] = None
            return None
        env: Dict[str, Set[str]] = {}
        pos = [x.arg for x in list(fn.args.posonlyargs) + list(fn.args.args)]
        for i, a in enumerate(pos):
            env[a] = {_ARG + ":" + str(i)}
        for a in fn.args.kwonlyargs:
            env[a.arg] = set()
        self._fixpoint(fn.body, env, ns=False)

        flat: Set[str] = set()
        tuple_returns: List[List[Set[str]]] = []
        shapeless: Set[str] = set()  # kinds of returns with unknown arity
        for node in astutil.walk_stop_at_functions(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            ek: Optional[List[Set[str]]] = None
            if isinstance(v, ast.Tuple):
                ek = [self._eval(x, env, False) for x in v.elts]
            elif isinstance(v, ast.Call) and \
                    isinstance(v.func, ast.Name) and v.func.id in self._defs \
                    and self._defs[v.func.id] is not fn:
                sub = self._summary(self._defs[v.func.id])
                if sub is not None and sub.elts is not None:
                    argk = [self._eval(a, env, False) for a in v.args]
                    ek = [FunctionSummary._resolve(e, argk)
                          for e in sub.elts]
            if ek is not None:
                for part in ek:
                    flat |= part
                tuple_returns.append(ek)
            else:
                kinds = self._eval(v, env, False)
                flat |= kinds
                shapeless |= kinds

        elts: Optional[List[Set[str]]] = None
        arities = {len(ek) for ek in tuple_returns}
        if len(arities) == 1:
            n = arities.pop()
            elts = [set(shapeless) for _ in range(n)]
            for ek in tuple_returns:
                for i in range(n):
                    elts[i] |= ek[i]
        s = FunctionSummary(flat=flat, elts=elts)
        self._summaries[key] = s
        return s
