"""Rule ``recompile-hazard``: jit cache poisons that recompile (or crash)
per call instead of per shape.

Two shapes:

* **non-hashable / array-valued default arguments** on a jitted function —
  a ``list``/``dict``/``set`` default crashes when the argument is marked
  static, and an ``np.array(...)``/``jnp.zeros(...)`` default bakes a fresh
  constant identity into the signature;
* **jitted functions reading module-level mutable globals** — the traced
  value is frozen at first compile, so later mutation silently diverges
  from eager semantics (or forces a retrace with ``static_argnums``-style
  hashing of an unhashable).

Only syntactically jit-decorated functions are checked; the factory idiom
(returning a closure that the caller jits) is out of scope here, and
captured *immutable* globals (ints, tuples, constants) are fine.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from . import astutil
from .core import Finding, LintContext, register

_MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)

_MUTABLE_CTORS = frozenset({"list", "dict", "set", "defaultdict",
                            "OrderedDict", "deque", "Counter"})

_ARRAY_CTORS = frozenset({"array", "asarray", "zeros", "ones", "full",
                          "arange", "linspace", "empty", "eye"})

_ARRAY_ROOTS = frozenset({"np", "numpy", "jnp", "jax"})


def _is_mutable_value(expr: ast.AST) -> bool:
    if isinstance(expr, _MUTABLE_DISPLAYS):
        return True
    if isinstance(expr, ast.Call):
        return astutil.tail_name(expr.func) in _MUTABLE_CTORS
    return False


def _is_array_value(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call) and \
            astutil.tail_name(expr.func) in _ARRAY_CTORS:
        root = astutil.root_name(expr.func)
        return root in _ARRAY_ROOTS or root is None
    return False


def _jitted_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                any(astutil.is_jit_decorator(d) for d in node.decorator_list):
            out.append(node)
    return out


@register(
    "recompile-hazard",
    "non-hashable or array-valued defaults on jitted functions, and jitted "
    "functions capturing module-level mutable globals")
def check(ctx: LintContext) -> Iterator[Finding]:
    # module-level mutable bindings: name -> assignment line
    mutable_globals: Dict[str, int] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and _is_mutable_value(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mutable_globals[tgt.id] = node.lineno
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                _is_mutable_value(node.value) and \
                isinstance(node.target, ast.Name):
            mutable_globals[node.target.id] = node.lineno

    for fn in _jitted_defs(ctx.tree):
        args = astutil.positional_args(fn)
        defaults = fn.args.defaults
        # defaults align with the tail of the positional args
        for arg, dflt in zip(args[len(args) - len(defaults):], defaults):
            if _is_mutable_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has a non-hashable "
                    f"(mutable) default for {arg.arg!r} — unhashable as a "
                    "static arg and shared across calls")
            elif _is_array_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has an array-valued "
                    f"default for {arg.arg!r} — a fresh constant identity "
                    "per import, a retrace per distinct identity")
        for arg, dflt in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if dflt is None:
                continue
            if _is_mutable_value(dflt) or _is_array_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has a non-hashable or "
                    f"array-valued default for keyword {arg.arg!r}")

        if not mutable_globals:
            continue
        local_names: Set[str] = {a.arg for a in args}
        local_names.update(a.arg for a in fn.args.kwonlyargs)
        for node in astutil.walk_stop_at_functions(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
        for node in astutil.walk_stop_at_functions(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_globals and \
                    node.id not in local_names:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} reads module-level "
                    f"mutable global {node.id!r} (defined line "
                    f"{mutable_globals[node.id]}) — its value is frozen "
                    "into the compiled program at first trace")
