"""Rule ``recompile-hazard``: jit cache poisons that recompile (or crash)
per call instead of per shape.

Two shapes:

* **non-hashable / array-valued default arguments** on a jitted function —
  a ``list``/``dict``/``set`` default crashes when the argument is marked
  static, and an ``np.array(...)``/``jnp.zeros(...)`` default bakes a fresh
  constant identity into the signature;
* **jitted functions reading module-level mutable globals** — the traced
  value is frozen at first compile, so later mutation silently diverges
  from eager semantics (or forces a retrace with ``static_argnums``-style
  hashing of an unhashable).

Only syntactically jit-decorated functions are checked; the factory idiom
(returning a closure that the caller jits) is out of scope here, and
captured *immutable* globals (ints, tuples, constants) are fine.

A third shape applies to serving code (any module with ``inference`` as a
path component): calls to a jit-wrapped callable whose **array operands
were shaped from per-request values** (``len(requests)`` and friends).
Each distinct live-request count is a distinct shape, so the step
retraces as load varies — exactly what the fixed-budget packing of
:mod:`..inference.engine` exists to avoid. The taint follows array
*constructors* (``zeros``/``asarray``/...) and shape-producing
*reshapers* (``reshape``/``split``/``array_split``/``tile``/``repeat``)
alike — the context-parallel prefill path made the latter an easy trap:
``np.array_split(prompt, len(prompt) // cp)`` hands the CP worker a
per-prompt chunk count, one compile per distinct prompt length, where
the ring prefill's fixed ``cp_prefill_width`` pad exists precisely so
the chunk grid never moves.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, Iterator, List, Set, Tuple

from . import astutil
from .core import Finding, LintContext, register

_MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)

_MUTABLE_CTORS = frozenset({"list", "dict", "set", "defaultdict",
                            "OrderedDict", "deque", "Counter"})

_ARRAY_CTORS = frozenset({"array", "asarray", "zeros", "ones", "full",
                          "arange", "linspace", "empty", "eye"})

_ARRAY_ROOTS = frozenset({"np", "numpy", "jnp", "jax"})

#: shape-producing calls (module fns and array methods both spell these):
#: a len()-tainted operand here yields an array whose shape — or chunk
#: count, for the splitters — tracks the per-request value
_SHAPE_METHODS = frozenset({"reshape", "split", "array_split", "tile",
                            "repeat"})


def _is_mutable_value(expr: ast.AST) -> bool:
    if isinstance(expr, _MUTABLE_DISPLAYS):
        return True
    if isinstance(expr, ast.Call):
        return astutil.tail_name(expr.func) in _MUTABLE_CTORS
    return False


def _is_array_value(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call) and \
            astutil.tail_name(expr.func) in _ARRAY_CTORS:
        root = astutil.root_name(expr.func)
        return root in _ARRAY_ROOTS or root is None
    return False


def _jitted_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                any(astutil.is_jit_decorator(d) for d in node.decorator_list):
            out.append(node)
    return out


@register(
    "recompile-hazard",
    "non-hashable or array-valued defaults on jitted functions, and jitted "
    "functions capturing module-level mutable globals")
def check(ctx: LintContext) -> Iterator[Finding]:
    # module-level mutable bindings: name -> assignment line
    mutable_globals: Dict[str, int] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and _is_mutable_value(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mutable_globals[tgt.id] = node.lineno
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                _is_mutable_value(node.value) and \
                isinstance(node.target, ast.Name):
            mutable_globals[node.target.id] = node.lineno

    for fn in _jitted_defs(ctx.tree):
        args = astutil.positional_args(fn)
        defaults = fn.args.defaults
        # defaults align with the tail of the positional args
        for arg, dflt in zip(args[len(args) - len(defaults):], defaults):
            if _is_mutable_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has a non-hashable "
                    f"(mutable) default for {arg.arg!r} — unhashable as a "
                    "static arg and shared across calls")
            elif _is_array_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has an array-valued "
                    f"default for {arg.arg!r} — a fresh constant identity "
                    "per import, a retrace per distinct identity")
        for arg, dflt in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if dflt is None:
                continue
            if _is_mutable_value(dflt) or _is_array_value(dflt):
                yield Finding(
                    ctx.path, dflt.lineno, dflt.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} has a non-hashable or "
                    f"array-valued default for keyword {arg.arg!r}")

        if not mutable_globals:
            continue
        local_names: Set[str] = {a.arg for a in args}
        local_names.update(a.arg for a in fn.args.kwonlyargs)
        for node in astutil.walk_stop_at_functions(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
        for node in astutil.walk_stop_at_functions(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_globals and \
                    node.id not in local_names:
                yield Finding(
                    ctx.path, node.lineno, node.col_offset,
                    "recompile-hazard",
                    f"jitted function {fn.name!r} reads module-level "
                    f"mutable global {node.id!r} (defined line "
                    f"{mutable_globals[node.id]}) — its value is frozen "
                    "into the compiled program at first trace")

    if "inference" in pathlib.PurePath(ctx.path).parts:
        yield from _per_request_shape_hazards(ctx)


def _len_taint(tree: ast.AST) -> Tuple[Set[str], "callable"]:
    """Names whose bound value involves ``len(...)`` (transitively through
    plain-name assignments), plus the taint predicate itself."""
    derived: Set[str] = set()

    def mentions_len(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "len":
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in derived:
                return True
        return False

    changed = True
    while changed:  # fixpoint over chained `n = len(q)`, `m = n + 1`
        changed = False
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(node, "value", None)
                if value is None or not mentions_len(value):
                    continue
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id not in derived:
                        derived.add(t.id)
                        changed = True
    return derived, mentions_len


def _per_request_shape_hazards(ctx: LintContext) -> Iterator[Finding]:
    """Serving-path extension: array operands of jitted calls whose shape
    follows the live-request count (``len(...)``)."""
    derived, mentions_len = _len_taint(ctx.tree)

    def shape_from_len(expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        tail = astutil.tail_name(expr.func)
        operands = list(expr.args) + [k.value for k in expr.keywords]
        if tail in _ARRAY_CTORS:
            root = astutil.root_name(expr.func)
            if root in _ARRAY_ROOTS or root is None:
                return any(mentions_len(a) for a in operands)
        if tail in _SHAPE_METHODS:
            # reshapers carry the taint whether spelled as module fns
            # (np.array_split(x, n_chunks)) or methods (x.reshape(n, -1))
            return any(mentions_len(a) for a in operands)
        return False

    # names bound to jax.jit(...) results, and names assigned a
    # len-shaped array (one hop of indirection each)
    jit_names: Set[str] = set()
    hazard_arrays: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if isinstance(node.value, ast.Call) and \
                astutil.tail_name(node.value.func) == "jit":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jit_names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    jit_names.add(t.attr)   # self._step = jax.jit(...)
        if shape_from_len(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    hazard_arrays.add(t.id)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = astutil.tail_name(node.func)
        direct_jit = (isinstance(node.func, ast.Call) and
                      astutil.tail_name(node.func.func) == "jit")
        if fname not in jit_names and not direct_jit:
            continue
        for a in list(node.args) + [k.value for k in node.keywords]:
            if shape_from_len(a) or \
                    (isinstance(a, ast.Name) and a.id in hazard_arrays):
                yield Finding(
                    ctx.path, a.lineno, a.col_offset, "recompile-hazard",
                    f"call to jitted {fname or '<expr>'!r} with an operand "
                    "shaped from a per-request value (len(...)) — every "
                    "live-request count retraces; pack into a fixed "
                    "token-budget shape instead")
