"""Rule ``mesh-axis``: every string literal used as a mesh-axis name must be
one of the canonical axis constants from ``parallel/mesh.py``.

Axis names are stringly-typed invariants threaded through ``PartitionSpec``s,
``psum``/``axis_index`` calls and ``shard_map`` specs; a typo (``"tp "``,
``"dp_ep"``) trips only at trace time on the one config that exercises that
spec. This rule checks, purely syntactically:

* ``PartitionSpec(...)`` / ``P(...)`` arguments (including nested tuples),
* the axis argument of the named-axis collectives
  (``psum``/``pmean``/``pmax``/``pmin``/``all_gather``/``ppermute``/
  ``all_to_all``/``psum_scatter``/``pbroadcast``/``axis_index`` and the
  in-repo ``comm.*`` wrappers),
* string literals passed to ``named_sharding`` / ``with_sharding_constraint``
  (this repo's helpers take bare spec entries),
* ``Mesh(devices, (...))`` axis-name tuples and ``shard_map`` spec kwargs.

Code that passes an axis through a *variable* (``axis=ps.TP_AXIS``, the
dominant idiom here) is untouched — the constant definition site is the
single point of truth.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from . import astutil
from .core import Finding, LintContext, register

# call-name -> positional index of the axis argument
_COLLECTIVE_AXIS_POS = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "ppermute": 1,
    "pbroadcast": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "all_reduce": 1,
    "reduce_scatter": 1,
    "axis_index": 0,
    "axis_size": 0,
}

_AXIS_KWARGS = ("axis_name", "axis")

# every string literal among the args is an axis name
_SPEC_CALLS = frozenset({"PartitionSpec", "P", "named_sharding",
                         "with_sharding_constraint"})

_SPEC_KWARG_CALLS = frozenset({"shard_map"})  # in_specs / out_specs kwargs


def _check_literal(node: ast.Constant, ctx: LintContext,
                   where: str) -> Optional[Finding]:
    name = node.value
    if name in ctx.axes:
        return None
    hint = ""
    stripped = name.strip()
    if stripped != name and stripped in ctx.axes:
        hint = f" (did you mean {stripped!r}?)"
    return Finding(
        ctx.path, node.lineno, node.col_offset, "mesh-axis",
        f"{name!r} used as a mesh-axis name in {where} is not a canonical "
        f"axis {sorted(ctx.axes)}{hint}")


def _check_expr(expr: ast.AST, ctx: LintContext,
                where: str) -> Iterator[Finding]:
    for lit in astutil.iter_str_constants(expr):
        f = _check_literal(lit, ctx, where)
        if f is not None:
            yield f


@register(
    "mesh-axis",
    "string literals used as mesh-axis names must match the canonical axis "
    "constants exported by parallel/mesh.py")
def check(ctx: LintContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = astutil.call_tail(node)
        if tail is None:
            continue

        if tail in _SPEC_CALLS:
            skip_first = tail == "with_sharding_constraint"
            args = node.args[1:] if skip_first else node.args
            for a in args:
                yield from _check_expr(a, ctx, f"{tail}(...)")
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                yield from _check_expr(kw.value, ctx, f"{tail}(...)")
            continue

        if tail == "Mesh":
            # Mesh(devices, axis_names) / Mesh(devices, ("dp", "tp"))
            cand = (astutil.get_kwarg(node, "axis_names")
                    or (node.args[1] if len(node.args) > 1 else None))
            if cand is not None:
                yield from _check_expr(cand, ctx, "Mesh axis_names")
            continue

        if tail in _SPEC_KWARG_CALLS:
            for kwname in ("in_specs", "out_specs"):
                kw = astutil.get_kwarg(node, kwname)
                if kw is None:
                    continue
                # raw strings inside spec trees (P(...) calls inside are
                # their own sites, caught by the _SPEC_CALLS branch)
                yield from _check_expr(kw, ctx, f"shard_map {kwname}")
            continue

        if tail in _COLLECTIVE_AXIS_POS:
            axis_expr: Optional[ast.AST] = None
            for kwname in _AXIS_KWARGS:
                axis_expr = astutil.get_kwarg(node, kwname)
                if axis_expr is not None:
                    break
            if axis_expr is None:
                pos = _COLLECTIVE_AXIS_POS[tail]
                if len(node.args) > pos:
                    axis_expr = node.args[pos]
            if axis_expr is not None:
                yield from _check_expr(axis_expr, ctx, f"{tail}(...) axis")
