"""Rule ``tp-overlap``: blocking collective + matmul pairs serialize the
TP hot path.

A raw ``all_gather``/``psum`` whose result immediately feeds a matmul
(``einsum``/``dot``/``matmul``/``tensordot``/``@``) is the fully
serialized form of a tensor-parallel linear: the wire is idle during the
matmul and the MXU is idle during the collective.
:mod:`..ops.collective_matmul` provides the decomposed equivalents
(``all_gather_matmul``, ``matmul_reduce_scatter``, ``matmul_all_reduce``,
``copy_matmul``) that stream shards around a ``ppermute`` ring while each
step's partial matmul runs — bit-exact in fp32 and auto-falling-back on
non-tileable shapes (docs/tp_overlap.md).

The rule fires in model/module code when a matmul consumes a variable that
an earlier statement in the same function assigned from a raw
``all_gather``/``psum`` call, and the variable is activation-named
(``x``/``h``/``hidden*``/``act*``/...). ``parallel/`` and ``ops/`` are
exempt — the mappings, the compressed collectives and the decomposed
primitives themselves legitimately compose raw collectives with matmuls.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List

from . import astutil, dataflow
from .core import Finding, LintContext, register

# the activation-name heuristic lives in dataflow.py now (it seeds the
# taint lattice); kept as a module alias for heuristics-only mode
_ACT_NAME = dataflow.ACT_NAME

_COLLECTIVES = ("all_gather", "psum")
_MATMULS = ("einsum", "dot", "matmul", "tensordot")


def _collective_tail(node: ast.AST):
    if isinstance(node, ast.Call):
        tail = astutil.tail_name(node.func)
        if tail in _COLLECTIVES:
            return tail
    return None


def _name_operands(node: ast.AST) -> Iterator[str]:
    if isinstance(node, ast.Call):
        for arg in node.args:
            if isinstance(arg, ast.Name):
                yield arg.id
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
        for side in (node.left, node.right):
            if isinstance(side, ast.Name):
                yield side.id


@register(
    "tp-overlap",
    "blocking all_gather/psum followed by a matmul on the gathered "
    "activations — use ops.collective_matmul so the transfer overlaps "
    "the per-shard partial matmuls",
    exempt=("parallel", "ops"))
def check(ctx: LintContext) -> Iterator[Finding]:
    df = ctx.dataflow

    def _is_activation(name: str, value: ast.Call) -> bool:
        # the gathered value is an activation when the target is
        # activation-named (v1 heuristic) or — with the tier-2 engine —
        # when the collective's operand carries the ACTIVATION kind
        # through renames/unpacking the regex can't see
        if _ACT_NAME.search(name):
            return True
        if df is not None and value.args:
            return dataflow.ACTIVATION in df.expr_kinds(value.args[0])
        return False

    findings: List[Finding] = []
    for func in ast.walk(ctx.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # gather (assignment | matmul-use) events and replay them in source
        # order — ast.walk order is not statement order
        events = []
        for node in astutil.walk_stop_at_functions(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                events.append(("assign", node))
            elif (isinstance(node, ast.Call)
                  and astutil.tail_name(node.func) in _MATMULS) or (
                      isinstance(node, ast.BinOp)
                      and isinstance(node.op, ast.MatMult)):
                events.append(("matmul", node))
        events.sort(key=lambda e: (e[1].lineno, e[1].col_offset))

        gathered: Dict[str, str] = {}  # activation var -> collective tail
        for kind, node in events:
            if kind == "assign":
                # assignment from a collective marks the var; any other
                # reassignment clears it (the gathered value was replaced)
                name = node.targets[0].id
                tail = _collective_tail(node.value)
                if tail and _is_activation(name, node.value):
                    gathered[name] = tail
                else:
                    gathered.pop(name, None)
                continue
            for name in _name_operands(node):
                tail = gathered.get(name)
                if tail is None:
                    continue
                op = ("all_gather_matmul" if tail == "all_gather"
                      else "matmul_all_reduce / matmul_reduce_scatter")
                findings.append(Finding(
                    ctx.path, node.lineno, node.col_offset, "tp-overlap",
                    f"matmul on {name!r} produced by a blocking {tail} — "
                    f"the collective serializes with the compute; use "
                    f"ops.collective_matmul.{op} to overlap the transfer "
                    "with per-shard partial matmuls (docs/tp_overlap.md)"))
                break
    yield from findings
