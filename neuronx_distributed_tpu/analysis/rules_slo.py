"""Rule ``slo``: latency thresholds in serving code belong in SloPolicy.

The SLO layer (``obs/slo.py``, docs/observability.md "SLOs") exists so
that every latency judgment the serving stack makes — when to degrade,
when to scale, when a replica counts as unhealthy — is stated once, in a
declarative :class:`SloPolicy`, where operators can see and change it.
A comparison like ``ttft_p99_s > 0.25`` buried in router code is the
anti-pattern: an invisible SLO that no policy file mentions, no
``nxd_slo_compliance`` gauge tracks, and no breach event fires for.

The rule flags ordering comparisons (``<``/``<=``/``>``/``>=``) between
a latency-named value (ttft/tpot/latency/queue/wait/e2e stems, ``*_s`` /
``*_ms`` / ``*_p99``-style suffixes) and a positive numeric literal.

Not flagged — these are how the threshold is *supposed* to arrive:

* comparisons against configuration attributes (``pol.ttft_p99_high_s``,
  ``self.cfg.degrade_threshold``, ``policy.max_queue_s``): the base name
  chain mentions a config/policy object, so the number lives in a
  policy, not in the code;
* zero/negative literals (``ttft > 0`` is a validity guard, not an SLO);
* equality checks (thresholds are orderings).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from . import astutil
from .core import Finding, LintContext, register

#: name shapes that read as a latency/duration measurement
_LATENCY_RE = re.compile(
    r"(^|_)(ttft|tpot|latency|queue|wait|e2e)(_|$)"
    r"|_p\d{2}(_m?s)?$"
    r"|_m?s$")

#: a base-chain component that marks the value as policy/config-sourced
_POLICY_BASES = frozenset(
    {"cfg", "config", "policy", "pol", "slo", "scale", "target",
     "targets", "threshold", "thresholds"})


def _latency_name(node: ast.AST) -> Optional[str]:
    """The latency-ish name a comparison side measures, or None."""
    name = astutil.tail_name(node)
    if name is not None and _LATENCY_RE.search(name):
        return name
    return None


def _policy_sourced(node: ast.AST) -> bool:
    """True when any component of the dotted base chain names a
    config/policy object — the threshold came from configuration."""
    while isinstance(node, ast.Attribute):
        node = node.value
        if astutil.tail_name(node) in _POLICY_BASES:
            return True
    return isinstance(node, ast.Name) and node.id in _POLICY_BASES


def _positive_number(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool)
            and node.value > 0)


@register(
    "slo",
    "hard-coded latency threshold in serving code (ordering comparison "
    "of a ttft/tpot/latency-named value against a numeric literal) — "
    "the number belongs in a declarative SloPolicy where it is visible, "
    "monitored, and emits breach events",
    scope=("inference",))
def check(ctx: LintContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        ops = node.ops
        for i, op in enumerate(ops):
            if not isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
                continue
            lhs, rhs = sides[i], sides[i + 1]
            for measured, literal in ((lhs, rhs), (rhs, lhs)):
                name = _latency_name(measured)
                if name is None or not _positive_number(literal):
                    continue
                if _policy_sourced(measured):
                    continue
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, "slo",
                    f"`{name}` compared against the literal "
                    f"`{literal.value}` — a latency threshold hard-coded "
                    "outside SloPolicy is an invisible SLO: no "
                    "nxd_slo_compliance gauge tracks it and no "
                    "slo_breach event fires when it is violated; move "
                    "the number into the policy (obs/slo.py) and "
                    "consult SloMonitor instead")
                break
