"""CLI for nxdlint: ``python -m neuronx_distributed_tpu.analysis [paths]``.

Four tiers (see docs/analysis.md):

* syntactic + dataflow (default): lint the given paths with the rule
  set, with the def-use taint engine feeding the rules; pass
  ``--heuristics-only`` for the name-pattern-only v1 behavior.
* ``--jaxpr``: abstract-trace the registered entry points on the CPU
  backend and audit the resulting jaxprs (collective scope, host
  callbacks, donation, wire precision).
* ``--mesh-protocol``: the tier-4 mesh-protocol verifier — extract each
  entry point's collective schedule (flagging cond-branch divergence
  and malformed ppermute rings) and check post-propagation shardings
  against the registered contract. ``--emit-schedule FILE`` writes the
  extracted schedule as reviewable JSON (implies ``--mesh-protocol``).

``--changed-only`` restricts the syntactic tiers to files changed
relative to ``--base`` (default HEAD, per ``git diff --name-only`` plus
untracked files), falling back to a full scan outside a git repo.

The CI ratchet: ``--baseline FILE --write-baseline`` records the
current findings; ``--baseline FILE --fail-on-new`` then fails only on
findings not in the baseline. ``--format json|sarif`` emits
machine-readable output (SARIF 2.1.0 for code-scanning UIs).

Exit status: 0 when no unsuppressed (or un-baselined) findings, 1 when
findings remain, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set

from . import baseline as baseline_mod
from . import jaxpr_audit, mesh_protocol, output
from .core import all_rules, analyze_paths


def _split(csv: Optional[str]) -> Optional[List[str]]:
    if csv is None:
        return None
    return [s.strip() for s in csv.split(",") if s.strip()]


def _explain(rule_id: str) -> int:
    rules = all_rules()
    if rule_id in rules:
        rule = rules[rule_id]
        print(f"{rule_id}: {rule.description}")
        if rule.scope:
            print(f"  scope: {', '.join(rule.scope)}")
        if rule.exempt:
            print(f"  exempt: {', '.join(rule.exempt)}")
        doc = getattr(sys.modules.get(rule.check.__module__), "__doc__",
                      None)
        if doc:
            print()
            print(doc.strip())
        return 0
    for mod in (jaxpr_audit, mesh_protocol):
        if rule_id in mod.RULES:
            print(f"{rule_id}: {mod.RULES[rule_id]}")
            if mod.__doc__:
                print()
                print(mod.__doc__.strip())
            return 0
    known = (sorted(rules) + sorted(jaxpr_audit.RULES)
             + sorted(mesh_protocol.RULES))
    print(f"error: unknown rule {rule_id!r}; known rules: "
          f"{', '.join(known)}", file=sys.stderr)
    return 2


def _rule_descriptions() -> Dict[str, str]:
    descs = {name: rule.description
             for name, rule in all_rules().items()}
    descs.update(jaxpr_audit.RULES)
    descs.update(mesh_protocol.RULES)
    return descs


def _changed_files(base: str) -> Optional[Set[str]]:
    """Absolute paths of files changed vs ``base`` (plus untracked
    files), or ``None`` when git is unavailable / not a repo — the
    caller falls back to a full scan then."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", base],
            capture_output=True, text=True, check=True).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    return {os.path.join(top, ln.strip())
            for ln in (diff + untracked).splitlines() if ln.strip()}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m neuronx_distributed_tpu.analysis",
        description="nxdlint: JAX/SPMD-aware static analysis "
                    "(syntactic rules + def-use dataflow + optional "
                    "jaxpr-level program audit)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint")
    parser.add_argument("--select", metavar="RULES",
                        help="comma-separated rules to run (default: all)")
    parser.add_argument("--disable", metavar="RULES", default=None,
                        help="comma-separated rules to skip")
    parser.add_argument("--extra-axes", metavar="AXES", default=None,
                        help="comma-separated additional canonical axis "
                             "names (also settable via [tool.nxdlint] "
                             "extra_axes in pyproject.toml)")
    parser.add_argument("--exclude", metavar="PATTERNS", default=None,
                        help="comma-separated path patterns to skip "
                             "(directory/file name, or a /-joined path "
                             "suffix)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--heuristics-only", action="store_true",
                        help="disable the def-use dataflow tier and fall "
                             "back to v1 name-pattern heuristics")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="output format (default: text)")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help="baseline file for the CI ratchet")
    parser.add_argument("--fail-on-new", action="store_true",
                        help="with --baseline: report and fail only on "
                             "findings not in the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="with --baseline: record current findings "
                             "as the new baseline and exit 0")
    parser.add_argument("--jaxpr", action="store_true",
                        help="audit registered entry points at the "
                             "jaxpr level (abstract tracing on the CPU "
                             "backend; no user code is executed)")
    parser.add_argument("--mesh-protocol", action="store_true",
                        help="run the tier-4 mesh-protocol verifier on "
                             "the registered entry points: collective-"
                             "schedule divergence, ppermute ring "
                             "bijectivity, and sharding-contract / "
                             "replication audits")
    parser.add_argument("--emit-schedule", metavar="FILE", default=None,
                        help="write the extracted collective schedule "
                             "as JSON to FILE ('-' for stdout); implies "
                             "--mesh-protocol")
    parser.add_argument("--register", metavar="FILE", action="append",
                        default=None,
                        help="with --jaxpr/--mesh-protocol: execute FILE "
                             "to register extra entry points (replaces "
                             "the default registry for this run; "
                             "repeatable)")
    parser.add_argument("--entry", metavar="NAMES", default=None,
                        help="with --jaxpr/--mesh-protocol: comma-"
                             "separated entry-point names to audit "
                             "(default: all registered)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs --base (git "
                             "diff --name-only + untracked); full scan "
                             "outside a git repo")
    parser.add_argument("--base", metavar="REF", default="HEAD",
                        help="with --changed-only: git ref to diff "
                             "against (default: HEAD)")
    parser.add_argument("--explain", metavar="RULE", default=None,
                        help="print the rule's description and rationale "
                             "and exit")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit")
    args = parser.parse_args(argv)

    if args.emit_schedule:
        args.mesh_protocol = True

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            print(f"{name}: {rule.description}")
        for name in sorted(jaxpr_audit.RULES):
            print(f"{name}: {jaxpr_audit.RULES[name]} [--jaxpr]")
        for name in sorted(mesh_protocol.RULES):
            print(f"{name}: {mesh_protocol.RULES[name]} "
                  "[--mesh-protocol]")
        return 0
    if args.explain:
        return _explain(args.explain)
    if not args.paths and not args.jaxpr and not args.mesh_protocol:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2
    if (args.fail_on_new or args.write_baseline) and not args.baseline:
        print("error: --fail-on-new/--write-baseline require --baseline",
              file=sys.stderr)
        return 2

    only_files = None
    if args.changed_only:
        only_files = _changed_files(args.base)
        if only_files is None:
            print("nxdlint: --changed-only: not a git repo, running a "
                  "full scan", file=sys.stderr)

    findings = []
    if args.paths:
        try:
            findings = analyze_paths(
                args.paths,
                select=_split(args.select),
                disable=_split(args.disable) or (),
                extra_axes=_split(args.extra_axes) or (),
                dataflow=not args.heuristics_only,
                exclude=tuple(_split(args.exclude) or ()),
                only_files=only_files)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.jaxpr or args.mesh_protocol:
        jaxpr_audit.ensure_cpu_backend()
        if args.register:
            import runpy
            for reg in args.register:
                runpy.run_path(reg)
        entry_names = _split(args.entry)
        include_defaults = not args.register
        try:
            if args.jaxpr:
                findings = findings + jaxpr_audit.audit_entry_points(
                    names=entry_names, include_defaults=include_defaults)
            if args.mesh_protocol:
                mp_findings, schedules = mesh_protocol.audit_entry_points(
                    names=entry_names, include_defaults=include_defaults)
                findings = findings + mp_findings
                if args.emit_schedule:
                    doc = mesh_protocol.schedules_to_json(schedules)
                    if args.emit_schedule == "-":
                        print(doc)
                    else:
                        with open(args.emit_schedule, "w",
                                  encoding="utf-8") as fh:
                            fh.write(doc + "\n")
                        print(f"nxdlint: wrote collective schedule for "
                              f"{len(schedules)} entry point(s) to "
                              f"{args.emit_schedule}", file=sys.stderr)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    active = [f for f in findings if not f.suppressed]

    if args.write_baseline:
        n = baseline_mod.write_baseline(args.baseline, active)
        print(f"nxdlint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} "
              f"({len(active)} finding(s)) to {args.baseline}",
              file=sys.stderr)
        return 0

    if args.baseline:
        try:
            base = baseline_mod.load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: cannot load baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        active = baseline_mod.new_findings(active, base)

    shown = findings if args.show_suppressed else active
    if args.format == "json":
        print(output.findings_to_json(shown))
    elif args.format == "sarif":
        print(output.findings_to_sarif(shown, _rule_descriptions()))
    else:
        for f in shown:
            print(f.format())
    n_sup = len(findings) - len([f for f in findings if not f.suppressed])
    label = "new finding(s)" if args.baseline else "finding(s)"
    print(f"nxdlint: {len(active)} {label}, {n_sup} suppressed",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
