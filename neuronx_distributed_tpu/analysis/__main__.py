"""CLI for nxdlint: ``python -m neuronx_distributed_tpu.analysis [paths]``.

Exit status: 0 when no unsuppressed findings, 1 when findings remain,
2 on usage errors.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .core import all_rules, analyze_paths


def _split(csv: Optional[str]) -> Optional[List[str]]:
    if csv is None:
        return None
    return [s.strip() for s in csv.split(",") if s.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m neuronx_distributed_tpu.analysis",
        description="nxdlint: JAX/SPMD-aware static analysis "
                    "(mesh-axis, trace-safety, custom-vjp, "
                    "recompile-hazard, resilience)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint")
    parser.add_argument("--select", metavar="RULES",
                        help="comma-separated rules to run (default: all)")
    parser.add_argument("--disable", metavar="RULES", default=None,
                        help="comma-separated rules to skip")
    parser.add_argument("--extra-axes", metavar="AXES", default=None,
                        help="comma-separated additional canonical axis "
                             "names (also settable via [tool.nxdlint] "
                             "extra_axes in pyproject.toml)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            print(f"{name}: {rule.description}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    try:
        findings = analyze_paths(
            args.paths,
            select=_split(args.select),
            disable=_split(args.disable) or (),
            extra_axes=_split(args.extra_axes) or ())
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    active = [f for f in findings if not f.suppressed]
    shown = findings if args.show_suppressed else active
    for f in shown:
        print(f.format())
    n_sup = len(findings) - len(active)
    print(f"nxdlint: {len(active)} finding(s), {n_sup} suppressed",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
