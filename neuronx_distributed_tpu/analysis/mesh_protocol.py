"""nxdlint tier 4: mesh-protocol verifier (``--mesh-protocol``).

Abstract-traces every registered entry point (:mod:`.audit_registry`) on
the virtual CPU mesh — like tier 3, tracing evaluates shapes/dtypes
only, the entry function is never executed — and verifies the
*rank-coordinated protocol* the jaxpr encodes, the class of contract
whose violation hangs real multi-host hardware instead of raising:

* ``jaxpr-collective-divergence`` — the per-axis collective schedule
  (ordered ``psum``/``ppermute``/``all_gather``/... with payload shape,
  dtype and axis) is extracted by walking nested pjit/shard_map/scan/
  while bodies and every ``cond`` branch. A ``cond`` whose branches
  issue *different* collective sequences is a static deadlock hazard:
  under SPMD every rank takes its own branch, so some ranks arrive at a
  collective their peers never post. (``pbroadcast`` bookkeeping that
  ``shard_map``'s replication checker inserts moves zero wire bytes and
  is excluded.)
* ``jaxpr-ring-malformed`` — every ``ppermute`` perm must be a
  bijection over the named axis that covers it exactly once: duplicate
  sources drop data, duplicate destinations race, and a ring that skips
  a rank stalls that rank's recv forever.
* ``jaxpr-silent-replication`` — entry points registered with
  ``max_replicated_bytes=`` are lowered (``jit(fn).lower(...).
  compile()``) with *uncommitted* avals so XLA's sharding propagation
  picks the layouts; any input/output at or above the ceiling that ends
  up fully replicated across a multi-device mesh is flagged — the
  megatensor quietly costs ``n_devices`` copies of HBM.
* ``jaxpr-implicit-gather`` — entry points registered with
  ``in_shardings=`` declare a per-argument sharding contract; a
  propagated input sharding that does not match it means XLA inserted
  an implicit all-gather/reshard on every call to reconcile the layout
  the body actually wants.

The extracted schedule itself is a reviewable artifact:
``--emit-schedule FILE`` writes it as deterministic JSON (ordered
collectives with axis, prim, payload bytes, wire dtype, trip count and
lexical scope) so schedule diffs show up in PRs before they show up as
hangs.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .audit_registry import (EntryPoint, all_entry_points,
                             load_default_entry_points)
from .core import Finding
from .jaxpr_audit import (_COLLECTIVE_PRIMS, _aval_bytes, _entry_location,
                          _subjaxprs)

#: stable rule IDs -> short description (merged into ``--list-rules``,
#: ``--explain`` and the SARIF rule catalog)
RULES: Dict[str, str] = {
    "jaxpr-collective-divergence":
        "cond branches issue different collective sequences — under SPMD "
        "each rank takes its own branch, so ranks block on collectives "
        "their peers never post (static deadlock/hang hazard); hoist the "
        "collectives out of the cond or make the branches symmetric",
    "jaxpr-ring-malformed":
        "ppermute perm is not a bijection covering the named axis "
        "exactly once — duplicate sources drop data, duplicate "
        "destinations race, and an uncovered rank stalls its recv "
        "forever; build the ring as [(i, (i+1) % size) for i in "
        "range(size)]",
    "jaxpr-silent-replication":
        "tensor at or above the entry's max_replicated_bytes lowers to a "
        "fully replicated sharding on a multi-device mesh — it silently "
        "costs one HBM copy per device; shard it (with_sharding_"
        "constraint) or raise the registered ceiling",
    "jaxpr-implicit-gather":
        "propagated input sharding disagrees with the entry's declared "
        "in_shardings contract — XLA reconciles the layouts with an "
        "implicit all-gather/reshard on every call; fix the in_specs or "
        "pin the layout with with_sharding_constraint",
}

#: collectives that move bytes on the wire. ``pbroadcast`` is excluded:
#: shard_map's check_rep rewrite inserts it as zero-wire replication
#: bookkeeping (including into cond branches with no collectives), so
#: counting it would make every benign cond look divergent.
WIRE_COLLECTIVES = frozenset(_COLLECTIVE_PRIMS - {"pbroadcast"})

#: primitives with inner jaxprs that we walk with explicit semantics
#: (everything else with sub-jaxprs is walked generically)
_RING_PRIM = "ppermute"


@dataclasses.dataclass
class CollectiveOp:
    """One wire collective in an entry point's extracted schedule."""

    seq: int
    prim: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    payload_bytes: int
    #: static execution count (scan lengths multiplied through); ``None``
    #: under a ``while`` whose trip count is data-dependent
    trips: Optional[int]
    #: lexical scope path, e.g. ``"shard_map/scan"``
    scope: str

    def signature(self) -> Tuple[Any, ...]:
        """Identity used for cross-branch schedule comparison: what the
        peer ranks must match for the collective to complete."""
        return (self.prim, self.axes, self.shape, self.dtype, self.trips)

    def describe(self) -> str:
        ax = ",".join(self.axes) or "?"
        return f"{self.prim}@{ax} {self.dtype}[{'x'.join(map(str, self.shape))}]"

    def to_json(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "prim": self.prim,
            "axes": list(self.axes),
            "shape": list(self.shape),
            "dtype": self.dtype,
            "payload_bytes": self.payload_bytes,
            "trips": self.trips,
            "scope": self.scope,
        }


def _op_axes(params: Dict[str, Any]) -> Tuple[str, ...]:
    axes = params.get("axes")
    if axes is None:
        axes = params.get("axis_name")
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _record_collective(eqn: Any, scope: str, trips: Optional[int],
                       ops: List[CollectiveOp]) -> None:
    avals = [v.aval for v in eqn.invars if hasattr(v, "aval")
             and hasattr(getattr(v, "aval"), "shape")]
    first = avals[0] if avals else None
    ops.append(CollectiveOp(
        seq=-1,  # renumbered after the walk
        prim=eqn.primitive.name,
        axes=_op_axes(eqn.params),
        shape=tuple(int(d) for d in first.shape) if first is not None else (),
        dtype=getattr(getattr(first, "dtype", None), "name", "?"),
        payload_bytes=sum(_aval_bytes(a) for a in avals),
        trips=trips,
        scope=scope))


def _check_perm(eqn: Any, axis_sizes: Dict[str, int], scope: str,
                defects: List[Tuple[str, str]]) -> None:
    perm = tuple(tuple(p) for p in eqn.params.get("perm", ()))
    if not perm:
        return
    axes = _op_axes(eqn.params)
    srcs = [p[0] for p in perm]
    dsts = [p[1] for p in perm]
    issues: List[str] = []
    dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_src:
        issues.append(f"duplicate source rank(s) {dup_src}")
    if dup_dst:
        issues.append(f"duplicate destination rank(s) {dup_dst}")
    size = next((axis_sizes[a] for a in axes if a in axis_sizes), None)
    if size is not None:
        oob = sorted({r for r in srcs + dsts if not 0 <= r < size})
        if oob:
            issues.append(f"rank(s) {oob} out of range for axis size {size}")
        full = set(range(size))
        if not oob and (set(srcs) != full or set(dsts) != full):
            missing = sorted((full - set(srcs)) | (full - set(dsts)))
            issues.append(
                f"ring covers the axis incompletely (rank(s) {missing} "
                "never send and/or never receive)")
    elif set(srcs) != set(dsts):
        issues.append("source and destination rank sets differ")
    if issues:
        ax = ",".join(axes) or "?"
        defects.append((
            "jaxpr-ring-malformed",
            f"ppermute over axis '{ax}' in scope '{scope}' with perm "
            f"{list(perm)}: " + "; ".join(issues)))


def _branch_summary(branch_ops: List[CollectiveOp]) -> str:
    if not branch_ops:
        return "(no collectives)"
    return ", ".join(op.describe() for op in branch_ops)


def _closed_inner(x: Any) -> Any:
    """The raw Jaxpr inside either a ClosedJaxpr or a raw Jaxpr."""
    return x.jaxpr if hasattr(x, "jaxpr") else x


def _visit(jaxpr: Any, axis_sizes: Dict[str, int], scope: str,
           trips: Optional[int], ops: List[CollectiveOp],
           defects: List[Tuple[str, str]]) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in WIRE_COLLECTIVES:
            _record_collective(eqn, scope, trips, ops)
            if prim == _RING_PRIM:
                _check_perm(eqn, axis_sizes, scope, defects)
            continue
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            per_branch: List[List[CollectiveOp]] = []
            for bi, br in enumerate(branches):
                b_ops: List[CollectiveOp] = []
                _visit(_closed_inner(br), axis_sizes,
                       f"{scope}/cond.b{bi}" if scope else f"cond.b{bi}",
                       trips, b_ops, defects)
                per_branch.append(b_ops)
            sigs = {tuple(op.signature() for op in b) for b in per_branch}
            if len(sigs) > 1:
                detail = "; ".join(
                    f"branch {bi}: {_branch_summary(b)}"
                    for bi, b in enumerate(per_branch))
                defects.append((
                    "jaxpr-collective-divergence",
                    f"cond in scope '{scope or '<top>'}' issues a "
                    f"different collective sequence per branch — {detail}"))
            if per_branch:
                # representative schedule: branches agree when clean, and
                # a divergence is already flagged when they do not
                ops.extend(per_branch[0])
            continue
        if prim == "shard_map":
            inner_sizes = dict(axis_sizes)
            mesh_shape = getattr(eqn.params.get("mesh"), "shape", None)
            if mesh_shape:
                inner_sizes.update({str(k): int(v)
                                    for k, v in dict(mesh_shape).items()})
            _visit(_closed_inner(eqn.params["jaxpr"]), inner_sizes,
                   f"{scope}/shard_map" if scope else "shard_map",
                   trips, ops, defects)
            continue
        if prim in ("xla_pmap", "pmap"):
            inner_sizes = dict(axis_sizes)
            ax, sz = eqn.params.get("axis_name"), eqn.params.get("axis_size")
            if ax is not None and sz is not None:
                inner_sizes[str(ax)] = int(sz)
            _visit(_closed_inner(eqn.params["call_jaxpr"]), inner_sizes,
                   f"{scope}/pmap" if scope else "pmap", trips, ops, defects)
            continue
        if prim == "scan":
            length = eqn.params.get("length")
            inner_trips = (None if trips is None or length is None
                           else trips * int(length))
            _visit(_closed_inner(eqn.params["jaxpr"]), axis_sizes,
                   f"{scope}/scan" if scope else "scan",
                   inner_trips, ops, defects)
            continue
        if prim == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    # data-dependent trip count: statically unbounded
                    _visit(_closed_inner(sub), axis_sizes,
                           f"{scope}/while" if scope else "while",
                           None, ops, defects)
            continue
        # pjit is transparent; other higher-order prims (remat, custom
        # vjp/jvp, ...) contribute their lexical name to the scope path
        inner_scope = scope
        if prim != "pjit":
            inner_scope = f"{scope}/{prim}" if scope else prim
        for sub in _subjaxprs(eqn.params):
            _visit(sub, axis_sizes, inner_scope, trips, ops, defects)


def extract_schedule(closed: Any) -> Tuple[List[CollectiveOp],
                                           List[Tuple[str, str]]]:
    """Walk a ClosedJaxpr and return ``(schedule, defects)``: the ordered
    wire collectives and the ``(rule, message)`` protocol violations
    found along the way."""
    ops: List[CollectiveOp] = []
    defects: List[Tuple[str, str]] = []
    _visit(_closed_inner(closed), {}, "", 1, ops, defects)
    for i, op in enumerate(ops):
        op.seq = i
    return ops, defects


# --------------------------------------------------------------------------
# Sharding-contract audit (lowered entry points)
# --------------------------------------------------------------------------

def _leaf_nbytes(leaf: Any) -> int:
    try:
        size = 1
        for d in leaf.shape:
            size *= int(d)
        return size * int(leaf.dtype.itemsize)
    except (AttributeError, TypeError):
        return 0


def _leaf_str(leaf: Any) -> str:
    try:
        return (f"{leaf.dtype.name}"
                f"[{','.join(str(d) for d in leaf.shape)}]")
    except AttributeError:
        return str(leaf)


def _audit_shardings(ep: EntryPoint, built: Any, closed: Any,
                     flag: Any) -> None:
    """Lower the entry with uncommitted avals so XLA's sharding
    propagation chooses the layouts, then check them against the
    registered contract (``in_shardings`` / ``max_replicated_bytes``)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    try:
        fn = built.fn if hasattr(built.fn, "lower") else jax.jit(built.fn)
        sds_args = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
            built.args)
        compiled = fn.lower(*sds_args).compile()
        in_sh = jax.tree_util.tree_leaves(compiled.input_shardings[0])
        out_sh = jax.tree_util.tree_leaves(compiled.output_shardings)
    except Exception as e:  # surfaced as a finding, not a crash
        flag("jaxpr-audit-error",
             f"sharding audit (lower+compile) failed: "
             f"{type(e).__name__}: {e}")
        return

    in_leaves = jax.tree_util.tree_leaves(built.args)
    out_leaves = list(closed.out_avals)

    mesh = built.mesh
    if mesh is None:
        for s in list(in_sh) + list(out_sh):
            m = getattr(s, "mesh", None)
            if m is not None:
                mesh = m
                break

    if ep.in_shardings is not None:
        if len(ep.in_shardings) != len(in_sh):
            flag("jaxpr-audit-error",
                 f"in_shardings contract lists {len(ep.in_shardings)} "
                 f"entries but the entry lowers to {len(in_sh)} input "
                 "leaves — fix the registration")
        elif mesh is None:
            flag("jaxpr-audit-error",
                 "no mesh available to evaluate the sharding contract — "
                 "return the mesh via BuiltEntry(mesh=...)")
        else:
            for i, (spec, actual) in enumerate(zip(ep.in_shardings, in_sh)):
                if spec is None:
                    continue
                ndim = len(in_leaves[i].shape)
                expected = NamedSharding(mesh, PartitionSpec(*spec))
                if actual.is_equivalent_to(expected, ndim):
                    continue
                if (getattr(actual, "is_fully_replicated", False)
                        and any(d is not None for d in spec)):
                    flag("jaxpr-implicit-gather",
                         f"input {i} ({_leaf_str(in_leaves[i])}) lowers "
                         f"fully replicated against declared sharding "
                         f"{tuple(spec)!r} — XLA all-gathers it on every "
                         "call; pin the layout with "
                         "with_sharding_constraint or fix the in_specs")
                else:
                    flag("jaxpr-implicit-gather",
                         f"input {i} ({_leaf_str(in_leaves[i])}) lowers "
                         f"to {actual} against declared sharding "
                         f"{tuple(spec)!r} — the propagated layout "
                         "implies an implicit reshard on every call")

    if ep.max_replicated_bytes is not None:
        for kind, leaves, shardings in (("input", in_leaves, in_sh),
                                        ("output", out_leaves, out_sh)):
            for i, (leaf, s) in enumerate(zip(leaves, shardings)):
                nbytes = _leaf_nbytes(leaf)
                ndev = len(getattr(s, "device_set", ()))
                if (nbytes >= ep.max_replicated_bytes and ndev > 1
                        and getattr(s, "is_fully_replicated", False)):
                    flag("jaxpr-silent-replication",
                         f"{kind} {i} ({_leaf_str(leaf)}, {nbytes} bytes) "
                         f"lowers fully replicated across {ndev} devices "
                         f"— {ndev}x HBM for a tensor above the "
                         f"registered ceiling of "
                         f"{ep.max_replicated_bytes} bytes; shard it or "
                         "raise max_replicated_bytes")


# --------------------------------------------------------------------------
# Entry-point drivers
# --------------------------------------------------------------------------

def audit_entry_point(ep: EntryPoint) -> Tuple[List[Finding],
                                               Optional[List[CollectiveOp]]]:
    """Build, trace and protocol-verify one entry point. Returns the
    findings plus the extracted collective schedule (``None`` when the
    build/trace itself failed)."""
    import jax

    path, line = _entry_location(ep)

    def flag(rule: str, message: str) -> None:
        findings.append(Finding(path, line, 0, rule,
                                f"entry point '{ep.name}': {message}"))

    findings: List[Finding] = []
    try:
        built = ep.build()
        closed = jax.make_jaxpr(built.fn)(*built.args)
    except Exception as e:
        flag("jaxpr-audit-error",
             f"build/trace failed: {type(e).__name__}: {e}")
        return findings, None

    schedule, defects = extract_schedule(closed)
    for rule, message in defects:
        flag(rule, message)

    if ep.in_shardings is not None or ep.max_replicated_bytes is not None:
        _audit_shardings(ep, built, closed, flag)
    return findings, schedule


def audit_entry_points(names: Optional[Iterable[str]] = None,
                       include_defaults: bool = True,
                       ) -> Tuple[List[Finding],
                                  Dict[str, List[CollectiveOp]]]:
    """Protocol-verify the selected (default: all registered) entry
    points. Returns ``(findings, schedules)``; ``schedules`` maps entry
    name -> extracted collective schedule."""
    entries = (load_default_entry_points() if include_defaults
               else all_entry_points())
    if names is not None:
        names = list(names)
        unknown = [n for n in names if n not in entries]
        if unknown:
            raise ValueError(
                f"unknown entry point(s): {unknown}; "
                f"known: {sorted(entries)}")
        entries = {n: entries[n] for n in names}
    findings: List[Finding] = []
    schedules: Dict[str, List[CollectiveOp]] = {}
    for name in sorted(entries):
        fs, schedule = audit_entry_point(entries[name])
        findings.extend(fs)
        if schedule is not None:
            schedules[name] = schedule
    return findings, schedules


def schedules_to_json(schedules: Dict[str, List[CollectiveOp]]) -> str:
    """Deterministic JSON for ``--emit-schedule``: same registry state in,
    byte-identical artifact out (keys sorted, no timestamps)."""
    doc = {
        "version": 1,
        "entries": {name: [op.to_json() for op in ops]
                    for name, ops in sorted(schedules.items())},
    }
    return json.dumps(doc, indent=2, sort_keys=True)
