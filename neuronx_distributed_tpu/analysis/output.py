"""Machine-readable emitters for nxdlint findings: JSON and SARIF 2.1.0.

The SARIF output follows the 2.1.0 schema shape consumed by code-scanning
UIs: ``runs[0].tool.driver`` carries the rule catalog (stable rule IDs +
short descriptions), each result carries ``ruleId``, ``level``,
``message.text`` and a ``physicalLocation`` with 1-based line/column.
Suppressed findings are emitted with an ``inSource`` suppression so
downstream tooling can audit them without failing on them.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List

from .core import Finding

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def findings_to_json(findings: Iterable[Finding]) -> str:
    rows: List[Dict[str, object]] = [
        {"path": f.path, "line": f.line, "col": f.col, "rule": f.rule,
         "message": f.message, "suppressed": f.suppressed}
        for f in findings]
    return json.dumps({"findings": rows}, indent=2, sort_keys=False)


def findings_to_sarif(findings: Iterable[Finding],
                      rule_descriptions: Dict[str, str]) -> str:
    findings = list(findings)
    used = sorted({f.rule for f in findings})
    rules = [{"id": rid,
              "shortDescription": {
                  "text": rule_descriptions.get(rid, rid)}}
             for rid in used]
    results = []
    for f in findings:
        result: Dict[str, object] = {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/")},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    doc = {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "nxdlint",
                                "informationUri":
                                    "docs/analysis.md",
                                "rules": rules}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
