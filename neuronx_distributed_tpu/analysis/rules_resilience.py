"""Rule ``resilience``: signal-handler and sleep hygiene.

Two failure classes the resilience subsystem exists to prevent creep back
in easily:

* **Bare ``signal.signal`` registration outside ``resilience/``** — ad-hoc
  handlers silently replace :class:`PreemptionGuard`'s, so SIGTERM stops
  producing the emergency checkpoint + resumable exit contract
  (``docs/resilience.md``). All signal registration must go through the
  guard (or live in the resilience package itself).

* **``time.sleep`` inside JAX-traced code** — a sleep in a ``jit``/
  ``shard_map``/``scan`` body runs at *trace* time only: the compiled
  program contains no delay, so the backoff/pacing the author intended
  silently does nothing (and retrace pauses show up at random). Host-side
  retry loops (``checkpoint_storage.retry_with_backoff``) are fine — the
  rule only fires inside syntactically-traced functions, reusing the
  trace-safety detector.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from . import astutil
from .core import Finding, LintContext, register
from .rules_trace_safety import _traced_function_nodes


def _in_resilience_package(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/resilience/" in norm or norm.startswith("resilience/")


def _is_signal_signal(call: ast.Call) -> bool:
    # signal.signal(...) or `from signal import signal; signal(...)`
    tail = astutil.tail_name(call.func)
    root = astutil.root_name(call.func)
    return tail == "signal" and root == "signal"


def _is_time_sleep(call: ast.Call) -> bool:
    tail = astutil.tail_name(call.func)
    root = astutil.root_name(call.func)
    # time.sleep(...) or `from time import sleep; sleep(...)`
    return (tail == "sleep" and root == "time") or \
        (tail == "sleep" and root == "sleep")


@register(
    "resilience",
    "bare signal.signal registration outside resilience/ (bypasses "
    "PreemptionGuard) and time.sleep inside JAX-traced code (no-op in the "
    "compiled program)")
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []

    if not _in_resilience_package(ctx.path):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_signal_signal(node):
                findings.append(Finding(
                    ctx.path, node.lineno, node.col_offset, "resilience",
                    "bare signal.signal registration outside resilience/ — "
                    "route signal handling through "
                    "resilience.PreemptionGuard so SIGTERM keeps the "
                    "emergency-checkpoint + resumable-exit contract"))

    traced = _traced_function_nodes(ctx.tree)
    if traced:
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            if id(node) not in traced:
                continue
            body = node.body if isinstance(node, ast.Lambda) else node
            for sub in ast.walk(body):
                if isinstance(sub, ast.Call) and _is_time_sleep(sub) \
                        and id(sub) not in seen:
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset, "resilience",
                        "time.sleep inside a JAX-traced function runs at "
                        "trace time only — the compiled program contains "
                        "no delay; move pacing to the host side"))
    yield from findings
