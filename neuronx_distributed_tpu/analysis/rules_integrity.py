"""Rule ``integrity``: host-side hashing inside JAX-traced code.

Content fingerprints are how the SDC defense (``resilience/integrity``)
notices corrupted bytes, and the tempting way to compute one is also the
broken way: ``hashlib.sha256(x.tobytes())`` (or ``zlib.crc32``) inside a
``jit``/``shard_map``/``scan`` body. Two distinct failures hide there:

* **Trace-time constants** — ``hashlib``/``zlib`` digest *concrete host
  bytes*. Under tracing, ``x`` is a tracer with no bytes; either the
  call raises, or (when fed a captured constant) it runs once at trace
  time and bakes a frozen "fingerprint" into every execution — a check
  that can never fire.

* **Forced host transfers** — ``.tobytes()`` / ``.tostring()`` on an
  array inside traced code is a device→host readback; even where JAX
  tolerates it, it breaks the one-readback-per-cadence budget the
  integrity layer is designed around.

The fix is the on-device fold: ``resilience.integrity.fingerprint_array``
/ ``fingerprint_tree`` are pure ``jnp`` bit arithmetic — jit-safe,
shard_map-safe, one int32 per leaf — with bit-exact host mirrors
(``fingerprint_array_np``) for the boundary compare. Host code (outside
traced functions) may hash freely: the checkpoint manifests *should* use
``hashlib.sha256`` on real files.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from . import astutil
from .core import Finding, LintContext, register
from .rules_trace_safety import _traced_function_nodes

#: hashlib constructors whose bare imported names we also recognize
#: (``from hashlib import sha256``).
_HASH_CTORS = frozenset({
    "md5", "sha1", "sha224", "sha256", "sha384", "sha512",
    "sha3_224", "sha3_256", "sha3_384", "sha3_512",
    "blake2b", "blake2s", "shake_128", "shake_256", "new",
})

#: zlib checksum functions (same trace-time-constant failure).
_ZLIB_FNS = frozenset({"crc32", "adler32"})

#: host readbacks that feed byte-level hashing.
_READBACK_TAILS = frozenset({"tobytes", "tostring"})


def _is_host_hash_call(call: ast.Call) -> bool:
    tail = astutil.tail_name(call.func)
    root = astutil.root_name(call.func)
    if root == "hashlib" and tail is not None:
        return True
    if tail in _ZLIB_FNS and root in ("zlib", tail):
        return True
    # bare ctor from `from hashlib import sha256` — but not `new` (too
    # generic unqualified) and not attribute forms like self.sha256(...)
    return (isinstance(call.func, ast.Name) and tail in _HASH_CTORS
            and tail != "new")


def _is_readback_call(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in _READBACK_TAILS)


@register(
    "integrity",
    "host-side hashing (hashlib/zlib) or .tobytes() readbacks inside "
    "JAX-traced code — a frozen trace-time 'fingerprint' that never "
    "detects anything; use resilience.integrity.fingerprint_array")
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []
    traced = _traced_function_nodes(ctx.tree)
    if traced:
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            if id(node) not in traced:
                continue
            body = node.body if isinstance(node, ast.Lambda) else node
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                if _is_host_hash_call(sub):
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "integrity",
                        "host-side hash inside a JAX-traced function "
                        "digests trace-time bytes (a frozen constant, "
                        "or a tracer error) — fingerprint on device "
                        "with resilience.integrity.fingerprint_array "
                        "/ fingerprint_tree"))
                elif _is_readback_call(sub):
                    seen.add(id(sub))
                    findings.append(Finding(
                        ctx.path, sub.lineno, sub.col_offset,
                        "integrity",
                        f".{sub.func.attr}() inside a JAX-traced "
                        "function forces a device->host readback (and "
                        "usually feeds a host hash) — keep integrity "
                        "fingerprints on device "
                        "(resilience.integrity.fingerprint_array)"))
    yield from findings
