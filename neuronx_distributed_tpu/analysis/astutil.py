"""Shared AST helpers for the nxdlint rules.

Everything here is stdlib-only: the analyzer must be able to lint a file
without importing it (a file whose import would initialise a TPU backend,
or one with a syntax error two lines below the bug being reported).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Tuple, Union

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.psum`` for an Attribute chain, ``psum`` for a Name, else
    None (calls of calls, subscripts, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def tail_name(node: ast.AST) -> Optional[str]:
    """Last component of a dotted callable name: ``jax.lax.psum`` -> ``psum``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """First component of a dotted name: ``np.sum`` -> ``np``."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_tail(call: ast.Call) -> Optional[str]:
    return tail_name(call.func)


def iter_str_constants(expr: ast.AST) -> Iterator[ast.Constant]:
    """Every string-literal node inside ``expr`` (descends tuples/lists but
    not into nested calls — a nested call is its own site)."""
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str):
            yield expr
    elif isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        for e in expr.elts:
            yield from iter_str_constants(e)


# Names whose decoration means "this function's body is traced by JAX".
JIT_NAMES = frozenset({"jit", "pjit"})


def _is_jit_callable_ref(node: ast.AST) -> bool:
    return tail_name(node) in JIT_NAMES


def is_jit_decorator(dec: ast.AST) -> bool:
    """@jit / @jax.jit / @nn.jit / @partial(jax.jit, ...) /
    @jax.jit(static_argnums=...)."""
    if _is_jit_callable_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_callable_ref(dec.func):
            return True  # @jax.jit(static_argnums=...)
        if tail_name(dec.func) == "partial" and dec.args \
                and _is_jit_callable_ref(dec.args[0]):
            return True
    return False


def int_tuple_values(expr: Optional[ast.AST]) -> Optional[List[int]]:
    """Literal ints from a tuple/list/bare-int expression, else None."""
    if expr is None:
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return out
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return [expr.value]
    return None


def str_tuple_values(expr: Optional[ast.AST]) -> List[str]:
    if expr is None:
        return []
    return [c.value for c in iter_str_constants(expr)]


def jit_static_param_names(dec: ast.AST, func: FuncNode) -> set:
    """Parameter names a jit-like decorator marks static
    (``static_argnames`` / ``static_argnums`` on ``@jax.jit(...)`` or
    ``@partial(jax.jit, ...)``)."""
    if not isinstance(dec, ast.Call):
        return set()
    names = set(str_tuple_values(get_kwarg(dec, "static_argnames")))
    nums = int_tuple_values(get_kwarg(dec, "static_argnums")) or []
    params = positional_args(func)
    for i in nums:
        if 0 <= i < len(params):
            names.add(params[i].arg)
    return names


def get_kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def positional_args(func: FuncNode) -> List[ast.arg]:
    a = func.args
    return list(a.posonlyargs) + list(a.args)


def arg_names(func: FuncNode) -> List[str]:
    a = func.args
    names = [x.arg for x in positional_args(func)] + \
            [x.arg for x in a.kwonlyargs]
    return names


def walk_stop_at_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Like ast.walk over a function body, but does not descend into nested
    function/class definitions (their scopes are analyzed separately)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))
