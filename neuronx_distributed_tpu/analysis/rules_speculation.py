"""Rule ``speculation-trace``: fixed-shape speculation stays fixed-shape.

The speculative-decoding integration (docs/serving.md "Speculative
decoding") holds the engine's one-executable invariant only because every
accept-rate-dependent decision is made with fixed-shape device arithmetic
(``jnp.where`` masks over all ``B * (k + 1)`` tree rows) and the round's
verdict crosses to the host exactly once, as one batched fetch. Two code
shapes quietly break that:

* **Python control flow over a traced accept value.** ``if accepted > 2:``
  or ``for _ in range(accept_len):`` inside a draft/verify function makes
  the *trace* depend on the accept mask — under ``jit`` it either raises a
  ``TracerBoolConversionError`` or, worse, silently specializes and
  recompiles per accept pattern, destroying ``compile_count() == 1``
  across accept-rate swings. The fix is a mask (``jnp.where``,
  ``lax.select``) or an explicit host conversion (``int(...)``) at the
  round boundary.

* **A host sync inside the speculation round.** ``np.asarray`` /
  ``jax.device_get`` / ``.block_until_ready()`` / ``.item()`` inside a
  round function serializes draft, verify, and bookkeeping — the
  round-trip per draft token that tree verification exists to avoid. The
  engine fetches ``(emit, accept_len, best_branch)`` once per round, in
  ``step()``, outside the round helpers.

Scope: functions whose name smells speculative (``spec``/``draft``/
``verify``/``medusa``) for the control-flow check, and round-named
functions for the host-sync check, in ``inference/`` paths. Names
assigned from ``int(...)``/``float(...)``/``bool(...)`` in the same
function are treated as host scalars and exempt — the wrapper is exactly
the documented conversion point.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set

from .core import Finding, LintContext, register

#: value names that read as an accept/reject verdict of a verify pass
_ACCEPT_RE = re.compile(
    r"(^|_)(accept(ed|s)?|accepts?_len|alen|reject(ed|s)?|best_node|"
    r"bstar)($|_)")

#: function names in speculation's blast radius (control-flow check)
_SPEC_FN_RE = re.compile(r"spec|draft|verify|medusa", re.IGNORECASE)

#: function names that ARE the speculation round (host-sync check)
_ROUND_FN_RE = re.compile(r"(^|_)round", re.IGNORECASE)

_HOST_CASTS = ("int", "float", "bool")

#: calls that force a device->host transfer mid-round
_SYNC_FUNCS = ("asarray", "array", "device_get", "block_until_ready",
               "item")


def _tail(node: ast.AST):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _accept_names(expr: ast.AST, casted: Set[int]) -> List[str]:
    """Accept-named values referenced by ``expr`` that are not wrapped
    in a host cast (``int(...)`` etc., collected in ``casted``)."""
    out: List[str] = []
    for node in ast.walk(expr):
        for cand in (_tail(node),
                     _tail(node.value)
                     if isinstance(node, ast.Subscript) else None):
            if (cand and _ACCEPT_RE.search(cand)
                    and id(node) not in casted):
                out.append(cand)
                break
    return out


def _casted_nodes(expr: ast.AST) -> Set[int]:
    """ids of every node living inside an int()/float()/bool() call."""
    out: Set[int] = set()
    for node in ast.walk(expr):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _HOST_CASTS):
            out.update(id(n) for a in node.args for n in ast.walk(a))
    return out


def _host_assigned(fn: ast.AST) -> Set[str]:
    """Names bound from a host cast anywhere in the function — these are
    Python scalars, so branching on them is trace-safe."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in _HOST_CASTS):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def _sync_call(call: ast.Call):
    """The offending name when ``call`` is a mid-round host sync."""
    f = call.func
    name = _tail(f)
    if name not in _SYNC_FUNCS:
        return None
    if name in ("asarray", "array"):
        # only np.asarray/np.array/numpy.* — a bare asarray() is ambiguous
        base = _tail(f.value) if isinstance(f, ast.Attribute) else None
        if base not in ("np", "numpy"):
            return None
        return f"{base}.{name}"
    if name == "device_get":
        base = _tail(f.value) if isinstance(f, ast.Attribute) else None
        if base not in ("jax",):
            return None
        return "jax.device_get"
    if name in ("block_until_ready", "item"):
        # method spelling: x.block_until_ready() / x.item()
        if isinstance(f, ast.Attribute):
            return f".{name}()"
    return None


@register(
    "speculation-trace",
    "Python control flow over a traced accept value in a speculation "
    "function (branch count depends on the accept mask: recompile "
    "hazard under the fixed-shape step), or a host sync inside the "
    "speculation round (serializes the round tree verification exists "
    "to batch) — use jnp.where masks, and fetch the verdict once at "
    "the round boundary",
    scope=("inference",))
def check(ctx: LintContext) -> Iterator[Finding]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        spec_fn = _SPEC_FN_RE.search(fn.name) is not None
        round_fn = _ROUND_FN_RE.search(fn.name) is not None
        if not spec_fn and not round_fn:
            continue
        host_names = _host_assigned(fn)
        for node in ast.walk(fn):
            if spec_fn and isinstance(node, (ast.If, ast.While, ast.IfExp)):
                casted = _casted_nodes(node.test)
                hits = [n for n in _accept_names(node.test, casted)
                        if n not in host_names]
                if hits:
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "speculation-trace",
                        f"branching on accept value `{hits[0]}` in "
                        f"`{fn.name}` — under jit the branch count "
                        "depends on the traced accept mask, so the "
                        "executable specializes per accept pattern and "
                        "compile_count()==1 dies on the first "
                        "accept-rate swing; keep the shape fixed with "
                        "jnp.where/lax.select over all tree rows, or "
                        "host-convert once with int(...) at the round "
                        "boundary")
            if spec_fn and isinstance(node, ast.For):
                it = node.iter
                if (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id == "range"):
                    casted = _casted_nodes(it)
                    hits = [n for n in _accept_names(it, casted)
                            if n not in host_names]
                    if hits:
                        yield Finding(
                            ctx.path, node.lineno, node.col_offset,
                            "speculation-trace",
                            f"loop trip count from accept value "
                            f"`{hits[0]}` in `{fn.name}` — a "
                            "range() over a traced accept length "
                            "unrolls differently per accept pattern "
                            "(recompile hazard); mask the fixed "
                            "k+1-row window instead")
            if round_fn and isinstance(node, ast.Call):
                sync = _sync_call(node)
                if sync is not None:
                    yield Finding(
                        ctx.path, node.lineno, node.col_offset,
                        "speculation-trace",
                        f"host sync `{sync}` inside speculation round "
                        f"`{fn.name}` — the round's verdict must cross "
                        "to the host exactly once (one batched fetch "
                        "after verify); a sync inside the round "
                        "serializes draft/verify/bookkeeping into the "
                        "per-token round-trip speculation exists to "
                        "amortize")
