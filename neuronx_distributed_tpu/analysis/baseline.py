"""The nxdlint CI ratchet: a baseline of known findings.

``--fail-on-new`` lets the self-gate extend to directories with
pre-existing findings (``tests/``, ``examples/``) without a big-bang
cleanup: existing findings are recorded in ``.nxdlint-baseline.json``
once, and CI fails only on findings *not* in the baseline. Fixing a
baselined finding never breaks the build (the baseline is a ceiling,
not a pin); introducing a new one does.

A finding's fingerprint is ``(normalized path, rule, message)`` with a
multiplicity count — deliberately *without* line numbers, so unrelated
edits that shift code down a file do not invalidate the baseline, while
adding a second identical violation to the same file still fails.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Tuple

from .core import Finding

_VERSION = 1

Fingerprint = Tuple[str, str, str]


def _norm_path(path: str) -> str:
    norm = path.replace("\\", "/")
    if os.path.isabs(norm):
        rel = os.path.relpath(norm).replace("\\", "/")
        if not rel.startswith(".."):
            norm = rel
    while norm.startswith("./"):
        norm = norm[2:]
    return norm


def fingerprint(f: Finding) -> Fingerprint:
    return (_norm_path(f.path), f.rule, f.message)


def counts(findings: Iterable[Finding]) -> Dict[Fingerprint, int]:
    out: Dict[Fingerprint, int] = {}
    for f in findings:
        fp = fingerprint(f)
        out[fp] = out.get(fp, 0) + 1
    return out


def write_baseline(path: str, findings: Iterable[Finding]) -> int:
    """Persist the fingerprints of ``findings``; returns the entry count."""
    entries = [{"path": p, "rule": r, "message": m, "count": c}
               for (p, r, m), c in sorted(counts(findings).items())]
    doc = {"version": _VERSION, "entries": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    return len(entries)


def load_baseline(path: str) -> Dict[Fingerprint, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} "
            f"in {path} (expected {_VERSION})")
    out: Dict[Fingerprint, int] = {}
    for e in doc.get("entries", ()):
        out[(e["path"], e["rule"], e["message"])] = int(e.get("count", 1))
    return out


def new_findings(findings: Iterable[Finding],
                 baseline: Dict[Fingerprint, int]) -> List[Finding]:
    """Findings beyond the baselined multiplicity of their fingerprint.
    Within a fingerprint the earliest occurrences (by line) are treated
    as the baselined ones."""
    groups: Dict[Fingerprint, List[Finding]] = {}
    for f in findings:
        groups.setdefault(fingerprint(f), []).append(f)
    fresh: List[Finding] = []
    for fp, fs in groups.items():
        allowed = baseline.get(fp, 0)
        fs.sort(key=lambda f: (f.line, f.col))
        fresh.extend(fs[allowed:])
    fresh.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return fresh
