"""Rule ``plan``: hand-rolled parallelism layouts the placement planner
strictly dominates.

A ``neuronx_distributed_config(...)`` call site with literal parallelism
kwargs pins a placement forever. This rule rebuilds the implied
:class:`..plan.cost.Plan`, runs the placement search
(:func:`..plan.search.search`) at the same device count over a fixed
reference model, and fires when the search's best plan models a step
time at least ``_MARGIN`` cheaper — i.e. the committed layout is
*dominated*: same hardware, strictly lower modeled cost, usually because
it leaves a known knob on the table (bubble-heavy pp with too few
microbatches, flat fp32 gradient rings across DCN, disabled overlap).

Only fully literal call sites are judged: any ``**kwargs``, any
non-constant relevant kwarg, or nested config objects with computed
arguments make the layout data-driven, and data-driven call sites are
someone's planner already. ``plan/`` itself is exempt (the emitter is
the planner's own output path), as are default-only calls (nothing to
dominate).

Unlike its sibling rules this one is not purely syntactic — it imports
the planner's cost model. It still never imports the code under
analysis.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, Optional

from . import astutil
from .core import Finding, LintContext, register

#: flag only when the planner's best plan is at least this much faster
_MARGIN = 1.05

# kwargs that define the layout; any of these non-literal -> skip the call
_PARALLEL_KWARGS = ("tensor_parallel_size", "pipeline_parallel_size",
                    "context_parallel_size", "expert_parallel_size",
                    "dcn_data_parallel_size", "tp_overlap_comm",
                    "sequence_parallel")
_NESTED = {"optimizer_config": "OptimizerConfig",
           "pipeline_config": "PipelineConfig",
           "activation_checkpoint_config": "ActivationCheckpointConfig"}


def _literal(node: ast.AST) -> Optional[Any]:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _nested_kwargs(node: ast.AST, clsname: str) -> Optional[Dict[str, Any]]:
    """Literal kwargs of a nested ``OptimizerConfig(...)``-style call, or
    None when it isn't one / isn't fully literal."""
    if not (isinstance(node, ast.Call)
            and astutil.tail_name(node.func) == clsname
            and not node.args):
        return None
    out: Dict[str, Any] = {}
    for kw in node.keywords:
        if kw.arg is None:
            return None
        if not isinstance(kw.value, ast.Constant):
            return None
        out[kw.arg] = kw.value.value
    return out


def _extract(call: ast.Call) -> Optional[Dict[str, Any]]:
    """The layout-relevant literal kwargs of one call site, or None when
    the site is not judgeable (``**kwargs`` / non-literal values)."""
    info: Dict[str, Any] = {}
    for kw in call.keywords:
        if kw.arg is None:          # **kwargs: layout is data-driven
            return None
        if kw.arg in _PARALLEL_KWARGS:
            val = _literal(kw.value)
            if val is None and not (isinstance(kw.value, ast.Constant)
                                    and kw.value.value is None):
                return None
            info[kw.arg] = val
        elif kw.arg in _NESTED:
            nested = _nested_kwargs(kw.value, _NESTED[kw.arg])
            if nested is None:
                return None
            info[kw.arg] = nested
    return info


def _reference_spec(world: int):
    """Fixed model the domination check is scored against: a ~1.7B llama
    shape (heads/layers divide every power-of-two degree up to 32, and
    feasible layouts exist from 2 devices up — a 7B-class reference would
    OOM every candidate at small worlds and mute the rule) with a global
    batch divisible by any dp that divides ``world``."""
    from ..plan.cost import ModelSpec

    return ModelSpec(name="lint-reference", vocab=32000, hidden=2048,
                     intermediate=5504, layers=32, heads=32, kv_heads=32,
                     seq=4096, global_batch=max(32, 2 * world))


def _implied_plan(info: Dict[str, Any], world: int, dcn: int):
    from ..plan.cost import Plan

    opt = info.get("optimizer_config", {})
    pipe = info.get("pipeline_config", {})
    ckpt = info.get("activation_checkpoint_config", {})
    tp = info.get("tensor_parallel_size") or 1
    pp = info.get("pipeline_parallel_size") or 1
    cp = info.get("context_parallel_size") or 1
    return Plan(
        devices=world, tp=tp, pp=pp, cp=cp,
        dp=world // (tp * pp * cp), dcn_dp=dcn,
        zero1=bool(opt.get("zero_one_enabled", False)),
        grad_comm_dtype=opt.get("grad_comm_dtype", "fp32"),
        grad_comm_hierarchical=bool(opt.get("grad_comm_hierarchical",
                                            False)),
        tp_overlap=bool(info.get("tp_overlap_comm")),
        sequence_parallel=bool(info.get("sequence_parallel", False)),
        remat=ckpt.get("mode", "none") != "none",
        num_microbatches=pipe.get("num_microbatches", 1))


@register(
    "plan",
    "hand-rolled neuronx_distributed_config(...) layout that the "
    "placement planner strictly dominates at the same device count — "
    "run python -m neuronx_distributed_tpu.plan",
    exempt=("plan",))
def check(ctx: LintContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and astutil.tail_name(node.func)
                == "neuronx_distributed_config"):
            continue
        info = _extract(node)
        if info is None:
            continue
        tp = info.get("tensor_parallel_size") or 1
        pp = info.get("pipeline_parallel_size") or 1
        cp = info.get("context_parallel_size") or 1
        dcn = info.get("dcn_data_parallel_size") or 1
        world = tp * pp * cp * dcn
        if world <= 1:
            continue    # defaults: nothing committed, nothing to judge
        from ..plan.cost import default_hardware, step_cost
        from ..plan.search import search

        spec = _reference_spec(world)
        hand = _implied_plan(info, world, dcn)
        try:
            hand_cost = step_cost(hand, spec, default_hardware())
        except (ValueError, ZeroDivisionError):
            continue    # layout incompatible with the reference shapes
        result = search(spec, default_hardware(), world, dcn_dp=dcn)
        best = result.best
        if best is None or best.total_s * _MARGIN >= hand_cost.total_s:
            continue
        yield Finding(
            ctx.path, node.lineno, node.col_offset, "plan",
            f"hand-rolled layout ({hand.describe()}) models "
            f"{hand_cost.total_s * 1e3:.1f} ms/step on the reference "
            f"model; the planner's best at the same {world} device(s) "
            f"({best.plan.describe()}) models "
            f"{best.total_s * 1e3:.1f} ms — "
            "run python -m neuronx_distributed_tpu.plan "
            "(docs/planner.md) or suppress if the layout is "
            "hardware-constrained")
