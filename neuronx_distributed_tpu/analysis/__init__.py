"""nxdlint — JAX/SPMD-aware static analysis for neuronx_distributed_tpu.

An AST-based linter for the stringly-typed invariants the Python toolchain
never checks: mesh-axis names, trace-safety of host operations, custom_vjp
fwd/bwd pairing, and jit recompilation hazards. See ``docs/analysis.md``.

Run it::

    python -m neuronx_distributed_tpu.analysis neuronx_distributed_tpu/

Suppress a finding in code::

    x = np.float32(scale)  # nxdlint: disable=trace-safety  -- host constant
"""

from .core import (DEFAULT_AXES, Finding, LintContext, Rule, all_rules,
                   analyze_paths, analyze_source, parse_suppressions)

__all__ = [
    "DEFAULT_AXES",
    "Finding",
    "LintContext",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "parse_suppressions",
]
