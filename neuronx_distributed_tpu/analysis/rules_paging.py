"""Rule ``paging-refcount``: block-pool bookkeeping stays in ``paging.py``.

With copy-on-write prefix sharing, correctness of the paged pool rests on
two invariants that only hold while every mutation goes through
``BlockAllocator`` / the engine's table plumbing (``docs/serving.md``):

* the allocator's free list and refcounts (``_free`` / ``_allocated`` /
  ``_refs``) agree with each other — a block is either on the free list
  or refcounted, never both. Code that appends to ``alloc._free`` or pokes
  ``alloc._refs[b]`` directly can double-free a block that another
  sequence still shares, silently cross-contaminating KV.
* ``block_tables`` rows are remapped only by the engine's admit / COW /
  release paths, which keep host mirrors, freed-position hygiene and the
  prefix trie in sync. A stray ``cache.block_tables.at[i].set(...)`` (or
  ``tables[i] = ...`` on the attribute) bypasses all three.

Everything outside ``inference/paging.py`` must use the public API:
``alloc()`` / ``ref()`` / ``free()`` and ``PagedKVCache.replace(...)``
fed from the engine's host tables.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from .core import Finding, LintContext, register

_ALLOC_PRIVATE = ("_free", "_allocated", "_refs")
_MUTATORS = ("append", "pop", "remove", "extend", "insert", "clear",
             "update", "discard", "add", "setdefault", "popitem")
_AT_WRITES = ("set", "add", "multiply", "mul", "divide", "div", "power",
              "min", "max", "apply")


def _attr_named(node, names) -> bool:
    return isinstance(node, ast.Attribute) and node.attr in names


def _block_tables_at_chain(call: ast.Call) -> bool:
    """``<x>.block_tables.at[...].set(...)`` (or any ``.at`` write op)."""
    f = call.func
    return (_attr_named(f, _AT_WRITES)
            and isinstance(f.value, ast.Subscript)
            and _attr_named(f.value.value, ("at",))
            and _attr_named(f.value.value.value, ("block_tables",)))


def _targets(node) -> List[ast.expr]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    return [node.target]                                 # AugAssign


@register(
    "paging-refcount",
    "direct free-list/refcount (_free/_allocated/_refs) or block_tables "
    "mutation outside inference/paging.py (bypasses the refcounted "
    "allocator + COW invariants and can cross-contaminate shared KV)",
    exempt=("inference/paging.py",))
def check(ctx: LintContext) -> Iterator[Finding]:
    findings: List[Finding] = []

    def flag(node, what: str) -> None:
        findings.append(Finding(
            ctx.path, node.lineno, node.col_offset, "paging-refcount",
            f"{what} — block-pool bookkeeping belongs to "
            "inference/paging.py; go through BlockAllocator "
            "(alloc/ref/free) or the engine's table plumbing"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            for tgt in _targets(node):
                if (isinstance(tgt, ast.Subscript)
                        and _attr_named(tgt.value,
                                        _ALLOC_PRIVATE + ("block_tables",))):
                    flag(node, f"direct item assignment into "
                         f"`.{tgt.value.attr}`")
                elif _attr_named(tgt, _ALLOC_PRIVATE + ("block_tables",)):
                    flag(node, f"direct rebind of `.{tgt.attr}`")
        elif isinstance(node, ast.Call):
            f = node.func
            if _block_tables_at_chain(node):
                flag(node, "in-place `.at[...]` write on `.block_tables`")
            elif (_attr_named(f, _MUTATORS)
                    and _attr_named(f.value, _ALLOC_PRIVATE)):
                flag(node, f"mutating call "
                     f"`.{f.value.attr}.{f.attr}(...)` on allocator "
                     "internals")
    yield from findings
