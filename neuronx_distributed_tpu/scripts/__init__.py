"""Conversion and operator CLIs (reference: ``scripts/``)."""

from . import checkpoint_converter

__all__ = ["checkpoint_converter"]
