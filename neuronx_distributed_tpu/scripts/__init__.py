"""Conversion and operator CLIs (reference: ``scripts/``)."""

from . import checkpoint_converter
from . import reshard_checkpoint

__all__ = ["checkpoint_converter", "reshard_checkpoint"]
