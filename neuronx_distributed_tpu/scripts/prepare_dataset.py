"""Tokenize and pack text into the native loader's flat token stream.

Completes the data path end to end: raw text -> HF tokenizer -> packed
``(seqlen + 1)``-token rows -> flat uint16/uint32 ``.bin`` that
``data/native_loader.TokenBatchLoader`` (csrc/data_loader.cpp) mmaps and
prefetches off the GIL. The role of the reference's dataset preparation in
its training examples (``examples/training/llama/.../get_dataset.py`` —
tokenize, concatenate, chunk to seqlen blocks).

    python -m neuronx_distributed_tpu.scripts.prepare_dataset \
        --input corpus.txt --tokenizer hf-internal-testing/llama-tokenizer \
        --seqlen 2048 --output tokens.bin

``--input`` accepts a text file (one document per line) or ``-`` for
stdin. Documents are concatenated with the tokenizer's EOS between them
and chunked into non-overlapping ``seqlen + 1`` rows (the +1 provides the
shifted-label target); the trailing remainder is dropped.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def pack_tokens(token_iter, seqlen: int, dtype) -> "np.ndarray":
    """Concatenate token id chunks and cut into [N, seqlen+1] rows."""
    flat = np.concatenate([np.asarray(t, np.int64) for t in token_iter])
    per = seqlen + 1
    n = len(flat) // per
    if n == 0:
        raise ValueError(
            f"corpus has {len(flat)} tokens, fewer than one row of "
            f"seqlen+1 = {per}")
    info = np.iinfo(dtype)
    if flat.max(initial=0) > info.max:
        raise ValueError(
            f"token id {int(flat.max())} exceeds {np.dtype(dtype).name}; "
            "use --dtype uint32")
    return flat[:n * per].astype(dtype)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True,
                    help="text file (one document per line), or '-'")
    ap.add_argument("--tokenizer", required=True,
                    help="HF tokenizer name or local path")
    ap.add_argument("--seqlen", type=int, default=2048)
    ap.add_argument("--output", required=True, help="output .bin path")
    ap.add_argument("--dtype", default="uint16",
                    choices=["uint16", "uint32"])
    args = ap.parse_args(argv)

    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(args.tokenizer)
    eos = [tok.eos_token_id] if tok.eos_token_id is not None else []

    def token_chunks():
        stream = (sys.stdin if args.input == "-"
                  else open(args.input, encoding="utf-8"))
        for line in stream:
            line = line.strip()
            if not line:
                continue
            yield tok.encode(line) + eos

    packed = pack_tokens(token_chunks(), args.seqlen,
                         np.dtype(args.dtype))
    packed.tofile(args.output)
    per = args.seqlen + 1
    print(f"wrote {args.output}: {len(packed) // per} sequences of "
          f"seqlen {args.seqlen} ({packed.nbytes / 1e6:.1f} MB, "
          f"{args.dtype})")


if __name__ == "__main__":
    main()
