"""HuggingFace ↔ framework checkpoint conversion.

Analogue of the reference's ``scripts/checkpoint_converter.py``
(``CheckpointConverterBase:23``: full↔TP/PP-sharded conversion, QKV
fuse/split with the GQA kv multiplier ``convert_full_state_to_tp:513``,
``merge_tp_checkpoints:317``).

TPU-native simplification: sharding is NOT baked into files — the framework
checkpoint is the *unsharded* param pytree (placement happens at load via
NamedSharding, and resharding between parallel configs is automatic, see
``trainer/checkpoint.py``). So conversion here is pure *naming/layout*
translation between the HF llama state dict and our scanned param tree:

=============================================  =============================
HF (torch ``[out, in]`` layout)                ours (``[in, out]``; layers
                                               stacked on a leading L dim)
=============================================  =============================
model.embed_tokens.weight                      model/embed/embedding
model.layers.N.self_attn.{q,k,v}_proj.weight   model/layers/layer/attn/qkv/
                                               {q,k,v}_kernel
model.layers.N.self_attn.o_proj.weight         model/layers/layer/attn/o_proj
model.layers.N.mlp.{gate,up}_proj.weight       fused gate_up_kernel [H, 2, I]
model.layers.N.mlp.down_proj.weight            model/layers/layer/mlp/down
model.layers.N.input_layernorm.weight          .../input_norm/scale
model.layers.N.post_attention_layernorm.weight .../post_norm/scale
model.norm.weight                              model/norm/scale
lm_head.weight                                 lm_head/kernel
=============================================  =============================
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def _t(w) -> np.ndarray:
    """torch [out, in] -> [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def convert_hf_llama_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF llama state dict (numpy/torch tensors) → our param tree
    (``LlamaForCausalLM`` with ``scan_layers=True``)."""
    sd = {k: np.asarray(v.float().numpy() if hasattr(v, "numpy") else v)
          for k, v in state_dict.items()}
    L = cfg.num_layers

    def stack(fmt: str, transform=_t) -> np.ndarray:
        return np.stack([transform(sd[fmt.format(i)]) for i in range(L)])

    layers = {
        "attn": {
            "qkv": {
                "q_kernel": stack(
                    "model.layers.{}.self_attn.q_proj.weight"),
                "k_kernel": stack(
                    "model.layers.{}.self_attn.k_proj.weight"),
                "v_kernel": stack(
                    "model.layers.{}.self_attn.v_proj.weight"),
            },
            "o_proj": {"kernel": stack(
                "model.layers.{}.self_attn.o_proj.weight")},
        },
        "mlp": {
            # fused [L, H, 2, I]: index 0 = gate, 1 = up
            "gate_up_kernel": np.stack([
                np.stack([_t(sd[f"model.layers.{i}.mlp.gate_proj.weight"]),
                          _t(sd[f"model.layers.{i}.mlp.up_proj.weight"])],
                         axis=1)
                for i in range(L)]),
            "down": {"kernel": stack("model.layers.{}.mlp.down_proj.weight")},
        },
        "input_norm": {"scale": stack(
            "model.layers.{}.input_layernorm.weight", np.asarray)},
        "post_norm": {"scale": stack(
            "model.layers.{}.post_attention_layernorm.weight", np.asarray)},
    }
    tree = {"params": {
        "model": {
            "embed": {"embedding": sd["model.embed_tokens.weight"]},
            "layers": {"layer": layers},
            "norm": {"scale": sd["model.norm.weight"]},
        },
    }}
    if getattr(cfg, "tie_embeddings", False):
        # tied models carry no lm_head param (llama.py tie_embeddings);
        # matches HF's tie_word_embeddings checkpoints omitting
        # lm_head.weight
        return tree
    lm_head = (sd["lm_head.weight"] if "lm_head.weight" in sd
               else sd["model.embed_tokens.weight"])
    tree["params"]["lm_head"] = {"kernel": _t(lm_head)}
    return tree


def convert_nxd_to_hf_llama(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_llama_to_nxd`."""
    p = params["params"]
    layers = p["model"]["layers"]["layer"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            p["model"]["embed"]["embedding"]),
        "model.norm.weight": np.asarray(p["model"]["norm"]["scale"]),
    }
    if "lm_head" in p:
        out["lm_head.weight"] = _t(p["lm_head"]["kernel"])
    # tied models (no lm_head param) export the HF tie_word_embeddings
    # convention: lm_head.weight omitted, embed_tokens carries the table
    L = cfg.num_layers
    for i in range(L):
        pre = f"model.layers.{i}."
        qkv = layers["attn"]["qkv"]
        out[pre + "self_attn.q_proj.weight"] = _t(qkv["q_kernel"][i])
        out[pre + "self_attn.k_proj.weight"] = _t(qkv["k_kernel"][i])
        out[pre + "self_attn.v_proj.weight"] = _t(qkv["v_kernel"][i])
        out[pre + "self_attn.o_proj.weight"] = _t(
            layers["attn"]["o_proj"]["kernel"][i])
        gu = np.asarray(layers["mlp"]["gate_up_kernel"][i])  # [H, 2, I]
        out[pre + "mlp.gate_proj.weight"] = _t(gu[:, 0])
        out[pre + "mlp.up_proj.weight"] = _t(gu[:, 1])
        out[pre + "mlp.down_proj.weight"] = _t(
            layers["mlp"]["down"]["kernel"][i])
        out[pre + "input_layernorm.weight"] = np.asarray(
            layers["input_norm"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = np.asarray(
            layers["post_norm"]["scale"][i])
    return out


def main(argv=None) -> None:
    """CLI (reference: the ``CheckpointConverterBase`` argparse driver)."""
    import argparse
    import pickle

    ap = argparse.ArgumentParser(
        description="Convert HF llama checkpoints to/from the framework "
                    "param-tree format")
    ap.add_argument("--input", required=True,
                    help=".safetensors / torch .bin / pickled tree")
    ap.add_argument("--output", required=True)
    ap.add_argument("--direction", choices=["hf2nxd", "nxd2hf"],
                    default="hf2nxd")
    ap.add_argument("--num-layers", type=int, required=True)
    args = ap.parse_args(argv)

    from ..models.llama import LlamaConfig

    cfg = LlamaConfig(num_layers=args.num_layers)

    if args.input.endswith(".safetensors"):
        from safetensors.numpy import load_file

        sd = load_file(args.input)
    else:
        with open(args.input, "rb") as f:
            sd = pickle.load(f)

    out = (convert_hf_llama_to_nxd(sd, cfg) if args.direction == "hf2nxd"
           else convert_nxd_to_hf_llama(sd, cfg))
    with open(args.output, "wb") as f:
        pickle.dump(out, f)
    print(f"wrote {args.output}")


if __name__ == "__main__":  # pragma: no cover
    main()
