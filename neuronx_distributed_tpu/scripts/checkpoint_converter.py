"""HuggingFace ↔ framework checkpoint conversion.

Analogue of the reference's ``scripts/checkpoint_converter.py``
(``CheckpointConverterBase:23``: full↔TP/PP-sharded conversion, QKV
fuse/split with the GQA kv multiplier ``convert_full_state_to_tp:513``,
``merge_tp_checkpoints:317``).

TPU-native simplification: sharding is NOT baked into files — the framework
checkpoint is the *unsharded* param pytree (placement happens at load via
NamedSharding, and resharding between parallel configs is automatic, see
``trainer/checkpoint.py``). So conversion here is pure *naming/layout*
translation between the HF llama state dict and our scanned param tree:

=============================================  =============================
HF (torch ``[out, in]`` layout)                ours (``[in, out]``; layers
                                               stacked on a leading L dim)
=============================================  =============================
model.embed_tokens.weight                      model/embed/embedding
model.layers.N.self_attn.{q,k,v}_proj.weight   model/layers/layer/attn/qkv/
                                               {q,k,v}_kernel
model.layers.N.self_attn.o_proj.weight         model/layers/layer/attn/o_proj
model.layers.N.mlp.{gate,up}_proj.weight       fused gate_up_kernel [H, 2, I]
model.layers.N.mlp.down_proj.weight            model/layers/layer/mlp/down
model.layers.N.input_layernorm.weight          .../input_norm/scale
model.layers.N.post_attention_layernorm.weight .../post_norm/scale
model.norm.weight                              model/norm/scale
lm_head.weight                                 lm_head/kernel
=============================================  =============================
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def _t(w) -> np.ndarray:
    """torch [out, in] -> [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def convert_hf_llama_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF llama state dict (numpy/torch tensors) → our param tree
    (``LlamaForCausalLM`` with ``scan_layers=True``)."""
    sd = {k: np.asarray(v.float().numpy() if hasattr(v, "numpy") else v)
          for k, v in state_dict.items()}
    L = cfg.num_layers

    def stack(fmt: str, transform=_t) -> np.ndarray:
        return np.stack([transform(sd[fmt.format(i)]) for i in range(L)])

    layers = {
        "attn": {
            "qkv": {
                "q_kernel": stack(
                    "model.layers.{}.self_attn.q_proj.weight"),
                "k_kernel": stack(
                    "model.layers.{}.self_attn.k_proj.weight"),
                "v_kernel": stack(
                    "model.layers.{}.self_attn.v_proj.weight"),
            },
            "o_proj": {"kernel": stack(
                "model.layers.{}.self_attn.o_proj.weight")},
        },
        "mlp": {
            # fused [L, H, 2, I]: index 0 = gate, 1 = up
            "gate_up_kernel": np.stack([
                np.stack([_t(sd[f"model.layers.{i}.mlp.gate_proj.weight"]),
                          _t(sd[f"model.layers.{i}.mlp.up_proj.weight"])],
                         axis=1)
                for i in range(L)]),
            "down": {"kernel": stack("model.layers.{}.mlp.down_proj.weight")},
        },
        "input_norm": {"scale": stack(
            "model.layers.{}.input_layernorm.weight", np.asarray)},
        "post_norm": {"scale": stack(
            "model.layers.{}.post_attention_layernorm.weight", np.asarray)},
    }
    tree = {"params": {
        "model": {
            "embed": {"embedding": sd["model.embed_tokens.weight"]},
            "layers": {"layer": layers},
            "norm": {"scale": sd["model.norm.weight"]},
        },
    }}
    if getattr(cfg, "tie_embeddings", False):
        # tied models carry no lm_head param (llama.py tie_embeddings);
        # matches HF's tie_word_embeddings checkpoints omitting
        # lm_head.weight
        return tree
    lm_head = (sd["lm_head.weight"] if "lm_head.weight" in sd
               else sd["model.embed_tokens.weight"])
    tree["params"]["lm_head"] = {"kernel": _t(lm_head)}
    return tree


def convert_nxd_to_hf_llama(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_llama_to_nxd`."""
    p = params["params"]
    layers = p["model"]["layers"]["layer"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            p["model"]["embed"]["embedding"]),
        "model.norm.weight": np.asarray(p["model"]["norm"]["scale"]),
    }
    if "lm_head" in p:
        out["lm_head.weight"] = _t(p["lm_head"]["kernel"])
    # tied models (no lm_head param) export the HF tie_word_embeddings
    # convention: lm_head.weight omitted, embed_tokens carries the table
    L = cfg.num_layers
    for i in range(L):
        pre = f"model.layers.{i}."
        qkv = layers["attn"]["qkv"]
        out[pre + "self_attn.q_proj.weight"] = _t(qkv["q_kernel"][i])
        out[pre + "self_attn.k_proj.weight"] = _t(qkv["k_kernel"][i])
        out[pre + "self_attn.v_proj.weight"] = _t(qkv["v_kernel"][i])
        out[pre + "self_attn.o_proj.weight"] = _t(
            layers["attn"]["o_proj"]["kernel"][i])
        gu = np.asarray(layers["mlp"]["gate_up_kernel"][i])  # [H, 2, I]
        out[pre + "mlp.gate_proj.weight"] = _t(gu[:, 0])
        out[pre + "mlp.up_proj.weight"] = _t(gu[:, 1])
        out[pre + "mlp.down_proj.weight"] = _t(
            layers["mlp"]["down"]["kernel"][i])
        out[pre + "input_layernorm.weight"] = np.asarray(
            layers["input_norm"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = np.asarray(
            layers["post_norm"]["scale"][i])
    return out


def _stack(sd: Dict[str, Any], fmt: str, num_layers: int,
           transform=_t) -> np.ndarray:
    """Stack per-layer HF tensors onto the leading scan dim (the analogue of
    the reference ``CheckpointConverterBase`` layer loops,
    ``scripts/checkpoint_converter.py:171-266``)."""
    return np.stack([transform(sd[fmt.format(i)])
                     for i in range(num_layers)])


def _asnp(w) -> np.ndarray:
    return np.asarray(w)


def convert_hf_mixtral_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF Mixtral state dict → our param tree (``MixtralForCausalLM``,
    ``scan_layers=True``). Expert stacking: HF's per-expert ``w1``
    (gate) / ``w3`` (up) fuse into ``gate_up [L, E, H, 2, I]``; ``w2``
    (down) stacks to ``[L, E, I, H]`` (reference Mixtral conversion)."""
    sd = {k: np.asarray(v) for k, v in state_dict.items()}
    L, E = cfg.num_layers, cfg.num_experts

    def expert_gate_up(i):
        pre = f"model.layers.{i}.block_sparse_moe.experts"
        return np.stack([
            np.stack([_t(sd[f"{pre}.{e}.w1.weight"]),
                      _t(sd[f"{pre}.{e}.w3.weight"])], axis=1)
            for e in range(E)])  # [E, H, 2, I]

    def expert_down(i):
        pre = f"model.layers.{i}.block_sparse_moe.experts"
        return np.stack([_t(sd[f"{pre}.{e}.w2.weight"])
                         for e in range(E)])  # [E, I, H]

    layers = {
        "attn": {
            "qkv": {
                "q_kernel": _stack(
                    sd, "model.layers.{}.self_attn.q_proj.weight", L),
                "k_kernel": _stack(
                    sd, "model.layers.{}.self_attn.k_proj.weight", L),
                "v_kernel": _stack(
                    sd, "model.layers.{}.self_attn.v_proj.weight", L),
            },
            "o_proj": {"kernel": _stack(
                sd, "model.layers.{}.self_attn.o_proj.weight", L)},
        },
        "moe": {
            "router": {"kernel": _stack(
                sd, "model.layers.{}.block_sparse_moe.gate.weight", L)},
            "experts": {
                "gate_up": np.stack([expert_gate_up(i) for i in range(L)]),
                "down": np.stack([expert_down(i) for i in range(L)]),
            },
        },
        "input_norm": {"scale": _stack(
            sd, "model.layers.{}.input_layernorm.weight", L, _asnp)},
        "post_norm": {"scale": _stack(
            sd, "model.layers.{}.post_attention_layernorm.weight", L,
            _asnp)},
    }
    # MixtralForCausalLM has no tied-head path (HF Mixtral never ties);
    # always materialise lm_head (from embed_tokens when the HF checkpoint
    # omits it)
    lm_head = (sd["lm_head.weight"] if "lm_head.weight" in sd
               else sd["model.embed_tokens.weight"])
    return {"params": {
        "model": {
            "embed": {"embedding": sd["model.embed_tokens.weight"]},
            "layers": {"layer": layers},
            "norm": {"scale": sd["model.norm.weight"]},
        },
        "lm_head": {"kernel": _t(lm_head)},
    }}


def convert_hf_neox_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF GPT-NeoX state dict → our param tree (``GPTNeoXForCausalLM``).

    The HF fused ``query_key_value`` is laid out head-major
    ``[heads, 3, head_dim]`` on the output dim — the split/fuse the
    reference's converter handles with its qkv helpers
    (``checkpoint_converter.py:513``)."""
    sd = {k: np.asarray(v) for k, v in state_dict.items()}
    L, n, hd = cfg.num_layers, cfg.num_heads, cfg.head_dim
    h = cfg.hidden_size

    def qkv_w(i, j):
        w = sd[f"gpt_neox.layers.{i}.attention.query_key_value.weight"]
        return _t(w.reshape(n, 3, hd, h)[:, j].reshape(n * hd, h))

    def qkv_b(i, j):
        b = sd[f"gpt_neox.layers.{i}.attention.query_key_value.bias"]
        return b.reshape(n, 3, hd)[:, j].reshape(n * hd)

    layers = {
        "attn": {
            "qkv": {
                "q_kernel": np.stack([qkv_w(i, 0) for i in range(L)]),
                "k_kernel": np.stack([qkv_w(i, 1) for i in range(L)]),
                "v_kernel": np.stack([qkv_w(i, 2) for i in range(L)]),
                "q_bias": np.stack([qkv_b(i, 0) for i in range(L)]),
                "k_bias": np.stack([qkv_b(i, 1) for i in range(L)]),
                "v_bias": np.stack([qkv_b(i, 2) for i in range(L)]),
            },
            "o_proj": {
                "kernel": _stack(
                    sd, "gpt_neox.layers.{}.attention.dense.weight", L),
                "bias": _stack(
                    sd, "gpt_neox.layers.{}.attention.dense.bias", L,
                    _asnp),
            },
        },
        "mlp": {
            "up": {
                "kernel": _stack(
                    sd, "gpt_neox.layers.{}.mlp.dense_h_to_4h.weight", L),
                "bias": _stack(
                    sd, "gpt_neox.layers.{}.mlp.dense_h_to_4h.bias", L,
                    _asnp),
            },
            "down": {
                "kernel": _stack(
                    sd, "gpt_neox.layers.{}.mlp.dense_4h_to_h.weight", L),
                "bias": _stack(
                    sd, "gpt_neox.layers.{}.mlp.dense_4h_to_h.bias", L,
                    _asnp),
            },
        },
        "ln1": {
            "scale": _stack(
                sd, "gpt_neox.layers.{}.input_layernorm.weight", L, _asnp),
            "bias": _stack(
                sd, "gpt_neox.layers.{}.input_layernorm.bias", L, _asnp),
        },
        "ln2": {
            "scale": _stack(
                sd, "gpt_neox.layers.{}.post_attention_layernorm.weight",
                L, _asnp),
            "bias": _stack(
                sd, "gpt_neox.layers.{}.post_attention_layernorm.bias", L,
                _asnp),
        },
    }
    return {"params": {
        "embed": {"embedding": sd["gpt_neox.embed_in.weight"]},
        "layers": {"layer": layers},
        "final_norm": {"scale": sd["gpt_neox.final_layer_norm.weight"],
                       "bias": sd["gpt_neox.final_layer_norm.bias"]},
        "lm_head": {"kernel": _t(sd["embed_out.weight"])},
    }}


def convert_hf_bert_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF BertForMaskedLM state dict → our param tree
    (``BertForPreTraining`` with ``mlm_transform=True``)."""
    sd = {k: np.asarray(v) for k, v in state_dict.items()}
    L = cfg.num_layers
    pre = "bert.encoder.layer.{}."

    def attn(part, what):
        return _stack(sd, pre + f"attention.self.{part}.{what}", L,
                      _t if what == "weight" else _asnp)

    layers = {
        "qkv": {
            "q_kernel": attn("query", "weight"),
            "k_kernel": attn("key", "weight"),
            "v_kernel": attn("value", "weight"),
            "q_bias": attn("query", "bias"),
            "k_bias": attn("key", "bias"),
            "v_bias": attn("value", "bias"),
        },
        "o_proj": {
            "kernel": _stack(sd, pre + "attention.output.dense.weight", L),
            "bias": _stack(sd, pre + "attention.output.dense.bias", L,
                           _asnp),
        },
        "ln_attn": {
            "scale": _stack(sd, pre + "attention.output.LayerNorm.weight",
                            L, _asnp),
            "bias": _stack(sd, pre + "attention.output.LayerNorm.bias", L,
                           _asnp),
        },
        "up": {
            "kernel": _stack(sd, pre + "intermediate.dense.weight", L),
            "bias": _stack(sd, pre + "intermediate.dense.bias", L, _asnp),
        },
        "down": {
            "kernel": _stack(sd, pre + "output.dense.weight", L),
            "bias": _stack(sd, pre + "output.dense.bias", L, _asnp),
        },
        "ln_mlp": {
            "scale": _stack(sd, pre + "output.LayerNorm.weight", L, _asnp),
            "bias": _stack(sd, pre + "output.LayerNorm.bias", L, _asnp),
        },
    }
    return {"params": {
        "embed": {
            "embedding": sd["bert.embeddings.word_embeddings.weight"]},
        "position_embedding":
            sd["bert.embeddings.position_embeddings.weight"],
        "type_embedding":
            sd["bert.embeddings.token_type_embeddings.weight"],
        "embed_norm": {"scale": sd["bert.embeddings.LayerNorm.weight"],
                       "bias": sd["bert.embeddings.LayerNorm.bias"]},
        "layers": {"layer": layers},
        "mlm_transform": {
            "kernel": _t(sd["cls.predictions.transform.dense.weight"]),
            "bias": sd["cls.predictions.transform.dense.bias"],
        },
        "mlm_norm": {
            "scale": sd["cls.predictions.transform.LayerNorm.weight"],
            "bias": sd["cls.predictions.transform.LayerNorm.bias"],
        },
        "mlm_bias": sd["cls.predictions.bias"],
    }}


def convert_hf_vit_to_nxd(state_dict: Dict[str, Any], cfg) -> Dict:
    """HF ``ViTForImageClassification`` state dict → our param tree
    (``models.vit.ViTForImageClassification``). The stride-``p`` Conv2d
    patch projection flattens to the dense kernel ``[C*p*p, hidden]`` in
    (c, i, j) element order — see ``models.vit.patchify``."""
    sd = {k: np.asarray(v) for k, v in state_dict.items()}
    L = cfg.num_layers
    pre = "vit.encoder.layer.{}."

    def attn(part, what):
        return _stack(sd, pre + f"attention.attention.{part}.{what}", L,
                      _t if what == "weight" else _asnp)

    def ln(which, what):
        return _stack(sd, pre + f"layernorm_{which}.{what}", L, _asnp)

    layers = {
        "ln_before": {"scale": ln("before", "weight"),
                      "bias": ln("before", "bias")},
        "qkv": {
            "q_kernel": attn("query", "weight"),
            "k_kernel": attn("key", "weight"),
            "v_kernel": attn("value", "weight"),
            "q_bias": attn("query", "bias"),
            "k_bias": attn("key", "bias"),
            "v_bias": attn("value", "bias"),
        },
        "o_proj": {
            "kernel": _stack(sd, pre + "attention.output.dense.weight", L),
            "bias": _stack(sd, pre + "attention.output.dense.bias", L,
                           _asnp),
        },
        "ln_after": {"scale": ln("after", "weight"),
                     "bias": ln("after", "bias")},
        "up": {
            "kernel": _stack(sd, pre + "intermediate.dense.weight", L),
            "bias": _stack(sd, pre + "intermediate.dense.bias", L, _asnp),
        },
        "down": {
            "kernel": _stack(sd, pre + "output.dense.weight", L),
            "bias": _stack(sd, pre + "output.dense.bias", L, _asnp),
        },
    }
    proj = sd["vit.embeddings.patch_embeddings.projection.weight"]
    return {"params": {
        "patch_proj": {
            "kernel": proj.reshape(proj.shape[0], -1).T,
            "bias": sd["vit.embeddings.patch_embeddings.projection.bias"],
        },
        "cls_token": sd["vit.embeddings.cls_token"],
        "position_embedding": sd["vit.embeddings.position_embeddings"][0],
        "layers": {"layer": layers},
        "final_norm": {"scale": sd["vit.layernorm.weight"],
                       "bias": sd["vit.layernorm.bias"]},
        "classifier": {"kernel": _t(sd["classifier.weight"]),
                       "bias": sd["classifier.bias"]},
    }}


def convert_nxd_to_hf_mixtral(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_mixtral_to_nxd` (per-expert w1/w3/w2
    unstacked from the fused ``gate_up``/``down`` banks)."""
    p = params["params"]
    layers = p["model"]["layers"]["layer"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            p["model"]["embed"]["embedding"]),
        "model.norm.weight": np.asarray(p["model"]["norm"]["scale"]),
        "lm_head.weight": _t(p["lm_head"]["kernel"]),
    }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        qkv = layers["attn"]["qkv"]
        out[pre + "self_attn.q_proj.weight"] = _t(qkv["q_kernel"][i])
        out[pre + "self_attn.k_proj.weight"] = _t(qkv["k_kernel"][i])
        out[pre + "self_attn.v_proj.weight"] = _t(qkv["v_kernel"][i])
        out[pre + "self_attn.o_proj.weight"] = _t(
            layers["attn"]["o_proj"]["kernel"][i])
        out[pre + "block_sparse_moe.gate.weight"] = _t(
            layers["moe"]["router"]["kernel"][i])
        gu = np.asarray(layers["moe"]["experts"]["gate_up"][i])  # [E,H,2,I]
        dn = np.asarray(layers["moe"]["experts"]["down"][i])     # [E,I,H]
        for e in range(cfg.num_experts):
            epre = pre + f"block_sparse_moe.experts.{e}."
            out[epre + "w1.weight"] = _t(gu[e, :, 0])
            out[epre + "w3.weight"] = _t(gu[e, :, 1])
            out[epre + "w2.weight"] = _t(dn[e])
        out[pre + "input_layernorm.weight"] = np.asarray(
            layers["input_norm"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = np.asarray(
            layers["post_norm"]["scale"][i])
    return out


def convert_nxd_to_hf_neox(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_neox_to_nxd` (re-fuses q/k/v into the
    HF head-major ``query_key_value`` layout ``[heads, 3, head_dim]``)."""
    p = params["params"]
    layers = p["layers"]["layer"]
    n, hd, h = cfg.num_heads, cfg.head_dim, cfg.hidden_size
    out: Dict[str, np.ndarray] = {
        "gpt_neox.embed_in.weight": np.asarray(p["embed"]["embedding"]),
        "gpt_neox.final_layer_norm.weight": np.asarray(
            p["final_norm"]["scale"]),
        "gpt_neox.final_layer_norm.bias": np.asarray(
            p["final_norm"]["bias"]),
        "embed_out.weight": _t(p["lm_head"]["kernel"]),
    }
    qkv = layers["attn"]["qkv"]
    for i in range(cfg.num_layers):
        pre = f"gpt_neox.layers.{i}."
        w = np.stack([_t(qkv[f"{j}_kernel"][i]).reshape(n, hd, h)
                      for j in ("q", "k", "v")], axis=1)  # [n, 3, hd, h]
        out[pre + "attention.query_key_value.weight"] = w.reshape(
            3 * n * hd, h)
        b = np.stack([np.asarray(qkv[f"{j}_bias"][i]).reshape(n, hd)
                      for j in ("q", "k", "v")], axis=1)
        out[pre + "attention.query_key_value.bias"] = b.reshape(3 * n * hd)
        out[pre + "attention.dense.weight"] = _t(
            layers["attn"]["o_proj"]["kernel"][i])
        out[pre + "attention.dense.bias"] = np.asarray(
            layers["attn"]["o_proj"]["bias"][i])
        out[pre + "mlp.dense_h_to_4h.weight"] = _t(
            layers["mlp"]["up"]["kernel"][i])
        out[pre + "mlp.dense_h_to_4h.bias"] = np.asarray(
            layers["mlp"]["up"]["bias"][i])
        out[pre + "mlp.dense_4h_to_h.weight"] = _t(
            layers["mlp"]["down"]["kernel"][i])
        out[pre + "mlp.dense_4h_to_h.bias"] = np.asarray(
            layers["mlp"]["down"]["bias"][i])
        for ours, hf in (("ln1", "input_layernorm"),
                         ("ln2", "post_attention_layernorm")):
            out[pre + hf + ".weight"] = np.asarray(layers[ours]["scale"][i])
            out[pre + hf + ".bias"] = np.asarray(layers[ours]["bias"][i])
    return out


def convert_nxd_to_hf_bert(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_bert_to_nxd`; emits the tied
    ``cls.predictions.decoder.*`` aliases HF checkpoints carry."""
    p = params["params"]
    layers = p["layers"]["layer"]
    embed = np.asarray(p["embed"]["embedding"])
    mlm_bias = np.asarray(p["mlm_bias"])
    out: Dict[str, np.ndarray] = {
        "bert.embeddings.word_embeddings.weight": embed,
        "bert.embeddings.position_embeddings.weight": np.asarray(
            p["position_embedding"]),
        "bert.embeddings.token_type_embeddings.weight": np.asarray(
            p["type_embedding"]),
        "bert.embeddings.LayerNorm.weight": np.asarray(
            p["embed_norm"]["scale"]),
        "bert.embeddings.LayerNorm.bias": np.asarray(
            p["embed_norm"]["bias"]),
        "cls.predictions.transform.dense.weight": _t(
            p["mlm_transform"]["kernel"]),
        "cls.predictions.transform.dense.bias": np.asarray(
            p["mlm_transform"]["bias"]),
        "cls.predictions.transform.LayerNorm.weight": np.asarray(
            p["mlm_norm"]["scale"]),
        "cls.predictions.transform.LayerNorm.bias": np.asarray(
            p["mlm_norm"]["bias"]),
        "cls.predictions.bias": mlm_bias,
        "cls.predictions.decoder.weight": embed,
        "cls.predictions.decoder.bias": mlm_bias,
    }
    for i in range(cfg.num_layers):
        pre = f"bert.encoder.layer.{i}."
        qkv = layers["qkv"]
        for j, part in (("q", "query"), ("k", "key"), ("v", "value")):
            out[pre + f"attention.self.{part}.weight"] = _t(
                qkv[f"{j}_kernel"][i])
            out[pre + f"attention.self.{part}.bias"] = np.asarray(
                qkv[f"{j}_bias"][i])
        out[pre + "attention.output.dense.weight"] = _t(
            layers["o_proj"]["kernel"][i])
        out[pre + "attention.output.dense.bias"] = np.asarray(
            layers["o_proj"]["bias"][i])
        out[pre + "attention.output.LayerNorm.weight"] = np.asarray(
            layers["ln_attn"]["scale"][i])
        out[pre + "attention.output.LayerNorm.bias"] = np.asarray(
            layers["ln_attn"]["bias"][i])
        out[pre + "intermediate.dense.weight"] = _t(
            layers["up"]["kernel"][i])
        out[pre + "intermediate.dense.bias"] = np.asarray(
            layers["up"]["bias"][i])
        out[pre + "output.dense.weight"] = _t(layers["down"]["kernel"][i])
        out[pre + "output.dense.bias"] = np.asarray(
            layers["down"]["bias"][i])
        out[pre + "output.LayerNorm.weight"] = np.asarray(
            layers["ln_mlp"]["scale"][i])
        out[pre + "output.LayerNorm.bias"] = np.asarray(
            layers["ln_mlp"]["bias"][i])
    return out


def convert_nxd_to_hf_vit(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_hf_vit_to_nxd` (dense patch kernel folds
    back into the HF Conv2d layout ``[hidden, C, p, p]``)."""
    p = params["params"]
    layers = p["layers"]["layer"]
    c, pp = cfg.num_channels, cfg.patch_size
    out: Dict[str, np.ndarray] = {
        "vit.embeddings.cls_token": np.asarray(p["cls_token"]),
        "vit.embeddings.position_embeddings": np.asarray(
            p["position_embedding"])[None],
        "vit.embeddings.patch_embeddings.projection.weight": np.asarray(
            p["patch_proj"]["kernel"]).T.reshape(
                cfg.hidden_size, c, pp, pp),
        "vit.embeddings.patch_embeddings.projection.bias": np.asarray(
            p["patch_proj"]["bias"]),
        "vit.layernorm.weight": np.asarray(p["final_norm"]["scale"]),
        "vit.layernorm.bias": np.asarray(p["final_norm"]["bias"]),
        "classifier.weight": _t(p["classifier"]["kernel"]),
        "classifier.bias": np.asarray(p["classifier"]["bias"]),
    }
    for i in range(cfg.num_layers):
        pre = f"vit.encoder.layer.{i}."
        qkv = layers["qkv"]
        for j, part in (("q", "query"), ("k", "key"), ("v", "value")):
            out[pre + f"attention.attention.{part}.weight"] = _t(
                qkv[f"{j}_kernel"][i])
            out[pre + f"attention.attention.{part}.bias"] = np.asarray(
                qkv[f"{j}_bias"][i])
        out[pre + "attention.output.dense.weight"] = _t(
            layers["o_proj"]["kernel"][i])
        out[pre + "attention.output.dense.bias"] = np.asarray(
            layers["o_proj"]["bias"][i])
        out[pre + "intermediate.dense.weight"] = _t(
            layers["up"]["kernel"][i])
        out[pre + "intermediate.dense.bias"] = np.asarray(
            layers["up"]["bias"][i])
        out[pre + "output.dense.weight"] = _t(layers["down"]["kernel"][i])
        out[pre + "output.dense.bias"] = np.asarray(
            layers["down"]["bias"][i])
        for ours, hf in (("ln_before", "layernorm_before"),
                         ("ln_after", "layernorm_after")):
            out[pre + hf + ".weight"] = np.asarray(layers[ours]["scale"][i])
            out[pre + hf + ".bias"] = np.asarray(layers[ours]["bias"][i])
    return out


_NXD2HF = {"llama": convert_nxd_to_hf_llama,
           "mixtral": convert_nxd_to_hf_mixtral,
           "neox": convert_nxd_to_hf_neox,
           "bert": convert_nxd_to_hf_bert,
           "vit": convert_nxd_to_hf_vit}


def _cli_config(family: str, **overrides):
    """Family config with CLI shape overrides (None values dropped — the
    converters read num_experts/num_heads/hidden_size off the config, so
    non-default checkpoints must be able to set them). Overrides a family
    has no field for raise instead of being silently ignored."""
    import dataclasses

    if family == "llama":
        from ..models.llama import LlamaConfig as cls

        extra = {}
    elif family == "mixtral":
        from ..models.mixtral import MixtralConfig as cls

        extra = {}
    elif family == "neox":
        from ..models.gpt_neox import GPTNeoXConfig as cls

        extra = {}
    elif family == "bert":
        from ..models.bert import BertConfig as cls

        extra = {"mlm_transform": True}
    elif family == "vit":
        from ..models.vit import ViTConfig as cls

        extra = {}
    else:
        raise ValueError(f"unknown family {family!r}")  # sync: _HF2NXD
    kw = {k: v for k, v in overrides.items() if v is not None}
    fields = {f.name for f in dataclasses.fields(cls)}
    unknown = sorted(set(kw) - fields)
    if unknown:
        raise SystemExit(
            f"--family {family} has no config field(s) {unknown}")
    return cls(**extra, **kw)


_HF2NXD = {"llama": convert_hf_llama_to_nxd,
           "mixtral": convert_hf_mixtral_to_nxd,
           "neox": convert_hf_neox_to_nxd,
           "bert": convert_hf_bert_to_nxd,
           "vit": convert_hf_vit_to_nxd}


def main(argv=None) -> None:
    """CLI (reference: the ``CheckpointConverterBase`` argparse driver,
    one subclass per model family)."""
    import argparse
    import pickle

    ap = argparse.ArgumentParser(
        description="Convert HF checkpoints to/from the framework "
                    "param-tree format")
    ap.add_argument("--input", required=True,
                    help=".safetensors / torch .bin / pickled tree")
    ap.add_argument("--output", required=True)
    ap.add_argument("--family", choices=sorted(_HF2NXD), default="llama")
    ap.add_argument("--direction", choices=["hf2nxd", "nxd2hf"],
                    default="hf2nxd")
    ap.add_argument("--num-layers", type=int, required=True)
    # shape fields the converters read off the config; defaults are each
    # family's flagship shape — set them for any other checkpoint size
    ap.add_argument("--hidden-size", type=int)
    ap.add_argument("--intermediate-size", type=int)
    ap.add_argument("--num-heads", type=int)
    ap.add_argument("--num-kv-heads", type=int)
    ap.add_argument("--num-experts", type=int)
    ap.add_argument("--vocab-size", type=int)
    ap.add_argument("--image-size", type=int)
    ap.add_argument("--patch-size", type=int)
    ap.add_argument("--num-channels", type=int)
    ap.add_argument("--num-labels", type=int)
    args = ap.parse_args(argv)

    cfg = _cli_config(args.family, num_layers=args.num_layers,
                      hidden_size=args.hidden_size,
                      intermediate_size=args.intermediate_size,
                      num_heads=args.num_heads,
                      num_kv_heads=args.num_kv_heads,
                      num_experts=args.num_experts,
                      vocab_size=args.vocab_size,
                      image_size=args.image_size,
                      patch_size=args.patch_size,
                      num_channels=args.num_channels,
                      num_labels=args.num_labels)

    if args.input.endswith(".safetensors"):
        from safetensors.numpy import load_file

        sd = load_file(args.input)
    else:
        with open(args.input, "rb") as f:
            sd = pickle.load(f)

    out = (_HF2NXD if args.direction == "hf2nxd"
           else _NXD2HF)[args.family](sd, cfg)
    with open(args.output, "wb") as f:
        pickle.dump(out, f)
    print(f"wrote {args.output}")


if __name__ == "__main__":  # pragma: no cover
    main()
