"""Checkpoint reshard/merge CLI.

Analogue of the reference's ``optimizer/convert_zero_checkpoints.py``
(``nxd_convert_zero_checkpoints``: merge/split DP-sharded optimizer states
sharded↔full↔resharded). Our checkpoint engine stores arrays
sharding-agnostically (Orbax/TensorStore), so "merging to full" and
"resharding" are both just a load (optionally onto a different mesh) plus a
save — this CLI packages that for operators.

    python -m neuronx_distributed_tpu.scripts.reshard_checkpoint \
        --input ckpts/run1 --tag -1 --output merged/ [--sync]
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="Merge or reshard a framework checkpoint")
    ap.add_argument("--input", required=True, help="checkpoint root dir")
    ap.add_argument("--tag", default="-1",
                    help="tag to load (-1 = newest complete)")
    ap.add_argument("--output", required=True, help="output checkpoint root")
    ap.add_argument("--output-tag", default=None,
                    help="tag to save under (default: same as loaded)")
    args = ap.parse_args(argv)

    from ..trainer import checkpoint as ckpt

    # resolve the tag FIRST so the loaded state and the saved tag can never
    # disagree (a concurrent writer could complete a newer tag in between)
    tag = args.tag
    if tag in (None, "-1"):
        tags = ckpt.list_complete_tags(args.input)
        if not tags:
            raise FileNotFoundError(
                f"no complete checkpoint under {args.input}")
        tag = tags[-1]
    ok, why = ckpt.verify_checkpoint(args.input, tag)
    print(f"verify {args.input}/{tag}: {'ok' if ok else 'FAILED'} ({why})")
    state, user_content = ckpt.load_checkpoint(args.input, tag=tag)
    out_tag = args.output_tag if args.output_tag is not None else tag
    ckpt.save_checkpoint(args.output, out_tag, state,
                         user_content=user_content, async_save=False)
    print(f"resharded {args.input}/{tag} -> {args.output}/{out_tag}")


if __name__ == "__main__":  # pragma: no cover
    main()
