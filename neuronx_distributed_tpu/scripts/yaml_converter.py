"""YAML → framework config conversion.

Analogue of the reference's ``scripts/yaml_converter.py:19`` (training
launchers driven by YAML config files). A YAML document maps one-to-one
onto :func:`..config.neuronx_distributed_config`:

.. code-block:: yaml

    tensor_parallel_size: 8
    pipeline_parallel_size: 2
    sequence_parallel: true
    optimizer:
      zero_one_enabled: true
      max_grad_norm: 1.0
    pipeline:
      num_microbatches: 8
      schedule: 1f1b
    activation_checkpoint:
      mode: full
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict

from .. import config as _cfg

_SECTIONS = {
    "optimizer": ("optimizer_config", _cfg.OptimizerConfig),
    "mixed_precision": ("mixed_precision_config",
                        _cfg.MixedPrecisionConfig),
    "activation_checkpoint": ("activation_checkpoint_config",
                              _cfg.ActivationCheckpointConfig),
    "pipeline": ("pipeline_config", _cfg.PipelineConfig),
    "checkpoint": ("checkpoint_config", _cfg.CheckpointConfig),
}


def dict_to_config_kwargs(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + convert a parsed YAML dict into
    ``neuronx_distributed_config`` kwargs (unknown keys raise — the
    reference's converter is strict the same way)."""
    kwargs: Dict[str, Any] = {}
    for key, value in doc.items():
        if key in _SECTIONS:
            name, cls = _SECTIONS[key]
            if not isinstance(value, dict):
                raise ValueError(
                    f"config section {key!r} must be a mapping, got "
                    f"{value!r} (an empty 'key:' line parses as null)")
            fields = {f.name for f in dataclasses.fields(cls)}
            unknown = set(value) - fields
            if unknown:
                raise ValueError(
                    f"unknown {key} option(s) {sorted(unknown)}; "
                    f"valid: {sorted(fields)}")
            kwargs[name] = cls(**value)
        elif key in ("tensor_parallel_size", "pipeline_parallel_size",
                     "context_parallel_size", "expert_parallel_size",
                     "dcn_data_parallel_size", "tp_overlap_comm",
                     "tp_activation_comm_dtype",
                     "tp_activation_sync_fraction",
                     "moe_ep_wire_dtype", "moe_overlap_dispatch",
                     "weight_quant", "sequence_parallel", "seed"):
            kwargs[key] = value
        else:
            raise ValueError(f"unknown config key {key!r}")
    return kwargs


def config_to_dict(cfg) -> Dict[str, Any]:
    """The inverse of :func:`dict_to_config_kwargs`: an
    :class:`..config.NxDConfig` back to a YAML-able dict such that
    ``dict_to_config_kwargs(config_to_dict(cfg))`` rebuilds ``cfg``
    exactly. Sections and scalars that still hold their defaults are
    elided, so emitted YAML stays as terse as hand-written files."""
    kwargs = cfg.to_config_kwargs()
    doc: Dict[str, Any] = {}
    for section, (kwarg, cls) in _SECTIONS.items():
        value = kwargs.pop(kwarg)
        if value != cls():
            doc[section] = dataclasses.asdict(value)
    for key, value in kwargs.items():
        default = None if key in ("dcn_data_parallel_size",
                                  "tp_overlap_comm",
                                  "moe_overlap_dispatch",
                                  "weight_quant") else (
            False if key == "sequence_parallel" else
            0 if key == "seed" else
            "fp32" if key in ("tp_activation_comm_dtype",
                              "moe_ep_wire_dtype") else
            1.0 if key == "tp_activation_sync_fraction" else 1)
        if value != default:
            doc[key] = value
    return doc


def load_yaml_config(path: str, init_mesh: bool = False):
    """Parse a YAML file into an :class:`..config.NxDConfig`."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    return _cfg.neuronx_distributed_config(init_mesh=init_mesh,
                                           **dict_to_config_kwargs(doc))


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="Validate a YAML training config and print the "
                    "resolved framework config")
    ap.add_argument("yaml_path")
    args = ap.parse_args(argv)
    cfg = load_yaml_config(args.yaml_path)
    print(json.dumps(dataclasses.asdict(cfg), indent=2, default=str))


if __name__ == "__main__":
    main()
