"""Typed configuration for the framework.

TPU-native analogue of the reference's ``neuronx_distributed_config`` nested
dict factory (reference: ``trainer/trainer.py:32-144``).  Instead of a loosely
validated dict we use frozen dataclasses with explicit defaults; environment
variable overrides are honoured at construction time where the reference
sprinkled ``os.environ`` reads at use sites (SURVEY §5 "Config / flag system").
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence


def configure_model(cfg: "NxDConfig", model_cfg: Any) -> Any:
    """Propagate framework-level knobs into a model config dataclass.

    The analogue of the reference's ``initialize_parallel_model`` applying
    nxd_config to the wrapped model (sequence_parallel, activation
    checkpointing, precision — ``trainer/trainer.py:147-236``). Any of
    ``sequence_parallel`` / ``remat`` / ``dtype`` / ``tp_size`` present on
    the model config dataclass is overridden from ``cfg``.
    """
    import jax.numpy as jnp

    updates = {}
    fields = {f.name for f in dataclasses.fields(model_cfg)}
    if "sequence_parallel" in fields:
        updates["sequence_parallel"] = cfg.sequence_parallel
    if "remat" in fields:
        updates["remat"] = cfg.activation_checkpoint.mode != "none"
    if "dtype" in fields:
        updates["dtype"] = jnp.dtype(cfg.mixed_precision.compute_dtype)
    if "tp_size" in fields:
        updates["tp_size"] = cfg.parallel.tensor_parallel_size
    if "overlap_comm" in fields:
        updates["overlap_comm"] = cfg.parallel.tp_overlap_comm
    if "activation_comm_dtype" in fields:
        updates["activation_comm_dtype"] = \
            cfg.parallel.tp_activation_comm_dtype
    if "activation_sync_fraction" in fields:
        updates["activation_sync_fraction"] = \
            cfg.parallel.tp_activation_sync_fraction
    if "moe_ep_wire_dtype" in fields:
        updates["moe_ep_wire_dtype"] = cfg.parallel.moe_ep_wire_dtype
    if "moe_overlap_dispatch" in fields:
        updates["moe_overlap_dispatch"] = cfg.parallel.moe_overlap_dispatch
    if "weight_quant" in fields and cfg.parallel.weight_quant is not None:
        updates["weight_quant"] = cfg.parallel.weight_quant
    model_cfg = dataclasses.replace(model_cfg, **updates)
    if "num_experts" in fields:
        # incoherent MoE knobs fail here with actionable errors instead of
        # as shape errors inside a compiled program (reference
        # moe_config_validator.py:13)
        from .modules.moe.config_validator import validate_moe_config

        model_cfg = validate_moe_config(model_cfg, cfg)
    return model_cfg


def mesh_factorization(
    world: int,
    tensor_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    data_parallel_size: Optional[int] = None,
    dcn_data_parallel_size: Optional[int] = None,
) -> dict:
    """Validate a mesh factorization of ``world`` devices and return the
    resolved axis sizes ``{pp, dp, cp, tp, ep, dp_exp, dcn_dp, world}``.

    The single source of truth for the divisibility rules shared by
    ``parallel.mesh.initialize_model_parallel`` (which builds the device
    array from these sizes) and the placement planner's search
    (``plan.search``, which turns each violation into a machine-readable
    prune reason). Raises ``ValueError`` with the same messages the mesh
    initializer always raised.
    """
    tp, pp, cp, ep = (tensor_parallel_size, pipeline_parallel_size,
                      context_parallel_size, expert_parallel_size)
    denom = tp * pp * cp
    if world % denom != 0:
        raise ValueError(
            f"world size {world} not divisible by tp*pp*cp = {denom}")
    dp = world // denom
    if data_parallel_size is not None and data_parallel_size != dp:
        raise ValueError(
            f"explicit data_parallel_size {data_parallel_size} inconsistent "
            f"with world {world} / (tp*pp*cp) = {dp}")
    if (dp * cp) % ep != 0:
        raise ValueError(
            f"dp*cp = {dp * cp} not divisible by expert parallel size {ep}")
    dcn_dp = dcn_data_parallel_size or 1
    if dcn_dp > 1 and dp % dcn_dp != 0:
        raise ValueError(
            f"dp {dp} not divisible by dcn_data_parallel_size {dcn_dp}")
    return dict(pp=pp, dp=dp, cp=cp, tp=tp, ep=ep, dp_exp=dp * cp // ep,
                dcn_dp=dcn_dp, world=world)


@dataclass(frozen=True)
class ParallelConfig:
    """Parallel dimensions of the device mesh.

    Mirrors the arguments of the reference's ``initialize_model_parallel``
    (``parallel_layers/parallel_state.py:391``): tensor/pipeline/context/expert
    parallel degrees; data parallel is inferred from the device count unless
    given explicitly.
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1
    # Inferred from jax.device_count() when None.
    data_parallel_size: Optional[int] = None
    # Virtual pipeline (interleaved 1F1B) model chunks per pp rank.
    virtual_pipeline_size: int = 1
    # Multi-slice: this many dp groups placed across slices (DCN); None/1
    # keeps everything within one ICI domain.
    dcn_data_parallel_size: Optional[int] = None
    # Decomposed collective-matmuls in the TP layers (docs/tp_overlap.md):
    # None = auto (engage when the tp axis size >= 4 and shapes tile),
    # True = engage wherever shapes allow, False = always monolithic.
    tp_overlap_comm: Optional[bool] = None
    # Activation-collective compression (docs/comm_compression.md): wire
    # dtype for TP activation collectives — "fp32" (off), "int8" or "fp8"
    # (blockwise quantized payloads + per-block fp32 scales). Composes with
    # tp_overlap_comm: quantizes the decomposed rings when they engage and
    # the monolithic collectives otherwise.
    tp_activation_comm_dtype: str = "fp32"
    # Reduced-sync TP: fraction of decoder layers whose row-parallel exit
    # all-reduces run; the rest are elided and compensated by a periodic
    # residual resync (PAPERS.md "Partially Synchronized Activations").
    # < 1.0 requires scan_layers=False models without sequence_parallel.
    tp_activation_sync_fraction: float = 1.0
    # MoE EP-dispatch wire (docs/moe.md): dtype for the expert-parallel
    # token gather/combine payloads — "fp32" (off), "int8" or "fp8"
    # (blockwise quantized + per-block fp32 scales). Blockwise dispatch
    # only (validate_moe_config enforces).
    moe_ep_wire_dtype: str = "fp32"
    # Decomposed (ppermute-ring) EP dispatch overlapping per-chunk expert
    # compute with later hops: None = auto (engage at ep >= 4), True =
    # engage whenever ep > 1, False = monolithic collectives.
    moe_overlap_dispatch: Optional[bool] = None
    # Serving weight-quantization tier (docs/quantization.md): None (float)
    # | "int8" | "fp8" (per-out-channel w8a16) | "mxfp4" | "mxfp8" (packed
    # OCP microscaling). Propagated onto model configs with a
    # ``weight_quant`` field by configure_model.
    weight_quant: Optional[str] = None

    def __post_init__(self) -> None:
        for f in ("tensor_parallel_size", "pipeline_parallel_size",
                  "context_parallel_size", "expert_parallel_size",
                  "virtual_pipeline_size"):
            v = getattr(self, f)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{f} must be a positive int, got {v!r}")
        d = self.dcn_data_parallel_size
        if d is not None and (not isinstance(d, int) or d < 1):
            raise ValueError(
                f"dcn_data_parallel_size must be a positive int or None, "
                f"got {d!r}")
        if self.tp_overlap_comm not in (None, True, False):
            raise ValueError(
                "tp_overlap_comm must be None (auto), True, or False, got "
                f"{self.tp_overlap_comm!r}")
        from .parallel.wire_codec import _WIRE_DTYPES

        if self.tp_activation_comm_dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"tp_activation_comm_dtype must be one of {_WIRE_DTYPES}, "
                f"got {self.tp_activation_comm_dtype!r}")
        if self.moe_ep_wire_dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"moe_ep_wire_dtype must be one of {_WIRE_DTYPES}, "
                f"got {self.moe_ep_wire_dtype!r}")
        if self.moe_overlap_dispatch not in (None, True, False):
            raise ValueError(
                "moe_overlap_dispatch must be None (auto), True, or False, "
                f"got {self.moe_overlap_dispatch!r}")
        if (self.moe_overlap_dispatch is True
                and self.expert_parallel_size <= 1):
            raise ValueError(
                "moe_overlap_dispatch=True requires expert_parallel_size > "
                f"1 (got ep={self.expert_parallel_size}): with a single EP "
                "rank there is no dispatch to decompose — use None (auto) "
                "or raise expert_parallel_size")
        f = self.tp_activation_sync_fraction
        if not (isinstance(f, (int, float)) and 0.0 < f <= 1.0):
            raise ValueError(
                "tp_activation_sync_fraction must be in (0, 1], got "
                f"{f!r}")
        wq_formats = ("int8", "fp8", "mxfp4", "mxfp8")
        if self.weight_quant is not None and \
                self.weight_quant not in wq_formats:
            raise ValueError(
                f"weight_quant must be one of {wq_formats} or None, got "
                f"{self.weight_quant!r}")

    @property
    def model_parallel_size(self) -> int:
        return (self.tensor_parallel_size * self.pipeline_parallel_size
                * self.context_parallel_size)


@dataclass(frozen=True)
class OptimizerConfig:
    """Reference: ``optimizer_config`` in ``trainer/trainer.py:52-60``.

    The ``grad_comm_*`` fields drive the communication-compression layer
    (``parallel/comm_compressed.py``, docs/comm_compression.md): wire
    dtype for gradient collectives, ZeRO++-style hierarchical staging
    over the declared fast/slow mesh-axis split, quantization block size,
    and whether the quantization residue is carried across steps
    (error feedback, checkpointed in ``TrainState.comm_error``).
    """

    zero_one_enabled: bool = False
    grad_clipping: bool = True
    max_grad_norm: float = 1.0
    grad_comm_dtype: str = "fp32"          # fp32 | int8 | fp8
    grad_comm_hierarchical: bool = False
    grad_comm_block_size: int = 256
    grad_comm_error_feedback: bool = True

    def __post_init__(self) -> None:
        if self.grad_comm_dtype not in ("fp32", "int8", "fp8"):
            raise ValueError(
                "grad_comm_dtype must be one of ('fp32', 'int8', 'fp8'), "
                f"got {self.grad_comm_dtype!r}")
        if (not isinstance(self.grad_comm_block_size, int)
                or self.grad_comm_block_size < 1):
            raise ValueError(
                "grad_comm_block_size must be a positive int, got "
                f"{self.grad_comm_block_size!r}")
        if self.grad_clipping and self.max_grad_norm <= 0:
            raise ValueError(
                "max_grad_norm must be positive when grad_clipping is "
                f"enabled, got {self.max_grad_norm!r}")


@dataclass(frozen=True)
class MixedPrecisionConfig:
    """Reference: ``mixed_precision_config`` in ``trainer/trainer.py:66-76``."""

    use_master_weights: bool = True
    use_fp32_grad_acc: bool = True
    use_master_weights_in_ckpt: bool = False
    # Compute dtype for matmuls/activations; params kept in fp32 masters.
    compute_dtype: str = "bfloat16"


@dataclass(frozen=True)
class ActivationCheckpointConfig:
    """Remat policy selection (reference: ``activation_checkpoint_config``
    argument of ``initialize_parallel_model``, ``trainer/trainer.py:147``)."""

    # one of: "none", "full", "attention", "custom"
    mode: str = "none"
    # jax.checkpoint policy name from jax.checkpoint_policies when mode=custom
    policy: Optional[str] = None


@dataclass(frozen=True)
class PipelineConfig:
    """Reference: ``pipeline_config`` dict (``trainer/trainer.py:44-51``) and
    ``NxDPPModel`` kwargs (``pipeline/model.py:74``)."""

    num_microbatches: int = 1
    # one of: "gpipe", "1f1b", "interleaved", "inference"
    schedule: str = "1f1b"
    # Names of layers (pytree path prefixes) at which to cut stages; empty =
    # even auto-partition (reference: ``partition.py:280``).
    manual_cut_points: Sequence[str] = ()


@dataclass(frozen=True)
class CheckpointConfig:
    """Reference: ``trainer/checkpoint.py`` save/load options."""

    output_dir: str = "checkpoints"
    save_interval: int = 0  # 0 = disabled
    keep_last: int = -1  # -1 = keep all (reference: num_kept arg)
    async_save: bool = True
    use_master_weights_in_ckpt: bool = False


@dataclass(frozen=True)
class NxDConfig:
    """Top-level framework config.

    The analogue of the dict returned by the reference's
    ``neuronx_distributed_config`` (``trainer/trainer.py:32``).
    """

    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    mixed_precision: MixedPrecisionConfig = field(default_factory=MixedPrecisionConfig)
    activation_checkpoint: ActivationCheckpointConfig = field(
        default_factory=ActivationCheckpointConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    sequence_parallel: bool = False
    seed: int = 0

    def replace(self, **kw: Any) -> "NxDConfig":
        return dataclasses.replace(self, **kw)

    def to_config_kwargs(self) -> dict:
        """The ``neuronx_distributed_config(...)`` kwargs that rebuild this
        config: ``neuronx_distributed_config(**cfg.to_config_kwargs(),
        init_mesh=False) == cfg``. The inverse of the factory — the YAML
        converter's config→YAML direction and the planner's emitted-config
        round-trip check both go through it."""
        return dict(
            tensor_parallel_size=self.parallel.tensor_parallel_size,
            pipeline_parallel_size=self.parallel.pipeline_parallel_size,
            context_parallel_size=self.parallel.context_parallel_size,
            expert_parallel_size=self.parallel.expert_parallel_size,
            dcn_data_parallel_size=self.parallel.dcn_data_parallel_size,
            tp_overlap_comm=self.parallel.tp_overlap_comm,
            tp_activation_comm_dtype=self.parallel.tp_activation_comm_dtype,
            tp_activation_sync_fraction=(
                self.parallel.tp_activation_sync_fraction),
            moe_ep_wire_dtype=self.parallel.moe_ep_wire_dtype,
            moe_overlap_dispatch=self.parallel.moe_overlap_dispatch,
            weight_quant=self.parallel.weight_quant,
            optimizer_config=self.optimizer,
            mixed_precision_config=self.mixed_precision,
            activation_checkpoint_config=self.activation_checkpoint,
            pipeline_config=self.pipeline,
            checkpoint_config=self.checkpoint,
            sequence_parallel=self.sequence_parallel,
            seed=self.seed,
        )


def neuronx_distributed_config(
    tensor_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    pipeline_config: Optional[PipelineConfig] = None,
    optimizer_config: Optional[OptimizerConfig] = None,
    activation_checkpoint_config: Optional[ActivationCheckpointConfig] = None,
    mixed_precision_config: Optional[MixedPrecisionConfig] = None,
    checkpoint_config: Optional[CheckpointConfig] = None,
    sequence_parallel: bool = False,
    seed: int = 0,
    init_mesh: bool = True,
    devices: Optional[Sequence[Any]] = None,
    dcn_data_parallel_size: Optional[int] = None,
    tp_overlap_comm: Optional[bool] = None,
    tp_activation_comm_dtype: str = "fp32",
    tp_activation_sync_fraction: float = 1.0,
    moe_ep_wire_dtype: str = "fp32",
    moe_overlap_dispatch: Optional[bool] = None,
    weight_quant: Optional[str] = None,
) -> NxDConfig:
    """Build an :class:`NxDConfig` and (by default) initialise the global mesh.

    Mirrors the reference's ``neuronx_distributed_config``
    (``trainer/trainer.py:32``) which both validates config and calls
    ``initialize_model_parallel``.
    """
    cfg = NxDConfig(
        parallel=ParallelConfig(
            tensor_parallel_size=tensor_parallel_size,
            pipeline_parallel_size=pipeline_parallel_size,
            context_parallel_size=context_parallel_size,
            expert_parallel_size=expert_parallel_size,
            dcn_data_parallel_size=dcn_data_parallel_size,
            tp_overlap_comm=tp_overlap_comm,
            tp_activation_comm_dtype=tp_activation_comm_dtype,
            tp_activation_sync_fraction=tp_activation_sync_fraction,
            moe_ep_wire_dtype=moe_ep_wire_dtype,
            moe_overlap_dispatch=moe_overlap_dispatch,
            weight_quant=weight_quant,
        ),
        optimizer=optimizer_config or OptimizerConfig(),
        mixed_precision=mixed_precision_config or MixedPrecisionConfig(),
        activation_checkpoint=(activation_checkpoint_config
                               or ActivationCheckpointConfig()),
        pipeline=pipeline_config or PipelineConfig(),
        checkpoint=checkpoint_config or CheckpointConfig(),
        sequence_parallel=sequence_parallel,
        seed=seed,
    )
    if init_mesh:
        from .parallel import mesh as _mesh

        _mesh.initialize_model_parallel(
            tensor_model_parallel_size=tensor_parallel_size,
            pipeline_model_parallel_size=pipeline_parallel_size,
            context_parallel_size=context_parallel_size,
            expert_model_parallel_size=expert_parallel_size,
            devices=devices,
            dcn_data_parallel_size=dcn_data_parallel_size,
        )
    return cfg
